"""Autodiff API (ref ``python/paddle/fluid/backward.py``: ``append_backward
:394``, ``calc_gradient:613``).

The reference does source-to-source differentiation: per-op grad OpDescs from
C++ GradOpMakers, duplicate-output summing, no-grad pruning. Capability
parity here is one symbolic ``autodiff`` op that re-traces the recorded
forward ops under ``jax.grad`` at executor-trace time (XLA CSEs the replay
against the forward — see ``core/opimpl/control_ops.py``). Grad variables
follow the reference's ``<name>@GRAD`` convention so downstream code
(clip, regularizer, optimizers, tests) composes identically.
"""

from .core.framework import Variable, grad_var_name

__all__ = ["append_backward", "calc_gradient", "gradients"]


def _collect_params(program, parameter_list=None, no_grad_set=None):
    params = [p for p in program.all_parameters() if p.trainable]
    if parameter_list is not None:
        wanted = {p.name if isinstance(p, Variable) else str(p)
                  for p in parameter_list}
        params = [p for p in params if p.name in wanted]
    if no_grad_set:
        banned = {v.name if isinstance(v, Variable) else str(v)
                  for v in no_grad_set}
        params = [p for p in params if p.name not in banned]
    return params


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Append gradient computation for ``loss`` w.r.t. trainable parameters;
    returns ``[(param, grad_var), ...]`` like the reference.

    ``checkpoints`` (a list of Variables) opts into gradient rematerialization
    — the TPU-native analog of the reference's memory_optimize pass — by
    wrapping the forward replay in ``jax.checkpoint`` regions (coarse v1:
    whole-graph remat when any checkpoint is given)."""
    prog = loss.block.program
    gb = prog.global_block()
    fwd_ops = list(gb.ops)
    params = _collect_params(prog, parameter_list, no_grad_set)
    wrt_names = [p.name for p in params]

    # SelectedRows parity: params marked ``is_sparse_grad`` (embedding with
    # is_sparse=True) get a (rows, values) gradient pair instead of a dense
    # full-table grad — ref ``lookup_table_op.cc`` grad emitting SelectedRows.
    # A table consumed by anything other than sparse lookups (e.g. weight
    # tying into an output projection) falls back to the dense grad.
    def _sparse_ok(p):
        if not getattr(p, "is_sparse_grad", False):
            return False
        uses = [o for o in fwd_ops if p.name in o.input_arg_names]
        return uses and all(
            o.type in ("lookup_table", "sharded_lookup_table")
            and o.attr("is_sparse", True)
            and o.input("W") is not None and o.input("W").name == p.name
            for o in uses)

    sparse_names = [p.name for p in params if _sparse_ok(p)]
    grad_vars = []
    rows_vars = []
    for p in params:
        gv = gb.create_var(name=grad_var_name(p.name), shape=p.shape,
                           dtype=str(p.dtype))
        if p.name in sparse_names:
            rv = gb.create_var(name=grad_var_name(p.name) + "@ROWS",
                               shape=None, dtype="int32")
            gv.sparse_rows_var = rv
            rows_vars.append(rv)
        grad_vars.append(gv)
    op = gb.append_op(
        "autodiff", {"Loss": loss},
        {"Grads": grad_vars, "SparseRows": rows_vars},
        {"fwd_ops": fwd_ops, "wrt_names": wrt_names,
         "sparse_wrt_names": sparse_names,
         "grad_callback": None,
         "remat": bool(checkpoints)})
    prog._backward_ops.append(op)
    return list(zip(params, grad_vars))


def require_merged_sparse(program):
    """Ask the program's autodiff op(s) to emit sparse (rows, values)
    grads with duplicates merged (each row once, zero-filled duplicate
    slots on an out-of-range sentinel). Called by consumers whose math
    needs once-per-row semantics: norm-based clipping and sparse weight
    decay. Everything else (scatter-add accumulation, the non-lazy
    densify, lazy optimizers' internal re-merge) is duplicate-safe, and
    the merge is expensive (argsort + segment-sum per table per step)."""
    for op in getattr(program, "_backward_ops", ()):
        if op.type == "autodiff":
            op.attrs["merge_sparse"] = True


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of ``targets`` w.r.t arbitrary ``inputs`` (ref
    ``backward.py:613``). ``target_gradients`` supplies the cotangent
    (vjp seed); defaults to ones."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None and not isinstance(
            target_gradients, (list, tuple)):
        target_gradients = [target_gradients]
    prog = targets[0].block.program
    gb = prog.global_block()
    fwd_ops = list(gb.ops)
    wrt_names = [v.name for v in inputs]
    grad_vars = [
        gb.create_var(name=grad_var_name(v.name), shape=v.shape,
                      dtype=str(v.dtype))
        for v in inputs
    ]
    gb.append_op(
        "autodiff_vjp",
        {"Targets": list(targets),
         **({"TargetGrads": list(target_gradients)}
            if target_gradients else {})},
        {"Grads": grad_vars},
        {"fwd_ops": fwd_ops, "wrt_names": wrt_names})
    return grad_vars


gradients = calc_gradient

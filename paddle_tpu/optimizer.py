"""Optimizers (ref ``python/paddle/fluid/optimizer.py``: Optimizer base
``:44``, ``minimize:357`` = append_backward + apply_gradients; concrete
optimizers ``:410-1484``).

Each optimizer appends symbolic update ops whose Out slots alias the state
var names — the executor's donated-state jit gives true in-place updates on
TPU. Accumulators (velocity/moments/...) are persistable vars initialized by
the startup program, mirroring the reference's ``_add_accumulator``.
"""

from .backward import append_backward
from .core import framework, unique_name
from .core.framework import Variable, Parameter
from .clip import append_gradient_clip_ops
from .regularizer import append_regularization_ops

__all__ = [
    "SGD", "Momentum", "Adagrad", "Adam", "Adamax", "DecayedAdagrad",
    "Adadelta", "RMSProp", "Ftrl", "Lamb", "LarsMomentum",
    "SGDOptimizer", "MomentumOptimizer", "AdagradOptimizer", "AdamOptimizer",
    "AdamaxOptimizer", "DecayedAdagradOptimizer", "AdadeltaOptimizer",
    "RMSPropOptimizer", "FtrlOptimizer", "LambOptimizer",
    "LarsMomentumOptimizer", "ModelAverage", "ExponentialMovingAverage",
]


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._accumulators = {}  # acc_name -> {param_name: var}
        self._lr_var = None
        self.helper = None

    # ---- learning rate ----
    def _create_lr_var(self, program):
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return
        if self._lr_var is not None:
            return
        name = unique_name.generate("learning_rate")
        gb = program.global_block()
        self._lr_var = gb.create_var(name=name, shape=(), dtype="float32",
                                     persistable=True)
        sb = framework.default_startup_program().global_block()
        sp = sb.create_var(name=name, shape=(), dtype="float32",
                           persistable=True)
        sb.append_op("fill_constant", outputs={"Out": sp},
                     attrs={"shape": (), "dtype": "float32",
                            "value": float(self._learning_rate)})

    def _lr_for(self, param):
        mult = 1.0
        if isinstance(param, Parameter) and param.optimize_attr:
            mult = param.optimize_attr.get("learning_rate", 1.0)
        if mult == 1.0:
            return self._lr_var
        from .layers import nn
        return nn.scale(self._lr_var, scale=float(mult))

    # ---- accumulators ----
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None,
                         dtype=None):
        if name in self._accumulators and \
                param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        var_name = unique_name.generate("%s_%s" % (param.name, name))
        shape = tuple(shape if shape is not None else param.shape)
        dtype = dtype or str(param.dtype)
        prog = param.block.program
        var = prog.global_block().create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True)
        # accumulators lay out like their parameter on the mesh (the
        # reference keeps optimizer state on the param's device/pserver
        # shard; here: same PartitionSpec, so sharded optimizers stay
        # local). The annotation may arrive AFTER minimize() — e.g.
        # DistributeTranspiler sharding is_distributed tables — so keep a
        # live link for the executor to resolve at compile time.
        if tuple(shape) == tuple(param.shape):
            var.sharding = getattr(param, "sharding", None)
            var.sharding_like = param
        # marks the var as optimizer state for BuildStrategy.Reduce
        # (ZeRO-style dp-sharding of accumulators, executor._mesh_shardings)
        var.is_optimizer_state = True
        sb = framework.default_startup_program().global_block()
        sp = sb.create_var(name=var_name, shape=shape, dtype=dtype,
                           persistable=True)
        sb.append_op("fill_constant", outputs={"Out": sp},
                     attrs={"shape": shape, "dtype": dtype,
                            "value": float(fill_value)})
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # ---- the optimize pass ----
    def _create_optimization_pass(self, params_grads):
        prog = params_grads[0][0].block.program
        self._create_lr_var(prog)
        ops = []
        for p, g in params_grads:
            with framework.name_scope("optimizer"):
                op = self._append_optimize_op(prog.global_block(), (p, g))
                op.attrs["is_optimizer_op"] = True
                rows = getattr(g, "sparse_rows_var", None)
                if rows is not None:
                    # SelectedRows-style grad: the update op takes its
                    # scatter branch (ref sparse optimizer kernels)
                    op.inputs["GradRows"] = [rows]
                ops.append(op)
        self._finish_update(prog.global_block(), params_grads)
        return ops

    def _finish_update(self, block, params_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def _append_grad_accumulation(self, params_grads, k):
        """Gradient accumulation (ref ``framework/ir/multi_batch_merge_pass
        .cc``, driven by ``dist_mnist_batch_merge.py``): raw grads sum into
        persistable buffers for ``k`` micro-steps; downstream clip/
        regularization/update consume the RUNNING AVERAGE, and the update
        ops fire only on every k-th step (Switch-conditioned, so their
        outputs revert to the previous state in between). k micro-steps of
        batch b are numerically one step of batch k*b (mean-loss grads).

        Returns (averaged params_grads, apply-condition var)."""
        from .layers import nn as lnn
        from .layers import tensor as ltensor
        from .layers import control_flow as lcf

        prog = params_grads[0][0].block.program
        block = prog.global_block()
        self._rescale_lr_decay_counter(block, k)
        with framework.name_scope("grad_acc"):
            counter = lnn.autoincreased_step_counter(
                counter_name=unique_name.generate("@GRAD_ACC_COUNTER@"))
            kvar = ltensor.fill_constant([1], "int64", k)
            phase = block.create_var(
                name=unique_name.generate("grad_acc_phase"),
                shape=(1,), dtype="int64")
            block.append_op("elementwise_mod", {"X": counter, "Y": kvar},
                            {"Out": phase}, {})
            apply_cond = lcf.equal(phase,
                                   ltensor.fill_constant([1], "int64", 0))
            # keep factor: 0.0 on apply steps (reset), 1.0 otherwise
            not_apply = block.create_var(
                name=unique_name.generate("grad_acc_keep"),
                shape=(1,), dtype="bool")
            block.append_op("logical_not", {"X": apply_cond},
                            {"Out": not_apply}, {})
            keep_f = ltensor.cast(not_apply, "float32")

            new_pg = []
            for p, g in params_grads:
                rows = getattr(g, "sparse_rows_var", None)
                # accumulator is always DENSE [p.shape]: sparse micro-step
                # grads scatter-add into it (ref multi_batch_merge_pass.cc
                # likewise materializes merged grads); apply steps then
                # take the dense optimizer branch. Out-of-range sentinel
                # rows (the sparse path's duplicate parking) drop in the
                # scatter.
                acc = block.create_var(
                    name=unique_name.generate("%s@GRAD_ACC" % p.name),
                    shape=p.shape, dtype=str(g.dtype), persistable=True)
                sb = framework.default_startup_program().global_block()
                sp = sb.create_var(name=acc.name, shape=p.shape,
                                   dtype=str(g.dtype), persistable=True)
                sb.append_op("fill_constant", outputs={"Out": sp},
                             attrs={"shape": tuple(p.shape),
                                    "dtype": str(g.dtype), "value": 0.0})
                acc_sum = block.create_var(
                    name=unique_name.generate("%s@GRAD_ACC_SUM" % p.name),
                    shape=p.shape, dtype=str(g.dtype))
                if rows is not None:
                    block.append_op("scatter",
                                    {"X": acc, "Ids": rows, "Updates": g},
                                    {"Out": acc_sum}, {"overwrite": False})
                else:
                    block.append_op("elementwise_add", {"X": acc, "Y": g},
                                    {"Out": acc_sum}, {})
                avg = lnn.scale(acc_sum, scale=1.0 / k)
                # write-back: keep the sum between apply steps, reset after
                block.append_op("elementwise_mul",
                                {"X": acc_sum, "Y": keep_f},
                                {"Out": block.vars[acc.name]},
                                {"axis": -1})
                new_pg.append((p, avg))
        return new_pg, apply_cond

    def _rescale_lr_decay_counter(self, block, k):
        """LR schedules tick their ``@LR_DECAY_COUNTER@`` once per executor
        run; under accumulation the reference's merged program ticks once
        per k micro-batches (``multi_batch_merge_pass.cc`` runs the
        schedule once per merged run). Match it by rewiring every schedule
        op to read ``ceil(counter / k)`` instead of the raw counter."""
        from .core.framework import Operator

        name = "@LR_DECAY_COUNTER@"
        if not any(name == n for op in block.ops
                   for n in op.output_arg_names):
            return
        inc_idx = max(i for i, op in enumerate(block.ops)
                      if name in op.output_arg_names)
        counter = block.vars[name]
        kconst = block.create_var(
            name=unique_name.generate("lr_counter_k"),
            shape=(1,), dtype="int64")
        eff = block.create_var(
            name=unique_name.generate("lr_counter_eff"),
            shape=(1,), dtype="int64")
        # the schedules see a 0-based effective-step count: micro-steps
        # t*k .. t*k+k-1 all map to effective step t, so the k-th
        # micro-step's APPLY uses exactly the lr the merged big-batch
        # step t would
        new_ops = [
            Operator(block, "fill_constant", None, {"Out": kconst},
                     {"shape": (1,), "dtype": "int64", "value": float(k)}),
            Operator(block, "elementwise_floordiv",
                     {"X": counter, "Y": kconst}, {"Out": eff}, {}),
        ]
        inc_op = block.ops[inc_idx]
        for j, op in enumerate(new_ops):
            block.ops.insert(inc_idx + 1 + j, op)
        # rewire downstream readers (the schedule's cast/pow/... chain)
        for op in block.ops[inc_idx + 1 + len(new_ops):]:
            for slot, vs in op.inputs.items():
                op.inputs[slot] = [eff if v.name == name else v for v in vs]
        # the backward replay runs the autodiff op's CAPTURED fwd_ops list
        # (same Operator objects, separate list) — mirror the insertion
        # there or the rewired readers see an undefined var in the replay
        for op in block.ops:
            if op.type != "autodiff":
                continue
            fwd = op.attrs.get("fwd_ops") or []
            for i, f in enumerate(fwd):
                if f is inc_op:
                    op.attrs["fwd_ops"] = (fwd[:i + 1] + new_ops
                                           + fwd[i + 1:])
                    break
        block.program._version += 1

    def apply_gradients(self, params_grads, accumulate_steps=None):
        apply_cond = None
        if accumulate_steps is not None and accumulate_steps > 1:
            params_grads, apply_cond = self._append_grad_accumulation(
                params_grads, int(accumulate_steps))
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        self._final_params_grads = params_grads
        block = params_grads[0][0].block.program.global_block()
        n0 = len(block.ops)
        ops = self._create_optimization_pass(params_grads)
        if apply_cond is not None:
            # Guard EVERY persistable-state write appended by the pass
            # (update ops AND _finish_update extras like Adamax's beta-pow
            # scale, ModelAverage/EMA accumulators) — anything less lets
            # auxiliary state advance per micro-step
            for op in block.ops[n0:]:
                for vs in op.outputs.values():
                    if any(getattr(v, "persistable", False) for v in vs):
                        op.attrs["_switch_cond"] = apply_cond.name
                        break
        return ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, accumulate_steps=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads, accumulate_steps)
        # return the post-clip/regularization pairs (what the update ops
        # actually consume) — more useful than the raw backward outputs
        return optimize_ops, self._final_params_grads


class SGD(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "sgd",
            {"Param": p, "Grad": g, "LearningRate": self._lr_for(p)},
            {"ParamOut": p}, {})


class Momentum(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._add_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            {"Param": p, "Grad": g, "Velocity": v,
             "LearningRate": self._lr_for(p)},
            {"ParamOut": p, "VelocityOut": v},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov})


class LarsMomentum(Optimizer):
    """LARS (ref ``lars_momentum_op.cc`` / LarsMomentumOptimizer)."""

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._add_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            {"Param": p, "Grad": g, "Velocity": v,
             "LearningRate": self._lr_for(p)},
            {"ParamOut": p, "VelocityOut": v},
            {"mu": self._momentum, "lars_coeff": self._lars_coeff,
             "lars_weight_decay": self._lars_weight_decay})


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None,
                 name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, regularization, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._add_accumulator("moment", p, fill_value=self._init_acc)
        return block.append_op(
            "adagrad",
            {"Param": p, "Grad": g, "Moment": m,
             "LearningRate": self._lr_for(p)},
            {"ParamOut": p, "MomentOut": m}, {"epsilon": self._epsilon})


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None,
                 lazy_mode=False):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lazy_mode = lazy_mode

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._add_accumulator("moment1", p)
        m2 = self._add_accumulator("moment2", p)
        b1p = self._add_accumulator("beta1_pow_acc", p, self._beta1,
                                    shape=(1,))
        b2p = self._add_accumulator("beta2_pow_acc", p, self._beta2,
                                    shape=(1,))
        return block.append_op(
            "adam",
            {"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
             "Beta1Pow": b1p, "Beta2Pow": b2p,
             "LearningRate": self._lr_for(p)},
            {"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
             "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon, "lazy_mode": self._lazy_mode})


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._add_accumulator("moment", p)
        inf = self._add_accumulator("inf_norm", p)
        b1p = self._add_accumulator("beta1_pow_acc", p, self._beta1,
                                    shape=(1,))
        return block.append_op(
            "adamax",
            {"Param": p, "Grad": g, "Moment": m, "InfNorm": inf,
             "Beta1Pow": b1p, "LearningRate": self._lr_for(p)},
            {"ParamOut": p, "MomentOut": m, "InfNormOut": inf},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon})

    def _finish_update(self, block, params_grads):
        for p, _ in params_grads:
            b1p = self._get_accumulator("beta1_pow_acc", p)
            op = block.append_op(
                "scale", {"X": b1p}, {"Out": b1p}, {"scale": self._beta1})
            op.attrs["is_optimizer_op"] = True


class DecayedAdagrad(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._decay, self._epsilon = decay, epsilon

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._add_accumulator("moment", p)
        return block.append_op(
            "decayed_adagrad",
            {"Param": p, "Grad": g, "Moment": m,
             "LearningRate": self._lr_for(p)},
            {"ParamOut": p, "MomentOut": m},
            {"decay": self._decay, "epsilon": self._epsilon})


class Adadelta(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._epsilon, self._rho = epsilon, rho

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        g2 = self._add_accumulator("avg_squared_grad", p)
        u2 = self._add_accumulator("avg_squared_update", p)
        return block.append_op(
            "adadelta",
            {"Param": p, "Grad": g, "AvgSquaredGrad": g2,
             "AvgSquaredUpdate": u2, "LearningRate": self._lr_for(p)},
            {"ParamOut": p, "AvgSquaredGradOut": g2,
             "AvgSquaredUpdateOut": u2},
            {"epsilon": self._epsilon, "rho": self._rho})


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        ms = self._add_accumulator("mean_square", p)
        mg = self._add_accumulator("mean_grad", p)
        mom = self._add_accumulator("momentum", p)
        return block.append_op(
            "rmsprop",
            {"Param": p, "Grad": g, "MeanSquare": ms, "MeanGrad": mg,
             "Moment": mom, "LearningRate": self._lr_for(p)},
            {"ParamOut": p, "MeanSquareOut": ms, "MeanGradOut": mg,
             "MomentOut": mom},
            {"decay": self._rho, "epsilon": self._epsilon,
             "momentum": self._momentum, "centered": self._centered})


class Ftrl(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._add_accumulator("squared", p)
        lin = self._add_accumulator("linear", p)
        return block.append_op(
            "ftrl",
            {"Param": p, "Grad": g, "SquaredAccumulator": sq,
             "LinearAccumulator": lin, "LearningRate": self._lr_for(p)},
            {"ParamOut": p, "SquaredAccumOut": sq, "LinearAccumOut": lin},
            {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power})


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, regularization=None,
                 name=None):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._wd = lamb_weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._add_accumulator("moment1", p)
        m2 = self._add_accumulator("moment2", p)
        b1p = self._add_accumulator("beta1_pow_acc", p, self._beta1,
                                    shape=(1,))
        b2p = self._add_accumulator("beta2_pow_acc", p, self._beta2,
                                    shape=(1,))
        return block.append_op(
            "lamb",
            {"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
             "Beta1Pow": b1p, "Beta2Pow": b2p,
             "LearningRate": self._lr_for(p)},
            {"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
             "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon, "weight_decay": self._wd})


class ModelAverage(Optimizer):
    """Sliding-window parameter averaging (ref ``optimizer.py`` ModelAverage).
    Maintains a running sum accumulator per param; ``apply()`` swaps params
    for their average, ``restore()`` swaps back."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, name)
        self._max_window = max_average_window

    def _append_average_ops(self, program):
        gb = program.global_block()
        ops = []
        for p in program.all_parameters():
            acc = self._add_accumulator("sum", p)
            cnt = self._add_accumulator("cnt", p, shape=(1,))
            op1 = gb.append_op("elementwise_add", {"X": acc, "Y": p},
                               {"Out": acc}, {"is_optimizer_op": True})
            op2 = gb.append_op("increment", {"X": cnt}, {"Out": cnt},
                               {"step": 1.0, "is_optimizer_op": True})
            ops += [op1, op2]
        return ops

    def minimize(self, loss, **kwargs):
        raise TypeError("ModelAverage wraps another optimizer's program; "
                        "call _append_average_ops after minimize")


class ExponentialMovingAverage:
    """EMA of parameters (capability extension; standard for TPU training)."""

    def __init__(self, decay=0.999, name=None):
        self._decay = decay
        self._name = name or "ema"
        self._shadows = {}

    def update(self, program=None):
        program = program or framework.default_main_program()
        gb = program.global_block()
        sb = framework.default_startup_program().global_block()
        for p in program.all_parameters():
            name = "%s.%s" % (p.name, self._name)
            shadow = gb.create_var(name=name, shape=p.shape,
                                   dtype=str(p.dtype), persistable=True)
            sp = sb.create_var(name=name, shape=p.shape, dtype=str(p.dtype),
                               persistable=True)
            sb.append_op("fill_constant", outputs={"Out": sp},
                         attrs={"shape": p.shape, "dtype": str(p.dtype),
                                "value": 0.0})
            # shadow = decay*shadow + (1-decay)*param
            tmp_sh = gb.append_op(
                "scale", {"X": shadow}, {"Out": shadow},
                {"scale": self._decay, "is_optimizer_op": True})
            from .layers import nn
            scaled_p = nn.scale(p, scale=1.0 - self._decay)
            gb.append_op("elementwise_add", {"X": shadow, "Y": scaled_p},
                         {"Out": shadow}, {"is_optimizer_op": True})
            self._shadows[p.name] = shadow


# reference-style aliases
SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
DecayedAdagradOptimizer = DecayedAdagrad
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl
LambOptimizer = Lamb
LarsMomentumOptimizer = LarsMomentum

"""AsyncExecutor: file-fed training driven by the native data plane.

Reference: ``paddle/fluid/framework/async_executor.h:64`` — worker threads
each own a DataFeed over a file split and run the program op-by-op;
``python/paddle/fluid/async_executor.py`` is the Python driver.

TPU-native re-design: the parallelism moves to the right places for one
big accelerator — C++ reader threads (``paddle_tpu/native_src/prefetch_queue.cc``) keep an
MPMC byte-record queue full from recordio files, the host assembles dense
batches (one np.frombuffer per slot, ``data/data_feed.py``), and ONE
compiled step function consumes them back-to-back (dispatch is async, so
host batching overlaps device compute). Thread-per-graph execution would
only fragment the TPU; thread_num instead scales the file readers.
"""

import warnings

import numpy as np

from . import native
from .core import framework
from .core.executor import Executor, global_scope
from .reliability import faults

__all__ = ["AsyncExecutor"]


class AsyncExecutor:
    def __init__(self, place=None, run_mode=""):
        self.place = place
        self._exe = Executor(place)

    def run(self, program, data_feed, filelist, thread_num=2,
            fetch=None, mode="", debug=False, n_epochs=1, scope=None,
            queue_capacity=1024, max_bad_records=0):
        """Train ``program`` over every sample in ``filelist`` (recordio
        files of ``data_feed``-serialized samples). Returns the list of
        fetch values from the last step.

        ``thread_num`` = native reader threads (ref: worker thread count).
        Partial final batches are dropped, matching the fixed-shape batch
        convention (and the reference's DataFeed batch semantics).

        ``max_bad_records``: a long-running ingest job must not die to one
        torn record (the reference's recordio chunk-CRC skip-on-corrupt,
        SURVEY §5.3) — records whose size does not match the
        ``data_feed`` schema are skipped and counted, up to this bound;
        one past it aborts the run. 0 (default) keeps fail-fast; ``None``
        is unbounded (counted + warned only)."""
        program = program or framework.default_main_program()
        fetch = fetch or []
        if isinstance(filelist, str):
            filelist = [filelist]
        if not native.native_available():
            raise RuntimeError("AsyncExecutor needs the native data plane "
                               "(g++ toolchain) — use PyReader instead")
        faults.maybe_install_from_env()
        scope = scope or global_scope()
        fetch_vals = None
        bs = data_feed.batch_size
        rec_nbytes = getattr(data_feed, "sample_nbytes", None)
        steps = 0
        bad = 0
        with native.PrefetchQueue(capacity=queue_capacity) as q:
            q.start_files(list(filelist), n_threads=int(thread_num),
                          n_epochs=int(n_epochs))
            batch = []
            for rec in q:
                # fault site: 'corrupt' truncates the record (drilling the
                # bounded-skip path below); 'error'/'hang' model a dying
                # or stalling reader
                if faults.trip("recordio.read") == "corrupt":
                    rec = faults.corrupt_bytes(rec)
                if rec_nbytes is not None and len(rec) != rec_nbytes:
                    bad += 1
                    if max_bad_records is not None and bad > max_bad_records:
                        raise ValueError(
                            "AsyncExecutor: %d malformed record(s) (got "
                            "%d bytes, schema says %d) exceeds "
                            "max_bad_records=%d — corrupt file or wrong "
                            "DataFeedDesc?"
                            % (bad, len(rec), rec_nbytes, max_bad_records))
                    continue
                batch.append(rec)
                if len(batch) < bs:
                    continue
                feed = data_feed.parse_batch(batch)
                batch = []
                fetch_vals = self._exe.run(program, feed=feed,
                                           fetch_list=fetch, scope=scope,
                                           return_numpy=False)
                steps += 1
                if debug and steps % 100 == 0:
                    print("AsyncExecutor: %d steps" % steps)
        if bad:
            warnings.warn(
                "AsyncExecutor: skipped %d malformed record(s) "
                "(max_bad_records=%s)" % (bad, max_bad_records),
                RuntimeWarning, stacklevel=2)
        if fetch_vals is None:
            raise RuntimeError(
                "AsyncExecutor: no full batch assembled from %d file(s) — "
                "fewer than batch_size=%d records present (partial batches "
                "are dropped)" % (len(filelist), bs))
        return [np.asarray(v) for v in fetch_vals]

    def run_from_stream(self, program, data_feed, stream, fetch=None,
                        scope=None, max_bad_records=0, max_steps=None,
                        on_step=None):
        """Continuous analog of :meth:`run`: consume a live
        :class:`~paddle_tpu.streaming.RecordStream` (tail-follow over a
        growing recordio file set — pure Python, no native toolchain
        needed) until it closes or ``max_steps`` training steps ran.

        The stream trips the ``recordio.read``/``stream.tail`` fault
        sites itself; ``max_bad_records`` bounds schema-size-mismatch
        skips exactly like :meth:`run`. ``on_step(step, fetch_vals)``
        fires after every executor step (the trainer's publish hook).
        Returns the number of steps executed."""
        from .streaming.stream import StreamIngester

        program = program or framework.default_main_program()
        fetch = fetch or []
        faults.maybe_install_from_env()
        scope = scope or global_scope()
        ingester = StreamIngester(stream, data_feed,
                                  max_bad_records=max_bad_records)
        steps = 0
        for feed in ingester.batches():
            vals = self._exe.run(program, feed=feed, fetch_list=fetch,
                                 scope=scope, return_numpy=False)
            steps += 1
            if on_step is not None:
                on_step(steps, vals)
            if max_steps is not None and steps >= max_steps:
                break
        return steps

"""Profiler (ref ``python/paddle/fluid/profiler.py`` +
``platform/profiler.h`` + CUPTI ``device_tracer.h`` + ``tools/timeline.py``).

TPU-native: jax.profiler XPlane traces (viewable in TensorBoard/Perfetto —
the chrome-trace parity) + a lightweight host-event aggregator giving the
reference's sorted-table report."""

import bisect
import contextlib
import threading
import time
from collections import defaultdict, deque

import jax

__all__ = ["profiler", "start_profiler", "stop_profiler", "reset_profiler",
           "record_event", "Histogram"]

_events = defaultdict(lambda: [0.0, 0])  # name -> [total_s, count]
# serving threads record events concurrently with a sampling stop/report
# on another thread; every _events touch goes through this lock
_events_lock = threading.Lock()
_trace_dir = None
_enabled = False


def start_profiler(state="All", trace_dir=None):
    global _enabled, _trace_dir
    _enabled = True
    _trace_dir = trace_dir
    if trace_dir:
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key="total", profile_path=None, silent=False):
    """``silent=True`` returns the report without printing — for callers
    (e.g. the serving metrics loop) that sample the profiler on a cadence
    and must not spam stdout."""
    global _enabled
    _enabled = False
    if _trace_dir:
        jax.profiler.stop_trace()
    report = _report(sorted_key)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report)
    elif not silent:
        print(report)
    return report


def reset_profiler():
    with _events_lock:
        _events.clear()


def _report(sorted_key="total"):
    lines = ["%-40s %10s %12s %12s" % ("Event", "Calls", "Total(ms)",
                                       "Avg(ms)")]
    with _events_lock:
        items = [(name, list(v)) for name, v in _events.items()]
    if sorted_key == "total":
        items.sort(key=lambda kv: -kv[1][0])
    elif sorted_key == "calls":
        items.sort(key=lambda kv: -kv[1][1])
    for name, (total, count) in items:
        lines.append("%-40s %10d %12.3f %12.3f"
                     % (name, count, total * 1e3,
                        total * 1e3 / max(count, 1)))
    return "\n".join(lines)


@contextlib.contextmanager
def record_event(name):
    """RAII host event (ref ``RecordEvent`` ``profiler.h:41``); also opens a
    jax.named_scope so the device trace carries the same label."""
    t0 = time.perf_counter()
    try:
        with jax.named_scope(name.replace("/", "_")):
            yield
    finally:
        if _enabled:
            dt = time.perf_counter() - t0
            with _events_lock:
                ev = _events[name]
                ev[0] += dt
                ev[1] += 1


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None,
             trace_dir=None):
    start_profiler(state, trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class Histogram:
    """Thread-safe sliding-window sample store with exact percentiles.

    The host-event table above aggregates to (total, count) — enough for a
    training report, useless for a latency SLO, where the tail IS the
    metric. This keeps the most recent ``max_samples`` raw observations
    (a sliding window, so a long-running server reports *current* tail
    behavior, not its lifetime average) and computes exact
    nearest-rank percentiles on demand.
    """

    def __init__(self, max_samples=8192):
        self._samples = deque(maxlen=max_samples)
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0

    def add(self, value):
        with self._lock:
            self._samples.append(float(value))
            self._count += 1
            self._total += float(value)

    @property
    def count(self):
        """Lifetime observation count (not capped by the window)."""
        return self._count

    @property
    def total(self):
        return self._total

    @staticmethod
    def _at_rank(data, p):
        """Nearest-rank percentile over an already-sorted sample list."""
        if not 0 <= p <= 100:
            raise ValueError("percentile must be in [0, 100], got %r" % p)
        rank = max(0, min(len(data) - 1,
                          int(round(p / 100.0 * (len(data) - 1)))))
        return data[rank]

    def percentile(self, p):
        """Nearest-rank percentile of the current window; None if empty."""
        with self._lock:
            data = sorted(self._samples)
        return self._at_rank(data, p) if data else None

    def percentiles(self, ps=(50, 95, 99)):
        with self._lock:
            data = sorted(self._samples)
        return {"p%g" % p: (self._at_rank(data, p) if data else None)
                for p in ps}

    def cdf(self, value):
        """Fraction of windowed samples <= value (SLO attainment check)."""
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return None
        return bisect.bisect_right(data, value) / float(len(data))

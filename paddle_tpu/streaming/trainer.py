"""Long-running DeepFM trainer over a live record stream.

Reference: the pslib/Downpour trainer loop — ``async_executor.cc`` workers
consume click logs forever while the table server ships fresh parameters
to serving. Here the trainer consumes a :class:`~.stream.RecordStream`,
tracks an accuracy proxy on held-out rows, and periodically *publishes*:
a CRC-verified versioned checkpoint (``checkpoint.save_checkpoint``)
whose write runs on a background thread — the device->host snapshot is
synchronous (the executor donates state buffers on the next step) but
the training loop never blocks on the disk write, and the atomic
``latest`` marker lands last so the swap plane can only ever observe
complete versions.

Fault site ``checkpoint.publish`` trips once per publish attempt:
``error`` models a failed publish (counted, training continues — a
long-running trainer must not die to one), ``corrupt`` damages the
landed version so the swap plane's fallback-to-previous-intact path can
be drilled end to end.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

from .. import checkpoint
from ..core.executor import Executor, Scope, scope_guard
from ..core.framework import Program, program_guard
from ..core import unique_name
from ..data.data_feed import DataFeedDesc
from ..obs import flight
from ..reliability import faults
from .stream import RecordStream, StreamIngester, write_records

__all__ = ["StreamingTrainer", "feed_desc", "synthesize_stream_files"]

TRAINER_READY_PREFIX = "PADDLE_TPU_TRAINER_READY "


def feed_desc(num_fields=4, dense_dim=4, batch_size=16):
    """The DeepFM stream schema: one record = concatenated dense slots."""
    return DataFeedDesc([("feat_ids", (num_fields,), "int64"),
                         ("dense_value", (dense_dim,), "float32"),
                         ("label", (1,), "int64")], batch_size=batch_size)


def synthesize_stream_files(data_dir, num_fields=4, sparse_feature_dim=64,
                            dense_dim=4, n_files=1, rows_per_file=256,
                            start_index=0, seed=0, chunk_rows=64):
    """Write learnable synthetic CTR rows as recordio files (pure-Python
    writer — no native toolchain needed). The labeling rule is a fixed
    function of ``seed``, so successive calls with increasing
    ``start_index`` extend the SAME distribution — the producer side of a
    tail-follow drill. Returns the file paths written."""
    desc = feed_desc(num_fields, dense_dim, batch_size=1)
    rule = np.random.RandomState(seed)
    w_id = rule.normal(0.0, 1.0, sparse_feature_dim)
    w_dense = rule.normal(0.0, 1.0, dense_dim)
    thresh = 0.5 * float(w_dense.sum())  # E[logit] -> ~balanced labels
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for fi in range(start_index, start_index + n_files):
        path = os.path.join(data_dir, "part-%05d.recordio" % fi)
        rng = np.random.RandomState(seed * 7919 + 1000 + fi)
        recs = []
        for _ in range(rows_per_file):
            ids = rng.randint(0, sparse_feature_dim, num_fields)
            dense = rng.uniform(0.0, 1.0, dense_dim).astype("f4")
            logit = float(w_id[ids].sum()) + float(dense @ w_dense)
            recs.append(desc.serialize({
                "feat_ids": ids, "dense_value": dense,
                "label": [int(logit > thresh)]}))
            if len(recs) >= chunk_rows:
                write_records(path, recs)
                recs = []
        if recs:
            write_records(path, recs)
        paths.append(path)
    return paths


class StreamingTrainer:
    """DeepFM trainer consuming a record stream, publishing versioned
    checkpoints every ``publish_every_steps`` without blocking the loop.

    ``ckpt_dir`` receives ``checkpoint_<n>`` versions (retention-GC'd to
    ``max_versions``, pinned versions excepted) and ``serve/`` — an
    inference-model export written once at startup that a ServingEngine
    loads; subsequent publishes only ship parameter deltas via the
    checkpoint versions the swap plane stages.

    The first ``holdout_batches`` full batches off the stream are HELD
    OUT (never trained on) and scored at every publish —
    ``last_eval_loss`` is the accuracy proxy the soak test watches
    improve across hot swaps."""

    def __init__(self, ckpt_dir, num_fields=4, sparse_feature_dim=64,
                 embedding_size=8, dense_dim=4, hidden_sizes=(32,),
                 batch_size=16, learning_rate=0.05, publish_every_steps=50,
                 max_versions=4, holdout_batches=2, seed=7, place=None):
        from ..models.deepfm import deepfm
        from .. import optimizer

        self.ckpt_dir = ckpt_dir
        self.serve_dir = os.path.join(ckpt_dir, "serve")
        self.publish_every_steps = int(publish_every_steps)
        self.max_versions = max_versions
        self.holdout_batches = int(holdout_batches)
        self.data_feed = feed_desc(num_fields, dense_dim, batch_size)
        self.holdout = []
        self.step = 0
        self.publishes = 0
        self.publish_failures = 0
        self.last_train_loss = None
        self.last_eval_loss = None
        self._writer = None

        self.main, self.startup = Program(), Program()
        self.main.random_seed = self.startup.random_seed = int(seed)
        self.scope = Scope()
        with program_guard(self.main, self.startup), \
                scope_guard(self.scope):
            unique_name.switch()
            spec = deepfm(sparse_feature_dim=sparse_feature_dim,
                          num_fields=num_fields,
                          embedding_size=embedding_size,
                          dense_dim=dense_dim, hidden_sizes=hidden_sizes)
            self.loss = spec.loss
            self.prob = spec.fetches["prob"]
            optimizer.Adam(learning_rate=learning_rate).minimize(self.loss)
            self.exe = Executor(place)
            self.exe.run(self.startup)
            # forward-only clone for held-out scoring: pruning to the loss
            # drops the backward + Adam ops, so scoring never trains
            eval_prog = self.main.clone(for_test=True)
            eval_loss = eval_prog.global_block().var(self.loss.name)
            self.eval_prog = eval_prog.prune([eval_loss])
            self.eval_loss = self.eval_prog.global_block().var(
                self.loss.name)
        self._export_serve_dir()

    def _export_serve_dir(self):
        from .. import io

        with scope_guard(self.scope):
            io.save_inference_model(
                self.serve_dir, ["feat_ids", "dense_value"], [self.prob],
                self.exe, main_program=self.main)

    # -- accuracy proxy ------------------------------------------------------
    def eval_holdout(self):
        """Mean loss over the held-out batches (lower = better)."""
        if not self.holdout:
            return None
        losses = []
        for feed in self.holdout:
            v, = self.exe.run(self.eval_prog, feed=feed,
                              fetch_list=[self.eval_loss], scope=self.scope,
                              return_numpy=False)
            losses.append(float(np.asarray(v)))
        return float(np.mean(losses))

    # -- publish -------------------------------------------------------------
    def publish(self):
        """Snapshot + async-write one checkpoint version. Never raises:
        a failed publish is counted (``publish_failures``), recorded to
        the flight ring, and training continues."""
        # surface a PREVIOUS publish's write failure now (non-blocking:
        # only a finished writer is examined)
        if self._writer is not None and self._writer.done() \
                and self._writer.error is not None:
            self.publish_failures += 1
            flight.record("publish.fail", step=self.step,
                          error=type(self._writer.error).__name__)
            self._writer = None
        self.last_eval_loss = self.eval_holdout()
        try:
            # fault site: 'error' = the publish path dying mid-flight,
            # 'corrupt' = a bad version landing (swap-plane fallback drill)
            mode = faults.trip("checkpoint.publish")
            writer = checkpoint.save_checkpoint(
                self.exe, self.ckpt_dir, main_program=self.main,
                scope=self.scope, async_write=True,
                max_versions=self.max_versions,
                extra_meta={"step": self.step,
                            "eval_loss": self.last_eval_loss})
        except Exception as e:  # noqa: BLE001 — a trainer outlives publishes
            self.publish_failures += 1
            flight.record("publish.fail", step=self.step,
                          error=type(e).__name__)
            return None
        version = int(os.path.basename(writer.path).split("_")[1])
        if mode == "corrupt":
            writer.wait()  # only the injected-corruption path blocks
            checkpoint._flip_byte(
                os.path.join(writer.path, "replicated.npz"))
        self._writer = writer
        self.publishes += 1
        flight.record("publish.version", version=version, step=self.step,
                      eval_loss=self.last_eval_loss)
        return writer

    # -- the loop ------------------------------------------------------------
    def run(self, stream, max_steps=None, max_bad_records=0,
            on_publish=None):
        """Consume ``stream`` until it closes (or ``max_steps`` training
        steps ran), publishing every ``publish_every_steps``. Returns the
        number of training steps executed."""
        ing = StreamIngester(stream, self.data_feed,
                             max_bad_records=max_bad_records)
        for feed in ing.batches():
            if len(self.holdout) < self.holdout_batches:
                self.holdout.append(feed)
                continue
            v, = self.exe.run(self.main, feed=feed, fetch_list=[self.loss],
                              scope=self.scope, return_numpy=False)
            self.last_train_loss = float(np.asarray(v))
            self.step += 1
            if self.publish_every_steps \
                    and self.step % self.publish_every_steps == 0:
                self.publish()
                if on_publish is not None:
                    on_publish(self)
            if max_steps is not None and self.step >= max_steps:
                break
        return self.step

    def close(self):
        """Join the in-flight checkpoint write (surfacing its error)."""
        if self._writer is not None:
            self._writer.wait()
            self._writer = None


def main(argv=None):
    """CLI for drills: tail-follow ``--data-dir`` and train, publishing
    into ``--ckpt-dir``. Prints a READY line (with the serve dir) once
    the model is built and exported, so a parent process can time its
    kill signals against the publish cadence."""
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--data-dir", required=True)
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--publish-every", type=int, default=25)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--max-versions", type=int, default=4)
    p.add_argument("--sparse-dim", type=int, default=64)
    p.add_argument("--poll-interval", type=float, default=0.05)
    p.add_argument("--seed", type=int, default=7)
    args = p.parse_args(argv)

    faults.maybe_install_from_env()
    flight.install()
    trainer = StreamingTrainer(
        args.ckpt_dir, batch_size=args.batch_size,
        publish_every_steps=args.publish_every,
        max_versions=args.max_versions,
        sparse_feature_dim=args.sparse_dim, seed=args.seed)
    print(TRAINER_READY_PREFIX + json.dumps(
        {"pid": os.getpid(), "serve_dir": trainer.serve_dir}), flush=True)
    stream = RecordStream(args.data_dir,
                          poll_interval_s=args.poll_interval)
    t0 = time.monotonic()
    steps = trainer.run(stream, max_steps=args.steps)
    trainer.close()
    flight.maybe_dump(reason="trainer-exit")
    print(json.dumps({
        "steps": steps, "publishes": trainer.publishes,
        "publish_failures": trainer.publish_failures,
        "eval_loss": trainer.last_eval_loss,
        "rows_per_sec": stream.rows_per_sec(),
        "elapsed_s": round(time.monotonic() - t0, 3)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

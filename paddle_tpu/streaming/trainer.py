"""Long-running DeepFM trainer over a live record stream.

Reference: the pslib/Downpour trainer loop — ``async_executor.cc`` workers
consume click logs forever while the table server ships fresh parameters
to serving. Here the trainer consumes a :class:`~.stream.RecordStream`,
tracks an accuracy proxy on held-out rows, and periodically *publishes*:
a CRC-verified versioned checkpoint (``checkpoint.save_checkpoint``)
whose write runs on a background thread — the device->host snapshot is
synchronous (the executor donates state buffers on the next step) but
the training loop never blocks on the disk write, and the atomic
``latest`` marker lands last so the swap plane can only ever observe
complete versions.

Fault site ``checkpoint.publish`` trips once per publish attempt:
``error`` models a failed publish (counted, training continues — a
long-running trainer must not die to one), ``corrupt`` damages the
landed version so the swap plane's fallback-to-previous-intact path can
be drilled end to end. Fault site ``cursor.write`` trips once per
cursor capture inside a publish: ``error`` fails the WHOLE publish (a
version without its cursor would silently replay from zero on resume),
``corrupt`` drops the file offsets — the resulting full replay must
show up *counted* in ``replayed_rows``, never silently.

Restart story (the multi-host control plane, ISSUE 19): every publish
carries the stream's ingest cursor inside the checkpoint manifest
(atomic — manifest present means cursor present), and a per-step
``progress.json`` ledger records how far past the last publish the
trainer had read. ``resume()`` restores weights + step + cursor from
the newest intact version and computes ``replayed_rows`` = ledger rows
minus cursor rows: at-least-once ingest with bounded, counted replay.
SIGTERM flips a preemption flag; the loop finishes its micro-batch,
flushes a final checkpoint+cursor under a ``Deadline`` grace budget,
releases its partition leases, and exits clean — so an elastic
whole-group restart (``distributed/launch.py --max_restarts``) resumes
instead of replaying from zero.
"""

import argparse
import json
import os
import signal
import sys
import threading
import time

import numpy as np

from .. import checkpoint
from ..core.executor import Executor, Scope, scope_guard
from ..core.framework import Program, program_guard
from ..core import unique_name
from ..data.data_feed import DataFeedDesc
from ..obs import flight
from ..reliability import faults
from ..reliability.policy import Deadline
from .stream import RecordStream, StreamIngester, write_records

__all__ = ["StreamingTrainer", "feed_desc", "synthesize_stream_files"]

_PROGRESS = "progress.json"

TRAINER_READY_PREFIX = "PADDLE_TPU_TRAINER_READY "


def feed_desc(num_fields=4, dense_dim=4, batch_size=16):
    """The DeepFM stream schema: one record = concatenated dense slots."""
    return DataFeedDesc([("feat_ids", (num_fields,), "int64"),
                         ("dense_value", (dense_dim,), "float32"),
                         ("label", (1,), "int64")], batch_size=batch_size)


def synthesize_stream_files(data_dir, num_fields=4, sparse_feature_dim=64,
                            dense_dim=4, n_files=1, rows_per_file=256,
                            start_index=0, seed=0, chunk_rows=64):
    """Write learnable synthetic CTR rows as recordio files (pure-Python
    writer — no native toolchain needed). The labeling rule is a fixed
    function of ``seed``, so successive calls with increasing
    ``start_index`` extend the SAME distribution — the producer side of a
    tail-follow drill. Returns the file paths written."""
    desc = feed_desc(num_fields, dense_dim, batch_size=1)
    rule = np.random.RandomState(seed)
    w_id = rule.normal(0.0, 1.0, sparse_feature_dim)
    w_dense = rule.normal(0.0, 1.0, dense_dim)
    thresh = 0.5 * float(w_dense.sum())  # E[logit] -> ~balanced labels
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for fi in range(start_index, start_index + n_files):
        path = os.path.join(data_dir, "part-%05d.recordio" % fi)
        rng = np.random.RandomState(seed * 7919 + 1000 + fi)
        recs = []
        for _ in range(rows_per_file):
            ids = rng.randint(0, sparse_feature_dim, num_fields)
            dense = rng.uniform(0.0, 1.0, dense_dim).astype("f4")
            logit = float(w_id[ids].sum()) + float(dense @ w_dense)
            recs.append(desc.serialize({
                "feat_ids": ids, "dense_value": dense,
                "label": [int(logit > thresh)]}))
            if len(recs) >= chunk_rows:
                write_records(path, recs)
                recs = []
        if recs:
            write_records(path, recs)
        paths.append(path)
    return paths


class StreamingTrainer:
    """DeepFM trainer consuming a record stream, publishing versioned
    checkpoints every ``publish_every_steps`` without blocking the loop.

    ``ckpt_dir`` receives ``checkpoint_<n>`` versions (retention-GC'd to
    ``max_versions``, pinned versions excepted) and ``serve/`` — an
    inference-model export written once at startup that a ServingEngine
    loads; subsequent publishes only ship parameter deltas via the
    checkpoint versions the swap plane stages.

    The first ``holdout_batches`` full batches off the stream are HELD
    OUT (never trained on) and scored at every publish —
    ``last_eval_loss`` is the accuracy proxy the soak test watches
    improve across hot swaps."""

    def __init__(self, ckpt_dir, num_fields=4, sparse_feature_dim=64,
                 embedding_size=8, dense_dim=4, hidden_sizes=(32,),
                 batch_size=16, learning_rate=0.05, publish_every_steps=50,
                 max_versions=4, holdout_batches=2, seed=7, place=None,
                 dp=None):
        from ..models.deepfm import deepfm
        from .. import optimizer

        self.ckpt_dir = ckpt_dir
        self.serve_dir = os.path.join(ckpt_dir, "serve")
        self.publish_every_steps = int(publish_every_steps)
        self.max_versions = max_versions
        self.holdout_batches = int(holdout_batches)
        self.data_feed = feed_desc(num_fields, dense_dim, batch_size)
        self.holdout = []
        self.step = 0
        self.publishes = 0
        self.publish_failures = 0
        self.last_train_loss = None
        self.last_eval_loss = None
        self._writer = None
        self._stream = None
        self.coordinator = None
        self.resumed_version = None
        self.replayed_rows = 0
        self.preempted = threading.Event()
        from .stream import REGISTRY
        REGISTRY.gauge(
            "paddle_tpu_stream_replayed_rows",
            "rows re-read past the resumed cursor (at-least-once replay)",
            fn=lambda: self.replayed_rows)

        self.main, self.startup = Program(), Program()
        self.main.random_seed = self.startup.random_seed = int(seed)
        self.scope = Scope()
        with program_guard(self.main, self.startup), \
                scope_guard(self.scope):
            unique_name.switch()
            spec = deepfm(sparse_feature_dim=sparse_feature_dim,
                          num_fields=num_fields,
                          embedding_size=embedding_size,
                          dense_dim=dense_dim, hidden_sizes=hidden_sizes)
            self.loss = spec.loss
            self.prob = spec.fetches["prob"]
            optimizer.Adam(learning_rate=learning_rate).minimize(self.loss)
            self.exe = Executor(place)
            self.exe.run(self.startup)
            # forward-only clone for held-out scoring: pruning to the loss
            # drops the backward + Adam ops, so scoring never trains
            eval_prog = self.main.clone(for_test=True)
            eval_loss = eval_prog.global_block().var(self.loss.name)
            self.eval_prog = eval_prog.prune([eval_loss])
            self.eval_loss = self.eval_prog.global_block().var(
                self.loss.name)
            # dp-sharded training (PR-6 embedding all-to-all rides the
            # same CompiledProgram path): per-host stream partitions feed
            # a data-parallel step when enough local devices exist
            self.train_prog = self.main
            self.dp = 0
            if dp and int(dp) > 1:
                import jax
                from jax.sharding import Mesh
                from ..core.compiler import CompiledProgram

                devs = jax.devices()
                if len(devs) >= int(dp) and batch_size % int(dp) == 0:
                    mesh = Mesh(np.array(devs[:int(dp)]), ("dp",))
                    self.train_prog = CompiledProgram(
                        self.main).with_data_parallel(
                            loss_name=self.loss.name, mesh=mesh)
                    self.dp = int(dp)
        self._export_serve_dir()

    def _export_serve_dir(self):
        from .. import io

        with scope_guard(self.scope):
            io.save_inference_model(
                self.serve_dir, ["feat_ids", "dense_value"], [self.prob],
                self.exe, main_program=self.main)

    # -- accuracy proxy ------------------------------------------------------
    def eval_holdout(self):
        """Mean loss over the held-out batches (lower = better)."""
        if not self.holdout:
            return None
        losses = []
        for feed in self.holdout:
            v, = self.exe.run(self.eval_prog, feed=feed,
                              fetch_list=[self.eval_loss], scope=self.scope,
                              return_numpy=False)
            losses.append(float(np.asarray(v)))
        return float(np.mean(losses))

    # -- durable cursor / progress ledger ------------------------------------
    def _capture_cursor(self):
        """The stream's resume point, destined for the version manifest.
        Fault site ``cursor.write``: ``error`` raises (failing the whole
        publish — a cursor-less version would replay from zero silently),
        ``corrupt`` drops the offsets, modeling a torn cursor that forces
        a full — but *counted* — replay."""
        if self._stream is None:
            return None
        mode = faults.trip("cursor.write")
        cur = self._stream.cursor()
        if mode == "corrupt":
            cur = {"rows": 0, "files": {}}
        return cur

    def _write_progress(self):
        """Per-step ledger of how far the ingest has read — the delta
        between this and the last published cursor is exactly the replay
        a restart pays, so resume() can COUNT it."""
        if self._stream is None:
            return
        tmp = os.path.join(self.ckpt_dir, _PROGRESS + ".tmp")
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps({"rows": self._stream.rows_total,
                                    "step": self.step}))
            os.replace(tmp, os.path.join(self.ckpt_dir, _PROGRESS))
        except OSError:
            pass  # a torn ledger only costs replay accounting, not data

    def _read_progress(self):
        try:
            with open(os.path.join(self.ckpt_dir, _PROGRESS)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # -- resume --------------------------------------------------------------
    def resume(self, stream=None):
        """Warm-start from the newest intact published version: weights,
        step counter, and (when ``stream`` is given) the ingest cursor —
        weights and cursor always come from the SAME version, so the
        model never skips rows it was not trained on. Replay is counted:
        ``replayed_rows`` = the dead incarnation's progress ledger minus
        the resumed cursor (bounded by the publish cadence). Returns the
        version resumed from, or None (cold start)."""
        try:
            v, updates, extra = checkpoint.load_staged(
                self.ckpt_dir, self.main)
        except checkpoint.NoCheckpointError:
            return None
        for name, value in updates:
            self.scope.set(name, value)
        self.step = int(extra.get("step", 0))
        self.resumed_version = v
        cur = extra.get("cursor")
        if stream is not None and cur is not None:
            stream.seek(cur)
            self._stream = stream
            ledger = self._read_progress()
            if ledger is not None:
                self.replayed_rows = max(
                    0, int(ledger.get("rows", 0)) - int(cur.get("rows", 0)))
        flight.record("trainer.resume", version=v, step=self.step,
                      replayed_rows=self.replayed_rows)
        return v

    # -- publish -------------------------------------------------------------
    def publish(self):
        """Snapshot + async-write one checkpoint version, the stream's
        ingest cursor riding in the manifest (atomic: a kill mid-publish
        leaves a manifest-less torn dir, so the older version's cursor
        stays the resume point). Never raises: a failed publish is
        counted (``publish_failures``), recorded to the flight ring, and
        training continues."""
        # surface a PREVIOUS publish's write failure now (non-blocking:
        # only a finished writer is examined)
        if self._writer is not None and self._writer.done() \
                and self._writer.error is not None:
            self.publish_failures += 1
            flight.record("publish.fail", step=self.step,
                          error=type(self._writer.error).__name__)
            self._writer = None
        self.last_eval_loss = self.eval_holdout()
        try:
            # fault site: 'error' = the publish path dying mid-flight,
            # 'corrupt' = a bad version landing (swap-plane fallback drill)
            mode = faults.trip("checkpoint.publish")
            extra = {"step": self.step, "eval_loss": self.last_eval_loss}
            cur = self._capture_cursor()
            if cur is not None:
                extra["cursor"] = cur
            writer = checkpoint.save_checkpoint(
                self.exe, self.ckpt_dir, main_program=self.main,
                scope=self.scope, async_write=True,
                max_versions=self.max_versions,
                extra_meta=extra)
        except Exception as e:  # noqa: BLE001 — a trainer outlives publishes
            self.publish_failures += 1
            flight.record("publish.fail", step=self.step,
                          error=type(e).__name__)
            return None
        version = int(os.path.basename(writer.path).split("_")[1])
        if mode == "corrupt":
            writer.wait()  # only the injected-corruption path blocks
            checkpoint._flip_byte(
                os.path.join(writer.path, "replicated.npz"))
        self._writer = writer
        self.publishes += 1
        flight.record("publish.version", version=version, step=self.step,
                      eval_loss=self.last_eval_loss)
        return writer

    # -- the loop ------------------------------------------------------------
    def run(self, stream, max_steps=None, max_bad_records=0,
            on_publish=None, on_step=None):
        """Consume ``stream`` until it closes (or ``max_steps`` training
        steps ran, or :attr:`preempted` is set — the SIGTERM grace path
        finishes the current micro-batch and returns). Publishes every
        ``publish_every_steps``; each step lands in the progress ledger.
        Returns the number of training steps executed."""
        self._stream = stream
        ing = StreamIngester(stream, self.data_feed,
                             max_bad_records=max_bad_records)
        for feed in ing.batches():
            if self.preempted.is_set():
                break
            if len(self.holdout) < self.holdout_batches:
                self.holdout.append(feed)
                continue
            v, = self.exe.run(self.train_prog, feed=feed,
                              fetch_list=[self.loss],
                              scope=self.scope, return_numpy=False)
            self.last_train_loss = float(np.asarray(v))
            self.step += 1
            self._write_progress()
            if on_step is not None:
                on_step(self)
            if self.publish_every_steps \
                    and self.step % self.publish_every_steps == 0:
                self.publish()
                if on_publish is not None:
                    on_publish(self)
            if max_steps is not None and self.step >= max_steps:
                break
        return self.step

    # -- preemption-aware shutdown -------------------------------------------
    def flush(self, grace_s=10.0, clock=None):
        """The SIGTERM grace path: one final synchronous publish (cursor
        included) bounded by a :class:`Deadline`, then lease release —
        so the whole-group restart resumes from HERE, not from the last
        periodic publish. Returns True when the flush landed whole."""
        deadline = Deadline(grace_s, clock=clock)
        ok = False
        writer = self.publish()
        if writer is not None:
            ok = writer.wait_until(deadline) and writer.error is None
        flight.record("preempt.flush", step=self.step, ok=ok,
                      remaining_s=round(deadline.remaining(), 3))
        if self.coordinator is not None:
            self.coordinator.release_all()
        return ok

    def close(self):
        """Join the in-flight checkpoint write (surfacing its error)."""
        if self._writer is not None:
            self._writer.wait()
            self._writer = None


def main(argv=None):
    """CLI for drills: tail-follow ``--data-dir`` and train, publishing
    into ``--ckpt-dir``. Prints a READY line (with the serve dir) once
    the model is built and exported, so a parent process can time its
    kill signals against the publish cadence.

    Multi-host mode (``--partitions N``): the host only tails stream
    files in partitions it holds a lease on (``streaming/coordinator``),
    heartbeating every step (and between empty tail polls, so an idle
    host keeps coordinating) and taking over expired/torn leases — a
    takeover adopts the dead host's published cursor from its
    ``--peer-dirs`` so reassigned partitions resume mid-file instead of
    from byte 0. Runs under ``distributed/launch.py --max_restarts``:
    host identity comes from ``PADDLE_TRAINER_ID``, and on restart the
    trainer resumes from its newest intact version's cursor (replay
    counted in ``replayed_rows``). SIGTERM = preemption notice: finish
    the micro-batch, flush checkpoint+cursor within ``--grace-s``,
    release leases, exit 0."""
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--data-dir", required=True)
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--publish-every", type=int, default=25)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--max-versions", type=int, default=4)
    p.add_argument("--sparse-dim", type=int, default=64)
    p.add_argument("--poll-interval", type=float, default=0.05)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--partitions", type=int, default=0,
                   help="stream partitions (0 = tail the whole dir)")
    p.add_argument("--host-id", default=None,
                   help="lease owner id (default: PADDLE_TRAINER_ID/pid)")
    p.add_argument("--num-hosts", type=int, default=None,
                   help="healthy-fleet share divisor (default: "
                        "PADDLE_TRAINERS or 1)")
    p.add_argument("--lease-ttl", type=float, default=2.0)
    p.add_argument("--grace-s", type=float, default=10.0,
                   help="SIGTERM flush budget (Deadline)")
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel degree over local devices")
    p.add_argument("--no-resume", action="store_true")
    p.add_argument("--peer-dirs", default="",
                   help="comma-separated peer ckpt dirs (cursor handover "
                        "source on partition takeover)")
    args = p.parse_args(argv)

    faults.maybe_install_from_env()
    flight.install()
    trainer = StreamingTrainer(
        args.ckpt_dir, batch_size=args.batch_size,
        publish_every_steps=args.publish_every,
        max_versions=args.max_versions,
        sparse_feature_dim=args.sparse_dim, seed=args.seed, dp=args.dp)
    host = args.host_id or os.environ.get("PADDLE_TRAINER_ID") \
        or str(os.getpid())
    num_hosts = args.num_hosts or int(os.environ.get("PADDLE_TRAINERS", 1))
    peer_dirs = [d for d in args.peer_dirs.split(",") if d]

    coord = None
    # Coordination must not starve with the batch loop: a host whose own
    # partitions run dry stops stepping, and a per-step beat alone would
    # then never renew its leases or reclaim a dead peer's. The stream's
    # idle sleep (between empty tail polls) drives the same beat, so an
    # idle survivor still takes over expired partitions.
    idle_hooks = {}

    def _idle_sleep(seconds):
        fn = idle_hooks.get("beat")
        if fn is not None:
            fn()
        time.sleep(seconds)

    if args.partitions > 0:
        from .coordinator import PartitionCoordinator

        share = -(-args.partitions // max(1, num_hosts))  # ceil
        coord = PartitionCoordinator(
            args.data_dir, host, args.partitions, ttl_s=args.lease_ttl,
            target_share=share)
        trainer.coordinator = coord
        coord.poll()
        stream = RecordStream(coord.source(),
                              poll_interval_s=args.poll_interval,
                              sleep=_idle_sleep)
    else:
        stream = RecordStream(args.data_dir,
                              poll_interval_s=args.poll_interval)

    def _on_sigterm(*_a):
        trainer.preempted.set()
        stream.interrupt()  # unblock an idle tail-follow immediately

    signal.signal(signal.SIGTERM, _on_sigterm)

    if not args.no_resume:
        try:
            trainer.resume(stream)
        except Exception as e:  # noqa: BLE001 — cold start beats dying
            flight.record("trainer.resume_failed", error=type(e).__name__)

    handover_done = set()

    def beat(tr):
        """Per-step coordination: renew leases, take over what expired,
        and adopt the dead owner's published cursor for gained ground."""
        gained = coord.poll()
        if not gained:
            return
        frag = coord.partition_cursor(
            peer_dirs + [args.ckpt_dir], gained)
        if frag["files"]:
            stream.seek(frag, merge=True)
        for d in peer_dirs:
            if d in handover_done:
                continue
            _v, extra = checkpoint.load_extra(d)
            cur = (extra or {}).get("cursor") or {}
            try:
                with open(os.path.join(d, _PROGRESS)) as f:
                    ledger = json.load(f)
            except (OSError, ValueError):
                continue
            if any(coord.partition_of(n) in gained
                   for n in cur.get("files", {})):
                # the dead host had read past its published cursor; the
                # takeover re-reads those rows — count them as replay
                tr.replayed_rows += max(
                    0, int(ledger.get("rows", 0))
                    - int(cur.get("rows", 0)))
                handover_done.add(d)

    if coord is not None:
        last_beat = [0.0]
        beat_every = max(0.05, args.lease_ttl / 4.0)

        def _idle_beat():
            now = time.monotonic()
            if now - last_beat[0] >= beat_every:
                last_beat[0] = now
                beat(trainer)

        idle_hooks["beat"] = _idle_beat

    print(TRAINER_READY_PREFIX + json.dumps(
        {"pid": os.getpid(), "serve_dir": trainer.serve_dir,
         "host": host, "resumed_version": trainer.resumed_version,
         "replayed_rows": trainer.replayed_rows}), flush=True)
    t0 = time.monotonic()
    steps = trainer.run(stream, max_steps=args.steps,
                        on_step=beat if coord is not None else None)
    owned_at_exit = sorted(coord.owned) if coord else None
    if trainer.preempted.is_set():
        trainer.flush(grace_s=args.grace_s)
    elif coord is not None:
        coord.release_all()
    trainer.close()
    flight.maybe_dump(reason="trainer-exit")
    print(json.dumps({
        "steps": steps, "publishes": trainer.publishes,
        "publish_failures": trainer.publish_failures,
        "eval_loss": trainer.last_eval_loss,
        "rows_per_sec": stream.rows_per_sec(),
        "rows_total": stream.rows_total,
        "host": host, "preempted": trainer.preempted.is_set(),
        "resumed_version": trainer.resumed_version,
        "replayed_rows": trainer.replayed_rows,
        "partitions_owned": owned_at_exit,
        "reassigned": coord.reassigned if coord else 0,
        "elapsed_s": round(time.monotonic() - t0, 3)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

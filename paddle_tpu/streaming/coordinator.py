"""Partition coordinator: TTL-leased ownership of stream partitions.

Reference: pslib/Downpour splits the click-log firehose across trainer
hosts by file, with the fleet controller reassigning a dead worker's
shard; ``checkpoint_notify`` is the cross-host coordination primitive
(SURVEY §C). Here the shared medium IS the stream directory: every
recordio file hashes to one of ``num_partitions`` partitions, and a
host may only tail files in partitions it holds a live *lease* on.

Leases are JSON files under ``<data_dir>/.leases/partition-<k>.lease``
with a wall-clock expiry. The protocol is deliberately boring:

* **acquire** — atomic tmp+``os.replace`` of a claim carrying a bumped
  ``epoch``, then read-back verify (last writer wins; the loser sees a
  foreign owner and walks away). No fcntl, works on any shared filesystem.
* **renew** (fault site ``lease.renew``) — heartbeat rewrite pushing
  ``expires`` forward. A renewal first re-reads the lease: if the disk
  copy is not ours-at-our-epoch, we LOST the lease (expired + reclaimed
  while we stalled) and must stop reading that partition — ownership is
  dropped loudly (``lease.lost`` flight event), never assumed.
* **expiry / torn leases** — a lease past ``expires``, or one that does
  not parse (a torn write from a dying host), is *reclaimed, not
  trusted*: any survivor may claim it with ``epoch + 1``. Reclaiming a
  foreign lease is counted (``reassigned``) and flight-recorded
  (``lease.reassign``) — host loss must be reconstructible from the dump.

``target_share`` bounds greed during normal operation (N healthy hosts
split the partitions evenly) but never blocks takeover: expired and torn
leases are claimed past the share, because a dead host's partitions have
no one else to go to.

The wall clock (``time.time``) is the coordination clock — leases cross
process boundaries, so a monotonic per-process clock cannot order them.
Tests inject a shared fake ``clock`` into every coordinator instead.
"""

import fnmatch
import json
import os
import time
import zlib

from ..obs import flight
from ..reliability import faults
from .stream import REGISTRY

__all__ = ["PartitionCoordinator", "partition_of"]


def partition_of(name, num_partitions):
    """Stable file->partition hash (basename only, so every host agrees
    regardless of mount point)."""
    base = os.path.basename(name)
    return zlib.crc32(base.encode("utf-8")) % int(num_partitions)


class PartitionCoordinator:
    """Lease-based ownership of stream-directory partitions for ONE host.

    Drive it with ``poll()`` (renew owned leases, then claim whatever is
    claimable) on the trainer's cadence; ``source()`` returns the file
    lister a :class:`~.stream.RecordStream` consumes, filtered live to
    the partitions currently owned — losing a lease stops the tail mid-
    stream, gaining one starts it."""

    def __init__(self, data_dir, host, num_partitions, ttl_s=5.0,
                 target_share=None, clock=None, registry=None,
                 lease_dir=None):
        self.data_dir = data_dir
        self.host = str(host)
        self.num_partitions = int(num_partitions)
        self.ttl_s = float(ttl_s)
        self.target_share = target_share
        self._clock = clock or time.time
        self.lease_dir = lease_dir or os.path.join(data_dir, ".leases")
        self.owned = set()
        self.epochs = {}          # partition -> epoch we hold it at
        self.reassigned = 0       # foreign expired/torn leases taken over
        self.lost = 0             # leases we held that got reclaimed
        self.renew_failures = 0
        reg = registry if registry is not None else REGISTRY
        self.registry = reg
        reg.gauge("paddle_tpu_stream_partitions_owned",
                  "stream partitions this host holds a live lease on",
                  fn=lambda: len(self.owned))

    # -- lease file plumbing -------------------------------------------------
    def _lease_path(self, k):
        return os.path.join(self.lease_dir, "partition-%d.lease" % k)

    def _read_lease(self, k):
        """(lease_dict_or_None, exists). A lease that exists but does not
        parse is TORN — reported as ``(None, True)`` and treated as
        reclaimable, never trusted."""
        try:
            with open(self._lease_path(k)) as f:
                raw = f.read()
        except OSError:
            return None, False
        try:
            lease = json.loads(raw)
            if not isinstance(lease, dict) or "owner" not in lease:
                return None, True
            return lease, True
        except ValueError:
            return None, True

    def _write_lease(self, k, epoch, torn=False):
        os.makedirs(self.lease_dir, exist_ok=True)
        now = self._clock()
        body = json.dumps({"partition": k, "owner": self.host,
                           "epoch": int(epoch), "renewed": now,
                           "expires": now + self.ttl_s})
        if torn:  # injected: model a host dying mid-rename
            body = body[:max(1, len(body) // 2)]
        tmp = self._lease_path(k) + (".claim-%s" % self.host)
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, self._lease_path(k))

    def _is_ours(self, k):
        lease, _ = self._read_lease(k)
        return (lease is not None and lease.get("owner") == self.host
                and lease.get("epoch") == self.epochs.get(k))

    # -- protocol ------------------------------------------------------------
    def acquire(self, k):
        """Claim partition ``k`` if its lease is absent, torn, expired, or
        a previous incarnation of *this* host's. Returns True on win."""
        if k in self.owned:
            return True
        lease, exists = self._read_lease(k)
        now = self._clock()
        if lease is not None and lease.get("owner") != self.host \
                and float(lease.get("expires", 0)) > now:
            return False  # live foreign lease: respect it
        foreign = (exists and (lease is None  # torn = foreign wreckage
                               or lease.get("owner") != self.host))
        epoch = (int(lease.get("epoch", 0)) + 1) if lease else 1
        self._write_lease(k, epoch)
        # read-back verify: last writer wins, the loser walks away
        cur, _ = self._read_lease(k)
        if not (cur is not None and cur.get("owner") == self.host
                and cur.get("epoch") == epoch):
            return False
        self.owned.add(k)
        self.epochs[k] = epoch
        if foreign:
            self.reassigned += 1
            age = (now - float(lease.get("expires", now))) if lease else None
            flight.record("lease.reassign", partition=k, host=self.host,
                          epoch=epoch, expired_for_s=age,
                          torn=lease is None)
        else:
            flight.record("lease.acquire", partition=k, host=self.host,
                          epoch=epoch)
        return True

    def renew(self):
        """Heartbeat every owned lease. Fault site ``lease.renew`` trips
        once per lease renewal: ``error`` = a missed heartbeat (the lease
        ages toward expiry — the takeover drill), ``corrupt`` = a torn
        renewal write (survivors must reclaim, not trust it)."""
        for k in sorted(self.owned):
            try:
                mode = faults.trip("lease.renew")
            except faults.InjectedFault:
                self.renew_failures += 1
                continue  # missed heartbeat; expiry clock keeps running
            if not self._is_ours(k):
                # expired + reclaimed while we stalled: ownership is gone,
                # stop reading the partition NOW (split-brain guard)
                self.owned.discard(k)
                self.epochs.pop(k, None)
                self.lost += 1
                flight.record("lease.lost", partition=k, host=self.host)
                continue
            self._write_lease(k, self.epochs[k], torn=(mode == "corrupt"))

    def poll(self):
        """One coordination beat: renew what we hold, claim what is
        claimable (bounded by ``target_share`` for healthy leases, never
        for expired/torn takeovers). Returns newly gained partitions."""
        self.renew()
        gained = set()
        for k in range(self.num_partitions):
            if k in self.owned:
                continue
            lease, exists = self._read_lease(k)
            now = self._clock()
            live_foreign = (lease is not None
                            and lease.get("owner") != self.host
                            and float(lease.get("expires", 0)) > now)
            if live_foreign:
                continue
            takeover = exists and (lease is None
                                   or lease.get("owner") != self.host)
            if (not takeover and self.target_share is not None
                    and len(self.owned) >= int(self.target_share)):
                continue  # healthy fleet: leave unclaimed ground to peers
            if self.acquire(k):
                gained.add(k)
        return gained

    def release(self, k):
        """Hand a partition back (preemption-aware shutdown): the lease
        file is removed so a peer claims it without waiting out the TTL."""
        if k not in self.owned:
            return
        if self._is_ours(k):
            try:
                os.unlink(self._lease_path(k))
            except OSError:
                pass
        self.owned.discard(k)
        self.epochs.pop(k, None)
        flight.record("lease.release", partition=k, host=self.host)

    def release_all(self):
        for k in sorted(self.owned):
            self.release(k)

    # -- stream integration --------------------------------------------------
    def partition_of(self, name):
        return partition_of(name, self.num_partitions)

    def source(self, pattern="*.recordio"):
        """A live file lister for :class:`~.stream.RecordStream`: only
        files in currently-owned partitions. Re-evaluated every poll, so
        lease gain/loss changes what the stream tails mid-run."""
        def _list():
            try:
                names = os.listdir(self.data_dir)
            except OSError:
                return []
            return [os.path.join(self.data_dir, n) for n in names
                    if fnmatch.fnmatch(n, pattern)
                    and self.partition_of(n) in self.owned]
        return _list

    def partition_cursor(self, ckpt_dirs, partitions):
        """Cross-host cursor handover: scan each publish dir's newest
        intact version for cursor entries whose file belongs to
        ``partitions``, keeping the furthest offset per file. Returns a
        cursor fragment for ``RecordStream.seek(..., merge=True)`` plus
        the max ``rows`` count seen (the dead host's accounted progress,
        for replay bookkeeping)."""
        from .. import checkpoint

        want = set(partitions)
        files, rows = {}, 0
        for d in ckpt_dirs:
            _v, extra = checkpoint.load_extra(d)
            cur = (extra or {}).get("cursor")
            if not cur:
                continue
            for name, ent in cur.get("files", {}).items():
                if self.partition_of(name) not in want:
                    continue
                best = files.get(name)
                if best is None or int(ent.get("offset", 0)) \
                        > int(best.get("offset", 0)):
                    files[name] = dict(ent)
            rows = max(rows, int(cur.get("rows", 0)))
        return {"files": files, "rows": rows}

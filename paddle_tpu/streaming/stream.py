"""Tail-follow recordio ingest: the stream side of continuous learning.

Reference: ``paddle/fluid/recordio/`` chunk format +
``async_executor.cc`` file-fed workers. The native reader
(``native_src/recordio.cc``) rescans byte-at-a-time on any framing
mismatch — correct for sealed files, wrong for a file still being
written, where a half-landed trailing chunk is NOT corruption but
"bytes in flight". This pure-Python tail parser keeps the same wire
format and corruption semantics (CRC32-verified chunks, skip-and-rescan
on damage) but treats an incomplete trailing chunk as *pending*: the
byte offset is saved and the next poll resumes exactly there.

Wire format (little-endian, ``recordio.cc``)::

    [magic u32 = 0x7061646c][num_records u32][payload_len u32][crc32 u32]
    payload: ([len u32][record bytes]) * num_records

Rotation contract (standard log rotation): files are named so
lexicographic order is write order (``part-00000``, ``part-00001``, ...)
and a file is immutable once a newer file exists. A partial tail on a
rotated-away file is therefore a torn write, counted and skipped; on the
newest file it is awaited.

Fault sites (``reliability/faults.py`` grammar):
  * ``stream.tail`` — trips once per poll cycle: ``error`` raises out of
    the iterator (a dying tailer), ``hang`` stalls the poll, ``corrupt``
    damages the first record delivered by that poll (a torn tail read).
  * ``recordio.read`` — trips once per record, same site the batch
    ``AsyncExecutor.run`` path drills: ``corrupt`` truncates the record
    so the bounded ``max_bad_records`` skip downstream can be exercised.
"""

import fnmatch
import os
import struct
import threading
import time
import warnings
import zlib
from collections import deque

from ..obs import registry as obs_registry
from ..reliability import faults

__all__ = ["RecordStream", "StreamIngester", "TailReader", "REGISTRY",
           "encode_chunk", "write_records"]

_MAGIC = 0x7061646C
_MAGIC_BYTES = struct.pack("<I", _MAGIC)
_HEADER = struct.Struct("<IIII")  # magic, num_records, payload_len, crc32
# framing guard: a corrupted payload_len must not make the tailer wait
# forever for bytes that will never come
_MAX_PAYLOAD = 1 << 26

# the streaming plane's metric registry (scrape via
# ``streaming.REGISTRY.prometheus_text()``); per-stream gauges register
# here unless a stream is given its own Registry
REGISTRY = obs_registry.Registry()


def encode_chunk(records):
    """One complete chunk (native wire format) holding ``records``."""
    payload = b"".join(struct.pack("<I", len(r)) + bytes(r)
                       for r in records)
    return _HEADER.pack(_MAGIC, len(records), len(payload),
                        zlib.crc32(payload) & 0xFFFFFFFF) + payload


def write_records(path, records, mode="ab"):
    """Append one chunk of ``records`` to ``path`` — the pure-Python
    counterpart of ``native.RecordIOWriter`` (no g++ toolchain needed),
    byte-compatible with the native reader. Returns bytes written."""
    chunk = encode_chunk(list(records))
    with open(path, mode) as f:
        f.write(chunk)
        f.flush()
    return len(chunk)


class TailReader:
    """Incremental chunk parser over ONE growing recordio file.

    ``poll(final=False)`` parses every complete chunk that has landed
    since the last call and returns ``(records, pending)`` — ``pending``
    means a partial trailing chunk is waiting for more bytes. With
    ``final=True`` (file rotated away / producer closed) a partial tail
    is a torn write: counted in ``bad_chunks`` and skipped."""

    def __init__(self, path):
        self.path = path
        self.offset = 0
        self.records_read = 0
        self.bad_chunks = 0
        self.done = False
        # (end_offset, n_records) per chunk parsed by the LAST poll —
        # the provenance RecordStream's durable cursor advances on
        self.last_chunks = []

    def poll(self, final=False):
        out = []
        self.last_chunks = []
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return out, False  # rotated away before we ever opened it
        if size <= self.offset:
            return out, False
        with open(self.path, "rb") as f:
            pending = self._scan(f, size, out, final)
        return out, pending

    def _scan(self, f, size, out, final):
        while self.offset < size:
            f.seek(self.offset)
            header = f.read(_HEADER.size)
            if len(header) < _HEADER.size:
                if final:
                    self.bad_chunks += 1
                    self.offset = size
                    return False
                return True  # header still landing
            magic, nrec, plen, crc = _HEADER.unpack(header)
            if magic != _MAGIC or plen > _MAX_PAYLOAD:
                self.offset += 1
                self._rescan(f, size)
                continue
            payload = f.read(plen)
            if len(payload) < plen:
                if final:
                    self.bad_chunks += 1
                    self.offset = size
                    return False
                return True  # payload still landing
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                self.bad_chunks += 1
                self.offset += 1
                self._rescan(f, size)
                continue
            recs, ok = _parse_payload(payload, nrec)
            if not ok:
                self.bad_chunks += 1
                self.offset += 1
                self._rescan(f, size)
                continue
            out.extend(recs)
            self.records_read += len(recs)
            self.offset += _HEADER.size + plen
            self.last_chunks.append((self.offset, len(recs)))
        return False

    def _rescan(self, f, size):
        """Native-reader recovery: after lost framing, advance to the
        next magic occurrence (buffered find, not byte-at-a-time)."""
        pos = self.offset
        overlap = len(_MAGIC_BYTES) - 1
        while pos < size:
            f.seek(pos)
            buf = f.read(1 << 16)
            if not buf:
                break
            hit = buf.find(_MAGIC_BYTES)
            if hit >= 0:
                self.offset = pos + hit
                return
            pos += max(len(buf) - overlap, 1)
        self.offset = size


def _parse_payload(payload, nrec):
    recs = []
    off = 0
    n = len(payload)
    while off + 4 <= n and len(recs) < nrec:
        (ln,) = struct.unpack_from("<I", payload, off)
        off += 4
        if off + ln > n:
            return recs, False
        recs.append(payload[off:off + ln])
        off += ln
    return recs, (len(recs) == nrec and off == n)


class RecordStream:
    """Tail-follow iterator over a growing, rotating recordio file SET.

    ``source`` is a directory (files matching ``pattern``, sorted
    lexicographically = rotation order) or a zero-arg callable returning
    the current file list. ``records()`` yields record bytes forever,
    sleeping ``poll_interval_s`` between empty polls, until ``close()``
    is called AND every file has drained. The producer calls ``close()``
    when it will append no more.

    ``rows_per_sec()`` is a sliding-window ingest rate, exported as the
    fn-backed gauge ``paddle_tpu_stream_ingest_rows_per_sec`` on
    ``registry`` (default: the module :data:`REGISTRY`).

    ``cursor()``/``seek()`` make the read position durable: a cursor is
    a plain dict (per-file byte offsets + done flags + the cumulative
    row count) that a trainer persists inside every published checkpoint
    version, so a restarted host resumes the tail-follow exactly where
    the last *published* state left off — at-least-once, with replay
    bounded by the publish cadence."""

    def __init__(self, source, pattern="*.recordio", poll_interval_s=0.05,
                 registry=None, clock=None, sleep=None):
        self._source = source
        self.pattern = pattern
        self.poll_interval_s = float(poll_interval_s)
        self._clock = clock or time.monotonic
        self._sleep = sleep or time.sleep
        self._readers = {}
        self._listed = []
        self._seek = {}       # basename -> {"offset": int, "done": bool}
        self._base_rows = 0   # rows accounted by an adopted cursor
        self._delivered = 0   # records handed to the consumer
        # the durable cursor: per-file positions at the last *delivered*
        # chunk boundary — never ahead of what the consumer has seen, so
        # resuming from it is at-least-once (replay <= one chunk + the
        # polls in flight), never at-most-once
        self._safe = {}
        self._safe_rows = 0
        self._closed = threading.Event()
        self._interrupted = threading.Event()
        self._window = deque(maxlen=64)  # (t, records_total) checkpoints
        reg = registry if registry is not None else REGISTRY
        self.registry = reg
        self._c_records = reg.counter(
            "paddle_tpu_stream_records_total",
            "records delivered by tail-follow streams")
        self._c_bad_chunks = reg.counter(
            "paddle_tpu_stream_bad_chunks_total",
            "CRC-failed / torn chunks skipped by tail-follow streams")
        reg.gauge("paddle_tpu_stream_ingest_rows_per_sec",
                  "sliding-window ingest throughput of the live stream",
                  fn=self.rows_per_sec)

    # -- producer-side signal ----------------------------------------------
    def close(self):
        """No more appends will happen: drain what landed, then stop."""
        self._closed.set()

    def interrupt(self):
        """Stop iterating NOW, without draining — the preemption path.
        The read position stays consistent: ``cursor()`` after an
        interrupt is a valid resume point."""
        self._interrupted.set()

    @property
    def closed(self):
        return self._closed.is_set()

    # -- stats --------------------------------------------------------------
    @property
    def records_read(self):
        return sum(r.records_read for r in self._readers.values())

    @property
    def bad_chunks(self):
        return sum(r.bad_chunks for r in self._readers.values())

    @property
    def rows_total(self):
        """Rows DELIVERED to the consumer over the stream's whole life,
        including rows the resumed cursor already accounted for (parsed-
        but-undelivered rows are excluded — they will come again)."""
        return self._base_rows + self._delivered

    def rows_per_sec(self):
        w = self._window
        if len(w) < 2:
            return 0.0
        dt = w[-1][0] - w[0][0]
        return (w[-1][1] - w[0][1]) / dt if dt > 0 else 0.0

    # -- durable position ----------------------------------------------------
    def cursor(self):
        """The stream's durable resume point: per-file byte offsets (keyed
        by basename, so checkpoint dirs stay relocatable), each file's
        rotation state (``done`` = sealed and fully drained), and the row
        count at that point. The position is the last chunk boundary
        whose records were all DELIVERED — parsed-but-undelivered bytes
        sit past it, so a resume re-reads them (at-least-once) instead of
        skipping them (at-most-once). JSON-serializable; feed it to
        :meth:`seek` on a fresh stream over the same directory."""
        files = {n: dict(e) for n, e in self._safe.items()}
        for name, ent in self._seek.items():  # adopted but not yet opened
            files.setdefault(name, dict(ent))
        return {"rows": self._base_rows + self._safe_rows, "files": files}

    def seek(self, cursor, merge=False):
        """Restore a :meth:`cursor`. Plain ``seek`` (before iteration
        starts) positions every file and adopts the cursor's row count;
        ``merge=True`` folds in positions for *additional* files mid-run
        — the partition-handover path, where a survivor adopts a dead
        host's published offsets instead of re-reading its partitions
        from byte 0. Unknown files in the cursor are kept pending and
        applied if/when they appear in the listing."""
        files = cursor.get("files", {})
        if not merge:
            if self._readers:
                raise RuntimeError(
                    "seek() must run before iteration starts; use "
                    "merge=True to adopt positions mid-run")
            self._seek = {n: dict(e) for n, e in files.items()}
            self._safe = {n: dict(e) for n, e in files.items()}
            self._base_rows = int(cursor.get("rows", 0))
            return self
        for name, ent in files.items():
            self._seek[name] = dict(ent)
            cur = self._safe.get(name)
            if cur is None or int(ent.get("offset", 0)) \
                    > int(cur.get("offset", 0)):
                self._safe[name] = dict(ent)
            for p, r in self._readers.items():
                if os.path.basename(p) == name:
                    if int(ent.get("offset", 0)) > r.offset:
                        r.offset = int(ent.get("offset", 0))
                    r.done = r.done or bool(ent.get("done", False))
        return self

    # -- iteration ----------------------------------------------------------
    def _list_files(self):
        if callable(self._source):
            return sorted(self._source())
        try:
            names = os.listdir(self._source)
        except OSError:
            return []
        return sorted(os.path.join(self._source, n) for n in names
                      if fnmatch.fnmatch(n, self.pattern))

    def _poll_once(self):
        # fault site: a dying ('error'), stalling ('hang') or torn-read
        # ('corrupt') tail-follow poll
        mode = faults.trip("stream.tail")
        listed = self._list_files()
        self._listed = listed
        for p in listed:
            if p not in self._readers:
                r = TailReader(p)
                ent = self._seek.get(os.path.basename(p))
                if ent:  # resume / partition handover: adopt the offset
                    r.offset = int(ent.get("offset", 0))
                    r.done = bool(ent.get("done", False))
                self._readers[p] = r
        out = []  # [(record, provenance)] — provenance on the LAST record
        # of each chunk: (basename, chunk_end_offset, file_drained)
        prev_bad = self.bad_chunks
        # only the CURRENT listing is polled: a partition-filtered source
        # that stops listing a file (lease lost) stops being read here
        for i, p in enumerate(listed):
            r = self._readers[p]
            if r.done:
                continue
            # rotation contract: a file is sealed once a newer one exists
            final = (i < len(listed) - 1) or self._closed.is_set()
            recs, pending = r.poll(final=final)
            if final and not pending:
                r.done = True
            base = os.path.basename(p)
            if not recs:
                # nothing to deliver for this file: its whole parse state
                # (skipped bad tails, the done flag) is already safe
                if r.last_chunks or r.done:
                    self._safe[base] = {"offset": r.offset,
                                        "done": bool(r.done)}
                continue
            idx = 0
            for end_off, nrec in r.last_chunks:
                for j in range(nrec):
                    prov = None
                    if j == nrec - 1:
                        prov = (base, end_off,
                                bool(r.done) and end_off >= r.offset)
                    out.append((recs[idx], prov))
                    idx += 1
            # bad-chunk rescans can reorder bookkeeping; never drop data
            while idx < len(recs):
                out.append((recs[idx], None))
                idx += 1
        new_bad = self.bad_chunks - prev_bad
        if new_bad:
            self._c_bad_chunks.inc(new_bad)
        if out:
            self._c_records.inc(len(out))
            self._window.append((self._clock(), self.records_read))
        if mode == "corrupt" and out:
            out[0] = (faults.corrupt_bytes(out[0][0]), out[0][1])
        return out

    def records(self):
        """Yield record bytes until closed and fully drained (or
        interrupted — then stop immediately, position preserved)."""
        while True:
            if self._interrupted.is_set():
                return
            got = self._poll_once()
            for rec, prov in got:
                # same per-record site AsyncExecutor.run drills on the
                # batch path: 'corrupt' truncates the record so the
                # bounded max_bad_records skip can be exercised
                if faults.trip("recordio.read") == "corrupt":
                    rec = faults.corrupt_bytes(rec)
                yield rec
                # the record is out the door: chunk boundaries it
                # completes become part of the durable cursor
                self._delivered += 1
                if prov is not None:
                    base, end_off, drained = prov
                    self._safe[base] = {"offset": end_off,
                                        "done": drained}
                    self._safe_rows = self._delivered
            if got:
                continue
            if self._closed.is_set():
                if all(self._readers[p].done for p in self._listed
                       if p in self._readers):
                    return
                continue  # close raced a partial tail; next poll seals it
            self._sleep(self.poll_interval_s)

    def __iter__(self):
        return self.records()


class StreamIngester:
    """Record stream -> dense ``DataFeedDesc`` batches for training.

    ``max_bad_records`` mirrors ``AsyncExecutor.run``: records whose size
    does not match the schema are skipped and counted up to this bound
    (0 = fail fast, ``None`` = unbounded, counted + warned). Partial
    final batches are dropped (fixed-shape batch convention)."""

    def __init__(self, stream, data_feed, max_bad_records=0):
        self.stream = stream
        self.data_feed = data_feed
        self.max_bad_records = max_bad_records
        self.bad_records = 0
        self._c_bad = stream.registry.counter(
            "paddle_tpu_stream_bad_records_total",
            "schema-size-mismatched records skipped by ingesters")

    def batches(self):
        bs = self.data_feed.batch_size
        want = self.data_feed.sample_nbytes
        batch = []
        for rec in self.stream.records():
            if len(rec) != want:
                self.bad_records += 1
                self._c_bad.inc()
                if (self.max_bad_records is not None
                        and self.bad_records > self.max_bad_records):
                    raise ValueError(
                        "StreamIngester: %d malformed record(s) (got %d "
                        "bytes, schema says %d) exceeds max_bad_records=%d"
                        % (self.bad_records, len(rec), want,
                           self.max_bad_records))
                continue
            batch.append(rec)
            if len(batch) == bs:
                yield self.data_feed.parse_batch(batch)
                batch = []
        if self.bad_records:
            warnings.warn(
                "StreamIngester: skipped %d malformed record(s) "
                "(max_bad_records=%s)"
                % (self.bad_records, self.max_bad_records),
                RuntimeWarning, stacklevel=2)

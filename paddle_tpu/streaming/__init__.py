"""``paddle_tpu.streaming`` — continuous learning: tail-follow ingest ->
long-running trainer -> versioned publish -> live serving hot-swap.

The reference's identity is file-fed async training at ad scale
(``paddle/fluid/framework/async_executor.cc``, pslib/Downpour): log
collectors append click records to a growing file set, trainers tail it
forever, and fresh models ship to the serving fleet without a restart.
This package is that loop, TPU-native:

  * :mod:`~paddle_tpu.streaming.stream` — :class:`RecordStream`, a
    tail-follow reader over a growing/rotating recordio file set (partial
    trailing chunks resume cleanly; corruption is CRC-detected and
    skipped), plus :class:`StreamIngester` batching into ``DataFeedDesc``
    feeds.
  * :mod:`~paddle_tpu.streaming.trainer` — :class:`StreamingTrainer`, a
    DeepFM trainer consuming the stream and periodically publishing
    CRC-verified versioned checkpoints (snapshot-then-async-write, atomic
    ``latest`` marker last) without blocking the training loop.
  * :mod:`~paddle_tpu.streaming.publisher` — :class:`ModelPublisher`, the
    model-swap plane: detects new versions, stages a CRC-verified load,
    and hot-swaps a live ``ServingEngine`` (or a whole router fleet via
    the ``reload`` RPC verb) between micro-batches — in-flight requests
    finish on the old weights, zero drops; corrupt versions fall back to
    the previous intact one behind a circuit breaker. Its fleet form,
    :class:`FleetPublisher`, drives N targets through a two-phase swap
    (prepare everywhere — any failure aborts the round — then commit
    per-target on a retry policy; stragglers are quarantined loudly via
    the ``fleet_version_skew`` gauge).
  * :mod:`~paddle_tpu.streaming.coordinator` —
    :class:`PartitionCoordinator`, multi-host ingest: stream-directory
    partitions owned through TTL lease files (heartbeat-renewed,
    crash-reclaimed), with exactly-once-resume cursors riding each
    published checkpoint so a restarted or adopting host replays a
    bounded, *counted* tail instead of losing or duplicating rows
    silently.

Quickstart (in-process; see README "Streaming training" for the
multi-process router form)::

    from paddle_tpu import streaming

    stream = streaming.RecordStream(data_dir)           # tail-follows
    trainer = streaming.StreamingTrainer(ckpt_dir, publish_every_steps=50)
    eng = serving.ServingEngine(trainer.serve_dir, num_replicas=2)
    pub = streaming.ModelPublisher(ckpt_dir, eng)
    pub.start()                                         # watcher thread
    trainer.run(stream, max_steps=500)                  # train + publish
    print(streaming.REGISTRY.prometheus_text())         # ingest/swap gauges
"""

from .stream import (REGISTRY, RecordStream, StreamIngester,  # noqa: F401
                     TailReader, encode_chunk, write_records)
from .trainer import StreamingTrainer, synthesize_stream_files  # noqa: F401
from .publisher import (FleetPublisher, ModelPublisher,  # noqa: F401
                        RouterTarget)
from .coordinator import PartitionCoordinator, partition_of  # noqa: F401

__all__ = ["RecordStream", "StreamIngester", "TailReader", "REGISTRY",
           "encode_chunk", "write_records", "StreamingTrainer",
           "synthesize_stream_files", "ModelPublisher", "FleetPublisher",
           "RouterTarget", "PartitionCoordinator", "partition_of"]

"""Model-swap plane: watch the checkpoint dir, hot-swap live serving.

Reference: pslib/Downpour's table server shipping fresh parameters to the
serving fleet without restarts. Here the trainer publishes versioned
checkpoints (``checkpoint_<n>`` + atomic ``latest`` marker) and this
watcher detects them and drives the ``reload`` verb — on an in-process
:class:`~paddle_tpu.serving.ServingEngine` directly, or across a whole
router fleet through :class:`RouterTarget`.

Failure story: every staged load is CRC-verified; a corrupt newest
version is recorded (``publish.bad_version`` flight event), counted
against a :class:`~paddle_tpu.reliability.policy.CircuitBreaker` —
repeated bad publishes OPEN it and the publisher stops hammering the
checkpoint dir until the reset timeout — and serving falls back to the
previous intact version. The served version is *pinned*
(``checkpoint.pin_version``) so the trainer's retention GC can never
delete the weights a serving process is using.

Staleness: ``serve-version lag`` (how many publishes behind the fleet
is) and ``staleness seconds`` (publish-to-swap latency, sampled per
swap) export as gauges on the streaming registry.
"""

import os
import threading
import time
import warnings

from .. import checkpoint
from ..obs import flight
from ..reliability.policy import CircuitBreaker
from .stream import REGISTRY

__all__ = ["ModelPublisher", "RouterTarget"]


class RouterTarget:
    """Adapts a :class:`~paddle_tpu.serving.RouterClient` to the
    publisher's target protocol (``reload(ckpt_dir, version=) -> int``):
    the swap broadcasts to every worker in the fleet."""

    def __init__(self, client):
        self.client = client

    def reload(self, ckpt_dir, version=None):
        return self.client.reload(ckpt_dir, version=version)["version"]


class ModelPublisher:
    """Poll ``ckpt_dir`` for fresh versions and hot-swap ``target``.

    ``target`` is anything with ``reload(ckpt_dir, version=) -> version``
    — a ``ServingEngine`` fits directly; wrap a ``RouterClient`` in
    :class:`RouterTarget`. Drive it with ``poll_once()`` (deterministic,
    test/fake-clock friendly) or ``start()``/``stop()`` (background
    watcher thread at ``poll_interval_s``)."""

    def __init__(self, ckpt_dir, target, poll_interval_s=0.2,
                 breaker=None, registry=None, clock=None, sleep=None,
                 pin_owner=None, pin=True):
        self.ckpt_dir = ckpt_dir
        self.target = target
        self.poll_interval_s = float(poll_interval_s)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, reset_timeout_s=10 * poll_interval_s,
            clock=clock, name="publisher")
        self._clock = clock or time.monotonic
        self._sleep = sleep or time.sleep
        self.pin = bool(pin)
        self.pin_owner = pin_owner or ("serving-%d" % os.getpid())
        self.published_version = None
        self.served_version = None
        self.swap_count = 0
        self.bad_publishes = 0
        self.staleness_samples = []  # publish-to-swap latency, seconds
        self._staleness_s = 0.0
        self._last_versions = []
        self._stop = threading.Event()
        self._thread = None
        reg = registry if registry is not None else REGISTRY
        self.registry = reg
        self._c_swaps = reg.counter(
            "paddle_tpu_stream_swaps_total",
            "successful live model hot-swaps")
        self._c_bad = reg.counter(
            "paddle_tpu_stream_bad_publishes_total",
            "published versions that failed the staged CRC load/swap")
        reg.gauge("paddle_tpu_stream_published_version",
                  "newest complete checkpoint version in the publish dir",
                  fn=lambda: -1 if self.published_version is None
                  else self.published_version)
        reg.gauge("paddle_tpu_stream_served_version",
                  "checkpoint version the serving tier runs on",
                  fn=lambda: -1 if self.served_version is None
                  else self.served_version)
        reg.gauge("paddle_tpu_stream_serve_version_lag",
                  "publishes the serving tier is behind the trainer",
                  fn=self.version_lag)
        reg.gauge("paddle_tpu_stream_staleness_seconds",
                  "age of the newest unserved publish (0 when current)",
                  fn=lambda: self._staleness_s)

    # -- staleness -----------------------------------------------------------
    def version_lag(self):
        """How many complete publishes the serving tier is behind (0 =
        serving the newest; N = N fresher versions exist)."""
        vs = self._last_versions
        if not vs:
            return 0
        if self.served_version is None:
            return len(vs)
        try:
            return vs.index(self.served_version)
        except ValueError:
            return len(vs)  # served version already GC'd: fully stale

    def _publish_age_s(self, version):
        try:
            mt = os.path.getmtime(os.path.join(
                self.ckpt_dir, "checkpoint_%d" % version,
                checkpoint._MANIFEST))
            return max(0.0, time.time() - mt)
        except OSError:
            return 0.0

    # -- the watcher ---------------------------------------------------------
    def poll_once(self):
        """One detection + swap attempt. Returns the version swapped to,
        or None (nothing new / breaker open / nothing intact)."""
        versions = checkpoint.candidate_versions(self.ckpt_dir)
        self._last_versions = versions
        if not versions:
            return None
        self.published_version = versions[0]
        if self.served_version == versions[0]:
            self._staleness_s = 0.0
            return None
        self._staleness_s = self._publish_age_s(versions[0])
        if not self.breaker.allow():
            return None  # repeated bad publishes: stop hammering
        swapped = None
        for v in versions:  # newest first; walk back past bad versions
            if self.served_version is not None \
                    and v == self.served_version:
                break  # nothing fresher is intact: keep serving current
            try:
                got = self.target.reload(self.ckpt_dir, version=v)
                swapped = v if got is None else got
                break
            except Exception as e:  # noqa: BLE001 — fall back, stay up
                self.bad_publishes += 1
                self._c_bad.inc()
                tripped = self.breaker.record_failure()
                flight.record("publish.bad_version", version=v,
                              error=type(e).__name__, tripped=tripped)
                warnings.warn(
                    "publisher: version %d failed staged load/swap (%s: "
                    "%s); falling back to the previous intact version"
                    % (v, type(e).__name__, e), RuntimeWarning)
        if swapped is None:
            return None
        if swapped == versions[0]:
            # only serving the NEWEST publish closes the breaker: a
            # fallback swap keeps the bad-publish streak alive
            self.breaker.record_success()
            self._staleness_s = 0.0
        prev = self.served_version
        self.served_version = swapped
        self.swap_count += 1
        self._c_swaps.inc()
        self.staleness_samples.append(self._publish_age_s(swapped))
        if self.pin:
            try:
                checkpoint.pin_version(self.ckpt_dir, swapped,
                                       owner=self.pin_owner)
            except FileNotFoundError:
                pass  # GC raced the swap; the version is gone from disk
            if prev is not None and prev != swapped:
                checkpoint.unpin_version(self.ckpt_dir, prev,
                                         owner=self.pin_owner)
        return swapped

    # -- background thread ---------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="paddle-tpu-publisher")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the watcher survives
                warnings.warn("publisher poll failed: %s: %s"
                              % (type(e).__name__, e), RuntimeWarning)
            self._sleep(self.poll_interval_s)

    def stop(self, unpin=True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if unpin and self.pin and self.served_version is not None:
            checkpoint.unpin_version(self.ckpt_dir, self.served_version,
                                     owner=self.pin_owner)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

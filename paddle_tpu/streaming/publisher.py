"""Model-swap plane: watch the checkpoint dir, hot-swap live serving.

Reference: pslib/Downpour's table server shipping fresh parameters to the
serving fleet without restarts. Here the trainer publishes versioned
checkpoints (``checkpoint_<n>`` + atomic ``latest`` marker) and this
watcher detects them and drives the ``reload`` verb — on an in-process
:class:`~paddle_tpu.serving.ServingEngine` directly, or across a whole
router fleet through :class:`RouterTarget`.

Failure story: every staged load is CRC-verified; a corrupt newest
version is recorded (``publish.bad_version`` flight event), counted
against a :class:`~paddle_tpu.reliability.policy.CircuitBreaker` —
repeated bad publishes OPEN it and the publisher stops hammering the
checkpoint dir until the reset timeout — and serving falls back to the
previous intact version. The served version is *pinned*
(``checkpoint.pin_version``) so the trainer's retention GC can never
delete the weights a serving process is using.

Staleness: ``serve-version lag`` (how many publishes behind the fleet
is) and ``staleness seconds`` (publish-to-swap latency, sampled per
swap) export as gauges on the streaming registry.

:class:`FleetPublisher` scales the same contract to N targets with a
two-phase swap (prepare everywhere, then commit per-target on a retry
policy) so a fleet either moves together or fails loudly — see its
docstring for the quarantine / ``fleet_version_skew`` story.
"""

import os
import threading
import time
import warnings

from .. import checkpoint
from ..obs import flight
from ..reliability.policy import CircuitBreaker, RetryError, RetryPolicy
from .stream import REGISTRY

__all__ = ["ModelPublisher", "FleetPublisher", "RouterTarget"]


class RouterTarget:
    """Adapts a :class:`~paddle_tpu.serving.RouterClient` to the
    publisher's target protocol (``reload(ckpt_dir, version=) -> int``
    plus the two-phase ``prepare``/``commit``/``abort`` verbs): each
    call broadcasts to every worker behind the router."""

    def __init__(self, client):
        self.client = client

    def reload(self, ckpt_dir, version=None):
        return self.client.reload(ckpt_dir, version=version)["version"]

    def prepare(self, ckpt_dir, version=None):
        return self.client.prepare(ckpt_dir, version=version)["version"]

    def commit(self, version=None):
        return self.client.commit(version=version)["version"]

    def abort(self):
        return self.client.abort()


class ModelPublisher:
    """Poll ``ckpt_dir`` for fresh versions and hot-swap ``target``.

    ``target`` is anything with ``reload(ckpt_dir, version=) -> version``
    — a ``ServingEngine`` fits directly; wrap a ``RouterClient`` in
    :class:`RouterTarget`. Drive it with ``poll_once()`` (deterministic,
    test/fake-clock friendly) or ``start()``/``stop()`` (background
    watcher thread at ``poll_interval_s``)."""

    def __init__(self, ckpt_dir, target, poll_interval_s=0.2,
                 breaker=None, registry=None, clock=None, sleep=None,
                 pin_owner=None, pin=True):
        self.ckpt_dir = ckpt_dir
        self.target = target
        self.poll_interval_s = float(poll_interval_s)
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, reset_timeout_s=10 * poll_interval_s,
            clock=clock, name="publisher")
        self._clock = clock or time.monotonic
        self._sleep = sleep or time.sleep
        self.pin = bool(pin)
        self.pin_owner = pin_owner or ("serving-%d" % os.getpid())
        self.published_version = None
        self.served_version = None
        self.swap_count = 0
        self.bad_publishes = 0
        self.staleness_samples = []  # publish-to-swap latency, seconds
        self._staleness_s = 0.0
        self._last_versions = []
        self._stop = threading.Event()
        self._thread = None
        reg = registry if registry is not None else REGISTRY
        self.registry = reg
        self._c_swaps = reg.counter(
            "paddle_tpu_stream_swaps_total",
            "successful live model hot-swaps")
        self._c_bad = reg.counter(
            "paddle_tpu_stream_bad_publishes_total",
            "published versions that failed the staged CRC load/swap")
        reg.gauge("paddle_tpu_stream_published_version",
                  "newest complete checkpoint version in the publish dir",
                  fn=lambda: -1 if self.published_version is None
                  else self.published_version)
        reg.gauge("paddle_tpu_stream_served_version",
                  "checkpoint version the serving tier runs on",
                  fn=lambda: -1 if self.served_version is None
                  else self.served_version)
        reg.gauge("paddle_tpu_stream_serve_version_lag",
                  "publishes the serving tier is behind the trainer",
                  fn=self.version_lag)
        reg.gauge("paddle_tpu_stream_staleness_seconds",
                  "age of the newest unserved publish (0 when current)",
                  fn=lambda: self._staleness_s)

    # -- staleness -----------------------------------------------------------
    def version_lag(self):
        """How many complete publishes the serving tier is behind (0 =
        serving the newest; N = N fresher versions exist)."""
        vs = self._last_versions
        if not vs:
            return 0
        if self.served_version is None:
            return len(vs)
        try:
            return vs.index(self.served_version)
        except ValueError:
            return len(vs)  # served version already GC'd: fully stale

    def _publish_age_s(self, version):
        try:
            mt = os.path.getmtime(os.path.join(
                self.ckpt_dir, "checkpoint_%d" % version,
                checkpoint._MANIFEST))
            return max(0.0, time.time() - mt)
        except OSError:
            return 0.0

    # -- the watcher ---------------------------------------------------------
    def poll_once(self):
        """One detection + swap attempt. Returns the version swapped to,
        or None (nothing new / breaker open / nothing intact)."""
        versions = checkpoint.candidate_versions(self.ckpt_dir)
        self._last_versions = versions
        if not versions:
            return None
        self.published_version = versions[0]
        if self.served_version == versions[0]:
            self._staleness_s = 0.0
            return None
        self._staleness_s = self._publish_age_s(versions[0])
        if not self.breaker.allow():
            return None  # repeated bad publishes: stop hammering
        swapped = None
        for v in versions:  # newest first; walk back past bad versions
            if self.served_version is not None \
                    and v == self.served_version:
                break  # nothing fresher is intact: keep serving current
            try:
                got = self.target.reload(self.ckpt_dir, version=v)
                swapped = v if got is None else got
                break
            except Exception as e:  # noqa: BLE001 — fall back, stay up
                self.bad_publishes += 1
                self._c_bad.inc()
                tripped = self.breaker.record_failure()
                flight.record("publish.bad_version", version=v,
                              error=type(e).__name__, tripped=tripped)
                warnings.warn(
                    "publisher: version %d failed staged load/swap (%s: "
                    "%s); falling back to the previous intact version"
                    % (v, type(e).__name__, e), RuntimeWarning)
        if swapped is None:
            return None
        if swapped == versions[0]:
            # only serving the NEWEST publish closes the breaker: a
            # fallback swap keeps the bad-publish streak alive
            self.breaker.record_success()
            self._staleness_s = 0.0
        prev = self.served_version
        self.served_version = swapped
        self.swap_count += 1
        self._c_swaps.inc()
        self.staleness_samples.append(self._publish_age_s(swapped))
        if self.pin:
            try:
                checkpoint.pin_version(self.ckpt_dir, swapped,
                                       owner=self.pin_owner)
            except FileNotFoundError:
                pass  # GC raced the swap; the version is gone from disk
            if prev is not None and prev != swapped:
                checkpoint.unpin_version(self.ckpt_dir, prev,
                                         owner=self.pin_owner)
        return swapped

    # -- background thread ---------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="paddle-tpu-publisher")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the watcher survives
                warnings.warn("publisher poll failed: %s: %s"
                              % (type(e).__name__, e), RuntimeWarning)
            self._sleep(self.poll_interval_s)

    def stop(self, unpin=False):
        """Stop the watcher thread. The served version's pin is kept:
        stopping the *publisher* does not stop the *serving process*,
        and unpinning while replicas still serve those weights lets the
        trainer's retention GC delete them out from under live traffic.
        Call :meth:`release` once serving shutdown (or supersession by
        a newer fleet version) is confirmed; ``unpin=True`` collapses
        the two for callers that have already shut serving down."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if unpin:
            self.release()

    def release(self):
        """Drop the retention pin on the served version. Only safe once
        no replica serves those weights any more — after a confirmed
        serving shutdown, or once the fleet converged on a newer
        version and this one is superseded."""
        if self.pin and self.served_version is not None:
            checkpoint.unpin_version(self.ckpt_dir, self.served_version,
                                     owner=self.pin_owner)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class FleetPublisher:
    """Two-phase fleet-wide model swap across N serving targets.

    Each target is anything with the two-phase verbs —
    ``prepare(ckpt_dir, version=) -> version`` (CRC-stage the weights
    without serving them), ``commit(version=) -> version`` (atomic
    swap, idempotent on the already-served version), ``abort()`` — so
    a :class:`~paddle_tpu.serving.ServingEngine` fits directly and a
    :class:`RouterTarget` spans a whole router's worker pool.

    The swap discipline:

    * **prepare** runs on *every* healthy target first. Any single
      failure aborts the round — every staged target gets ``abort()``,
      nothing swaps, the failure feeds the :class:`CircuitBreaker`, and
      the walk falls back to the next older intact version. A fleet
      never half-stages.
    * **commit** then runs per-target under a
      :class:`~paddle_tpu.reliability.policy.RetryPolicy` (``commit``
      is idempotent, so a lost ACK retries safely). A target that
      exhausts its budget is **quarantined**: a
      ``publish.partial_commit`` flight event fires, the
      ``paddle_tpu_stream_fleet_version_skew`` gauge goes positive, and
      the target is skipped until :meth:`readmit` — mixed fleets are
      loud, never silent.

    Retention: the fleet version is pinned; the previous pin is dropped
    only once **no** target (quarantined ones included) still serves
    it."""

    def __init__(self, ckpt_dir, targets, breaker=None, retry=None,
                 registry=None, clock=None, pin_owner=None, pin=True):
        self.ckpt_dir = ckpt_dir
        if isinstance(targets, dict):
            self.targets = dict(targets)
        else:
            self.targets = {"target-%d" % i: t
                            for i, t in enumerate(targets)}
        if not self.targets:
            raise ValueError("FleetPublisher needs at least one target")
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, reset_timeout_s=2.0, clock=clock,
            name="fleet-publisher")
        self.retry = retry or RetryPolicy(max_attempts=3,
                                          base_delay_s=0.05)
        self.pin = bool(pin)
        self.pin_owner = pin_owner or ("fleet-%d" % os.getpid())
        self.fleet_version = None           # version the fleet is on
        self.target_versions = {}           # name -> served version
        self.quarantined = set()            # names skipped until readmit
        self.swap_rounds = 0
        self.prepare_failures = 0
        self.partial_commits = 0
        self._pinned = set()
        reg = registry if registry is not None else REGISTRY
        self.registry = reg
        self._c_partial = reg.counter(
            "paddle_tpu_stream_partial_commits_total",
            "fleet swap rounds that left at least one target behind")
        reg.gauge("paddle_tpu_stream_fleet_version_skew",
                  "targets not serving the fleet version (0 = converged)",
                  fn=self.version_skew)
        reg.gauge("paddle_tpu_stream_fleet_version",
                  "checkpoint version the fleet last committed",
                  fn=lambda: -1 if self.fleet_version is None
                  else self.fleet_version)

    # -- health --------------------------------------------------------------
    def version_skew(self):
        """Targets not serving :attr:`fleet_version` — quarantined or
        never-committed targets count. 0 means the fleet converged."""
        if self.fleet_version is None:
            return 0
        return sum(1 for name in self.targets
                   if self.target_versions.get(name) != self.fleet_version)

    def readmit(self, name):
        """Lift a target's quarantine; the next :meth:`poll_once` tries
        to converge it onto the fleet version again."""
        self.quarantined.discard(name)

    # -- the two-phase round -------------------------------------------------
    def _active(self):
        return [n for n in self.targets if n not in self.quarantined]

    def _abort(self, name):
        # engines expose ``abort_swap`` (``abort`` would be ambiguous on
        # a serving surface); router targets expose ``abort``
        t = self.targets[name]
        fn = getattr(t, "abort_swap", None) or t.abort
        try:
            fn()
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass

    def _prepare_all(self, version, names):
        staged = []
        for name in names:
            try:
                self.targets[name].prepare(self.ckpt_dir, version=version)
                staged.append(name)
            except Exception as e:  # noqa: BLE001 — abort, stay up
                self.prepare_failures += 1
                for other in staged:
                    self._abort(other)
                tripped = self.breaker.record_failure()
                flight.record("publish.prepare_failed", version=version,
                              target=name, error=type(e).__name__,
                              staged=len(staged), tripped=tripped)
                warnings.warn(
                    "fleet publisher: target %r failed prepare of version "
                    "%d (%s: %s); round aborted, nothing swapped"
                    % (name, version, type(e).__name__, e), RuntimeWarning)
                return False
        return True

    def _commit_all(self, version, names):
        committed = []
        for name in names:
            try:
                self.retry.call(
                    lambda t=self.targets[name]: t.commit(version=version))
                self.target_versions[name] = version
                committed.append(name)
            except RetryError as e:
                self.quarantined.add(name)
                self.partial_commits += 1
                self._c_partial.inc()
                self._abort(name)
                flight.record("publish.partial_commit", version=version,
                              target=name, attempts=e.attempts,
                              error=type(e.last).__name__,
                              skew=self.version_skew() + 1)
                warnings.warn(
                    "fleet publisher: target %r exhausted %d commit "
                    "attempt(s) for version %d (%r); QUARANTINED — fleet "
                    "is version-skewed until it heals"
                    % (name, e.attempts, version, e.last), RuntimeWarning)
        return committed

    def _repin(self, version):
        if not self.pin:
            return
        if version not in self._pinned:
            try:
                checkpoint.pin_version(self.ckpt_dir, version,
                                       owner=self.pin_owner)
                self._pinned.add(version)
            except FileNotFoundError:
                pass  # GC raced the swap; the version is gone from disk
        # drop pins no target serves any more — a quarantined target
        # still serving an old version keeps that version's pin alive
        live = set(self.target_versions.values())
        for v in sorted(self._pinned - live - {version}):
            checkpoint.unpin_version(self.ckpt_dir, v,
                                     owner=self.pin_owner)
            self._pinned.discard(v)

    def poll_once(self):
        """One detection + two-phase swap round. Returns the version the
        fleet committed to, or None (nothing new / breaker open /
        nothing intact / round aborted)."""
        versions = checkpoint.candidate_versions(self.ckpt_dir)
        if not versions:
            return None
        names = self._active()
        if not names:
            return None  # whole fleet quarantined: nothing to drive
        stale = [n for n in names
                 if self.target_versions.get(n) != versions[0]]
        if not stale:
            return None
        if not self.breaker.allow():
            return None
        for v in versions:  # newest first; walk back past bad versions
            if self.fleet_version is not None and v < self.fleet_version:
                break  # never roll the fleet backwards
            todo = [n for n in names if self.target_versions.get(n) != v]
            if not todo:
                break
            if not self._prepare_all(v, todo):
                continue  # this version is bad somewhere: try an older
            committed = self._commit_all(v, todo)
            if not committed:
                return None
            self.fleet_version = v
            self.swap_rounds += 1
            if v == versions[0] and len(committed) == len(todo):
                self.breaker.record_success()
            flight.record("publish.fleet_commit", version=v,
                          committed=len(committed), skew=self.version_skew())
            self._repin(v)
            return v
        return None

    def release(self):
        """Drop every retention pin this publisher holds. Only safe once
        serving shutdown is confirmed fleet-wide."""
        for v in sorted(self._pinned):
            checkpoint.unpin_version(self.ckpt_dir, v,
                                     owner=self.pin_owner)
        self._pinned.clear()

"""Inference predictor stack.

Reference: ``paddle/fluid/inference/api/analysis_predictor.cc:183``
(AnalysisPredictor::Init) + ``:734`` (Run), configured by
``paddle_inference_api.h`` AnalysisConfig, exposed in Python as
``fluid.core.AnalysisConfig`` / ``create_paddle_predictor``.

TPU-native re-design: the analysis pass stack (IR optimization, fusion,
TensorRT/MKLDNN subgraphs, memory optimization) is subsumed by XLA
compilation of the whole pruned program — the predictor's job is model
loading, an isolated scope, a warm shape-keyed jit cache (the Executor's
program cache), and zero-copy device-resident feeds (jax.Array passthrough).
"""

import os

from . import io as io_mod
from .core.executor import Executor, Scope, scope_guard, XLAPlace

__all__ = ["AnalysisConfig", "Predictor", "create_paddle_predictor",
           "StableHLOPredictor", "load_stablehlo_predictor"]


class AnalysisConfig:
    """Parity shim for the reference AnalysisConfig.

    INERT KNOBS — read this before tuning: ``enable_use_gpu``,
    ``disable_gpu``, ``switch_ir_optim`` and ``enable_memory_optim`` are
    recorded but change NOTHING on TPU. The reference's analysis passes
    (IR fusion, TensorRT/MKLDNN subgraphs, memory reuse) are subsumed by
    XLA compiling the whole pruned program; execution always targets the
    XLA default device. Only the model paths act."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self._use_gpu = False
        self._mem_optim = True
        self._ir_optim = True

    # -- reference-API surface (no-op on TPU, XLA subsumes) -----------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_gpu = True  # accepted; execution targets the XLA device

    def disable_gpu(self):
        self._use_gpu = False

    def switch_ir_optim(self, x=True):
        self._ir_optim = bool(x)

    def enable_memory_optim(self, x=True):
        self._mem_optim = bool(x)

    def set_model(self, model_dir):
        self.model_dir = model_dir


class Predictor:
    """Loads a saved inference model into an isolated scope and serves
    ``run``/``predict`` with a warm compile cache.

    Ref ``analysis_predictor.cc``: Init loads + optimizes the program once;
    Run executes with feed/fetch binding. Here the first call per feed-shape
    compiles (XLA) and subsequent calls hit the Executor's program cache."""

    def __init__(self, config):
        if isinstance(config, str):
            config = AnalysisConfig(model_dir=config)
        self.config = config
        self._scope = Scope()
        self._exe = Executor(XLAPlace(0))
        model_dir = config.model_dir
        # combined-file form (ref SetModel(prog_file, params_file)): the
        # directory comes from the file paths, which must agree
        for fp in (config.prog_file, config.params_file):
            if fp is None:
                continue
            d = os.path.dirname(os.path.abspath(fp))
            if model_dir is None:
                model_dir = d
            elif os.path.abspath(model_dir) != d:
                raise ValueError(
                    "AnalysisConfig: %r is not inside model_dir %r"
                    % (fp, model_dir))
        if model_dir is None:
            raise ValueError("AnalysisConfig needs model_dir (the "
                             "save_inference_model output directory) or "
                             "prog_file/params_file paths")
        with scope_guard(self._scope):
            prog, feed_names, fetch_vars = io_mod.load_inference_model(
                model_dir, self._exe,
                model_filename=(os.path.basename(config.prog_file)
                                if config.prog_file else None),
                params_filename=(os.path.basename(config.params_file)
                                 if config.params_file else None))
        self._program = prog
        self.feed_names = list(feed_names)
        self._fetch_vars = fetch_vars
        self.fetch_names = [v.name for v in fetch_vars]

    def run(self, inputs, return_numpy=True):
        """``inputs``: dict name->array, or a list/tuple in feed order.
        Returns outputs in fetch order."""
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != len(self.feed_names):
                raise ValueError("expected %d inputs (%s), got %d"
                                 % (len(self.feed_names), self.feed_names,
                                    len(inputs)))
            feed = dict(zip(self.feed_names, inputs))
        else:
            feed = dict(inputs)
            missing = set(self.feed_names) - set(feed)
            if missing:
                raise ValueError("missing feeds: %s" % sorted(missing))
        # scope passed explicitly (not via the global scope_guard stack):
        # clones serving concurrently from other threads must not race on
        # process-global scope resolution. donate_state=False for the same
        # reason: donation would invalidate the scope's shared weight
        # arrays mid-call, a use-after-free when another clone reads them
        return self._exe.run(self._program, feed=feed,
                             fetch_list=self._fetch_vars,
                             scope=self._scope,
                             return_numpy=return_numpy,
                             donate_state=False)

    predict = run

    def clone(self):
        """A predictor sharing this one's weights (ref
        ``AnalysisPredictor::Clone``): same scope/program, fresh exe cache."""
        other = object.__new__(Predictor)
        other.config = self.config
        other._scope = self._scope
        other._exe = Executor(XLAPlace(0))
        other._program = self._program
        other.feed_names = list(self.feed_names)
        other._fetch_vars = self._fetch_vars
        other.fetch_names = list(self.fetch_names)
        return other

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return list(self.fetch_names)


def create_paddle_predictor(config):
    """Factory-name parity with the reference C-API."""
    return Predictor(config)


class StableHLOPredictor:
    """Serves from the serialized StableHLO artifact alone — no
    model-building Python, no symbolic program replay (ref parity:
    ``CreatePaddlePredictor`` runs from the serialized program+params,
    ``analysis_predictor.cc:734``). ``save_inference_model`` writes the
    artifact (``model.stablehlo.bin`` via jax.export) next to the params;
    this loader deserializes and executes it.

    Batch-size note: with a symbolic-batch export (manifest
    ``batch_mode: symbolic``) any batch works; a ``pinned-1`` export only
    accepts batch 1."""

    def __init__(self, dirname, params_filename=None):
        import json

        import jax.numpy as jnp
        import numpy as np
        from jax import export as jexport

        with open(os.path.join(dirname, "model.stablehlo.bin"), "rb") as f:
            self._exported = jexport.deserialize(f.read())
        with open(os.path.join(dirname, "stablehlo_manifest.json")) as f:
            man = json.load(f)
        self.feed_names = list(man["feed_names"])
        self.fetch_names = list(man["fetch_names"])
        self.batch_mode = man["batch_mode"]
        params = np.load(os.path.join(dirname,
                                      params_filename or "params.npz"),
                         allow_pickle=False)
        state_names = man["state_names"]
        missing = [n for n in state_names if n not in params]
        if missing:
            raise ValueError("params file lacks exported state vars %s"
                             % missing)
        self._state = {n: jnp.asarray(params[n]) for n in state_names}

    def run(self, inputs, return_numpy=True):
        import jax.numpy as jnp
        import numpy as np

        if isinstance(inputs, (list, tuple)):
            feed = dict(zip(self.feed_names, inputs))
        else:
            feed = dict(inputs)
        missing = set(self.feed_names) - set(feed)
        if missing:
            raise ValueError("missing feeds: %s" % sorted(missing))
        feed = {n: jnp.asarray(feed[n]) for n in self.feed_names}
        out = self._exported.call(self._state, feed)
        return [np.asarray(o) for o in out] if return_numpy else list(out)

    predict = run

    def clone(self):
        """API parity with ``Predictor.clone()`` (ref
        ``AnalysisPredictor::Clone``) so a replica pool — e.g.
        ``serving.ServingEngine`` — can treat either predictor type
        uniformly. The exported computation and the param arrays are
        immutable, so clones share both; there is no per-clone executor
        cache to refresh (``jax.export``'s ``call`` compiles per shape
        internally)."""
        other = object.__new__(StableHLOPredictor)
        other._exported = self._exported
        other._state = self._state
        other.feed_names = list(self.feed_names)
        other.fetch_names = list(self.fetch_names)
        other.batch_mode = self.batch_mode
        return other

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return list(self.fetch_names)


def load_stablehlo_predictor(dirname, params_filename=None):
    """Load-and-run from the ``save_inference_model`` StableHLO artifact."""
    return StableHLOPredictor(dirname, params_filename)

"""Inference predictor stack.

Reference: ``paddle/fluid/inference/api/analysis_predictor.cc:183``
(AnalysisPredictor::Init) + ``:734`` (Run), configured by
``paddle_inference_api.h`` AnalysisConfig, exposed in Python as
``fluid.core.AnalysisConfig`` / ``create_paddle_predictor``.

TPU-native re-design: the analysis pass stack (IR optimization, fusion,
TensorRT/MKLDNN subgraphs, memory optimization) is subsumed by XLA
compilation of the whole pruned program — the predictor's job is model
loading, an isolated scope, a warm shape-keyed jit cache (the Executor's
program cache), and zero-copy device-resident feeds (jax.Array passthrough).

Placement (the serving tier's mesh story, ISSUE 14):

  * ``clone(device=...)`` pins a replica to ONE device: the clone gets its
    own scope with every weight ``jax.device_put`` onto that device, so
    jit dispatch (which follows committed inputs) runs there — the
    ``ServingEngine(placement="per_device")`` building block.
  * ``shard(mesh)`` returns a tensor-parallel predictor: weights are laid
    out per their ``ParamAttr(sharding=...)`` annotations over the mesh
    (axes absent from the mesh degrade to replication, the
    ``executor._mesh_shardings`` rule), and runs go through
    ``CompiledProgram`` so GSPMD inserts the collectives. Models bigger
    than one chip's HBM serve through the same ``run`` API.
"""

import os

from . import io as io_mod
from .core.executor import Executor, Scope, scope_guard, XLAPlace

__all__ = ["AnalysisConfig", "Predictor", "ProgramPredictor",
           "create_paddle_predictor", "StableHLOPredictor",
           "load_stablehlo_predictor"]


class AnalysisConfig:
    """Parity shim for the reference AnalysisConfig.

    INERT KNOBS — read this before tuning: ``enable_use_gpu``,
    ``disable_gpu``, ``switch_ir_optim`` and ``enable_memory_optim`` are
    recorded but change NOTHING on TPU. The reference's analysis passes
    (IR fusion, TensorRT/MKLDNN subgraphs, memory reuse) are subsumed by
    XLA compiling the whole pruned program; execution always targets the
    XLA default device. Only the model paths and ``enable_int8`` act."""

    def __init__(self, model_dir=None, prog_file=None, params_file=None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self._use_gpu = False
        self._mem_optim = True
        self._ir_optim = True
        self._int8 = None  # None = auto-detect params.int8.npz in the dir

    # -- reference-API surface (no-op on TPU, XLA subsumes) -----------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_gpu = True  # accepted; execution targets the XLA device

    def disable_gpu(self):
        self._use_gpu = False

    def switch_ir_optim(self, x=True):
        self._ir_optim = bool(x)

    def enable_memory_optim(self, x=True):
        self._mem_optim = bool(x)

    def enable_int8(self, x=True):
        """Serve from the ``contrib.quantize`` int8 export
        (``params.int8.npz`` beside the model): dequantized onto the
        quantization grid at load. ``True`` requires the export, ``False``
        forces fp32, the default ``None`` auto-detects."""
        self._int8 = bool(x)

    def set_model(self, model_dir):
        self.model_dir = model_dir


class ProgramPredictor:
    """Predictor over an already-built (program, scope) pair — the
    in-process serving adapter for programs constructed in this very
    process (decode step programs, test programs), with no save/load
    round trip. ``Predictor`` subclasses it with the model-dir loading
    front end; everything below ``run`` is shared."""

    def __init__(self, program, feed_names, fetch_vars, scope=None):
        self.config = None
        self._scope = scope if scope is not None else Scope()
        self._exe = Executor(XLAPlace(0))
        self._program = program
        self._compiled = None
        self.feed_names = list(feed_names)
        self._fetch_vars = list(fetch_vars)
        self.fetch_names = [v.name if hasattr(v, "name") else str(v)
                            for v in fetch_vars]

    def run(self, inputs, return_numpy=True):
        """``inputs``: dict name->array, or a list/tuple in feed order.
        Returns outputs in fetch order."""
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != len(self.feed_names):
                raise ValueError("expected %d inputs (%s), got %d"
                                 % (len(self.feed_names), self.feed_names,
                                    len(inputs)))
            feed = dict(zip(self.feed_names, inputs))
        else:
            feed = dict(inputs)
            missing = set(self.feed_names) - set(feed)
            if missing:
                raise ValueError("missing feeds: %s" % sorted(missing))
        # scope passed explicitly (not via the global scope_guard stack):
        # clones serving concurrently from other threads must not race on
        # process-global scope resolution. donate_state=False for the same
        # reason: donation would invalidate the scope's shared weight
        # arrays mid-call, a use-after-free when another clone reads them
        return self._exe.run(self._compiled if self._compiled is not None
                             else self._program, feed=feed,
                             fetch_list=self._fetch_vars,
                             scope=self._scope,
                             return_numpy=return_numpy,
                             donate_state=False)

    predict = run

    def clone(self, device=None):
        """A predictor sharing this one's weights (ref
        ``AnalysisPredictor::Clone``): same scope/program, fresh exe cache.

        ``device``: pin the clone to one jax device — its scope becomes a
        COPY with every array ``device_put`` there (weights no longer
        shared with the parent; jit dispatch follows the committed
        arrays). The ``placement="per_device"`` replica constructor."""
        other = object.__new__(type(self))
        other.config = self.config
        other._scope = self._scope
        other._exe = Executor(XLAPlace(0))
        other._program = self._program
        other._compiled = self._compiled
        other.feed_names = list(self.feed_names)
        other._fetch_vars = self._fetch_vars
        other.fetch_names = list(self.fetch_names)
        if hasattr(self, "int8"):  # Predictor subclass advertises it
            other.int8 = self.int8
        if device is not None:
            import jax

            pinned = Scope()
            for name in self._scope.var_names():
                if name.startswith("@"):
                    continue  # RNG key re-seeds per scope
                pinned.set(name,
                           jax.device_put(self._scope.get(name), device))
            other._scope = pinned
        return other

    def shard(self, mesh, dp_axis="dp"):
        """A tensor-parallel predictor over ``mesh``: every annotated
        weight (``ParamAttr(sharding=...)``) is laid out per its spec
        (axes absent from the mesh degrade to replication), the rest
        replicate, and runs compile through ``CompiledProgram`` so GSPMD
        inserts the collectives. Weights are placed ONCE here — with the
        same NamedShardings the Executor derives — so the per-call jit
        never re-ships them. Feeds stay replicated unless the mesh has a
        ``dp_axis`` axis (the serving default: mp-only mesh)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .core.compiler import CompiledProgram

        mesh_axes = set(mesh.axis_names)
        gb = self._program.global_block()
        sharded = Scope()
        for name in self._scope.var_names():
            if name.startswith("@"):
                continue
            var = gb.vars.get(name)
            spec = getattr(var, "sharding", None) if var is not None \
                else None
            if spec is not None:
                spec = P(*[a if a in mesh_axes else None for a in spec])
            else:
                spec = P()
            sharded.set(name, jax.device_put(
                self._scope.get(name), NamedSharding(mesh, spec)))
        other = self.clone()
        other._scope = sharded
        other._exe = Executor(XLAPlace(0))
        other._compiled = CompiledProgram(self._program).with_data_parallel(
            mesh=mesh, dp_axis=dp_axis)
        return other

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return list(self.fetch_names)


class Predictor(ProgramPredictor):
    """Loads a saved inference model into an isolated scope and serves
    ``run``/``predict`` with a warm compile cache.

    Ref ``analysis_predictor.cc``: Init loads + optimizes the program once;
    Run executes with feed/fetch binding. Here the first call per feed-shape
    compiles (XLA) and subsequent calls hit the Executor's program cache."""

    def __init__(self, config):
        if isinstance(config, str):
            config = AnalysisConfig(model_dir=config)
        scope = Scope()
        exe = Executor(XLAPlace(0))
        model_dir = config.model_dir
        # combined-file form (ref SetModel(prog_file, params_file)): the
        # directory comes from the file paths, which must agree
        for fp in (config.prog_file, config.params_file):
            if fp is None:
                continue
            d = os.path.dirname(os.path.abspath(fp))
            if model_dir is None:
                model_dir = d
            elif os.path.abspath(model_dir) != d:
                raise ValueError(
                    "AnalysisConfig: %r is not inside model_dir %r"
                    % (fp, model_dir))
        if model_dir is None:
            raise ValueError("AnalysisConfig needs model_dir (the "
                             "save_inference_model output directory) or "
                             "prog_file/params_file paths")
        with scope_guard(scope):
            prog, feed_names, fetch_vars = io_mod.load_inference_model(
                model_dir, exe,
                model_filename=(os.path.basename(config.prog_file)
                                if config.prog_file else None),
                params_filename=(os.path.basename(config.params_file)
                                 if config.params_file else None))
        ProgramPredictor.__init__(self, prog, feed_names, fetch_vars,
                                  scope=scope)
        self.config = config
        self._exe = exe
        # int8 serving path: the contrib.quantize export, dequantized onto
        # the quantization grid at load (flag or auto-detect on the dir)
        self.int8 = False
        if config._int8 is not False:
            from .contrib.quantize.quantize_transpiler import \
                load_int8_params

            loaded = load_int8_params(model_dir, scope,
                                      require=config._int8 is True)
            self.int8 = bool(loaded)


def create_paddle_predictor(config):
    """Factory-name parity with the reference C-API."""
    return Predictor(config)


class StableHLOPredictor:
    """Serves from the serialized StableHLO artifact alone — no
    model-building Python, no symbolic program replay (ref parity:
    ``CreatePaddlePredictor`` runs from the serialized program+params,
    ``analysis_predictor.cc:734``). ``save_inference_model`` writes the
    artifact (``model.stablehlo.bin`` via jax.export) next to the params;
    this loader deserializes and executes it.

    Batch-size note: with a symbolic-batch export (manifest
    ``batch_mode: symbolic``) any batch works; a ``pinned-1`` export only
    accepts batch 1."""

    def __init__(self, dirname, params_filename=None):
        import json

        import jax.numpy as jnp
        import numpy as np
        from jax import export as jexport

        with open(os.path.join(dirname, "model.stablehlo.bin"), "rb") as f:
            self._exported = jexport.deserialize(f.read())
        with open(os.path.join(dirname, "stablehlo_manifest.json")) as f:
            man = json.load(f)
        self.feed_names = list(man["feed_names"])
        self.fetch_names = list(man["fetch_names"])
        self.batch_mode = man["batch_mode"]
        params = np.load(os.path.join(dirname,
                                      params_filename or "params.npz"),
                         allow_pickle=False)
        state_names = man["state_names"]
        missing = [n for n in state_names if n not in params]
        if missing:
            raise ValueError("params file lacks exported state vars %s"
                             % missing)
        self._state = {n: jnp.asarray(params[n]) for n in state_names}

    def run(self, inputs, return_numpy=True):
        import jax.numpy as jnp
        import numpy as np

        if isinstance(inputs, (list, tuple)):
            feed = dict(zip(self.feed_names, inputs))
        else:
            feed = dict(inputs)
        missing = set(self.feed_names) - set(feed)
        if missing:
            raise ValueError("missing feeds: %s" % sorted(missing))
        feed = {n: jnp.asarray(feed[n]) for n in self.feed_names}
        out = self._exported.call(self._state, feed)
        return [np.asarray(o) for o in out] if return_numpy else list(out)

    predict = run

    def clone(self, device=None):
        """API parity with ``Predictor.clone()`` (ref
        ``AnalysisPredictor::Clone``) so a replica pool — e.g.
        ``serving.ServingEngine`` — can treat either predictor type
        uniformly. The exported computation and the param arrays are
        immutable, so clones share both; there is no per-clone executor
        cache to refresh (``jax.export``'s ``call`` compiles per shape
        internally). ``device``: pin the clone's state arrays to one
        device (the per_device placement hook)."""
        import jax

        other = object.__new__(StableHLOPredictor)
        other._exported = self._exported
        other._state = self._state
        if device is not None:
            other._state = {n: jax.device_put(a, device)
                            for n, a in self._state.items()}
        other.feed_names = list(self.feed_names)
        other.fetch_names = list(self.fetch_names)
        other.batch_mode = self.batch_mode
        return other

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return list(self.fetch_names)


def load_stablehlo_predictor(dirname, params_filename=None):
    """Load-and-run from the ``save_inference_model`` StableHLO artifact."""
    return StableHLOPredictor(dirname, params_filename)

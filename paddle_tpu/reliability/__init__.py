"""``paddle_tpu.reliability`` — the fault-tolerance layer.

The reference treats robustness as first-class plumbing — pserver RPC
retry/deadline in brpc (``FLAGS_rpc_deadline``, ``grpc_client.cc``
Proceed error paths) and coordinated recovery via ``checkpoint_notify``
(``distribute_transpiler.py:1457``). This package is the TPU-native
analog, shared by serving, the launcher, checkpoints, and the async
ingest path:

  * :mod:`~paddle_tpu.reliability.faults` — a seeded, deterministic
    fault-injection harness (``PADDLE_TPU_FAULTS`` env var or an
    explicit :class:`FaultPlan`). Recovery paths are *tested* against
    injected failures, not hoped-for.
  * :mod:`~paddle_tpu.reliability.policy` — composable
    :class:`RetryPolicy` (exponential backoff + deterministic jitter,
    injectable clock/sleep like ``DynamicBatcher``),
    :class:`CircuitBreaker`, and :class:`Deadline` helpers.

Consumers: ``serving.ServingEngine`` (replica circuit breakers,
supervisor, cross-replica batch retry), ``distributed.launch``
(elastic restart backoff schedule), ``checkpoint`` (write faults +
CRC-verified load fallback), ``AsyncExecutor`` (bounded bad-record
skip).
"""

from .faults import (FaultPlan, FaultSpec, InjectedFault,  # noqa: F401
                     active_plan, corrupt_bytes, fault_scope, trip)
from .policy import (CircuitBreaker, Deadline,  # noqa: F401
                     DeadlineExpired, RetryError, RetryPolicy)

__all__ = [
    "FaultPlan", "FaultSpec", "InjectedFault", "active_plan",
    "corrupt_bytes", "fault_scope", "trip",
    "RetryPolicy", "RetryError", "CircuitBreaker", "Deadline",
    "DeadlineExpired",
]

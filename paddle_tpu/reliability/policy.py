"""Retry / circuit-breaker / deadline policies.

The reference's robustness knobs are RPC-layer constants
(``FLAGS_rpc_deadline``, brpc retry counts); here they are small
composable objects shared by every subsystem that talks to something
that can fail — the serving engine's replica breakers, the launcher's
elastic-restart backoff schedule, and callers of ``predictor.run``.

Time is injected exactly like ``serving.DynamicBatcher``'s clock:
``RetryPolicy`` takes ``sleep=``, ``CircuitBreaker``/``Deadline`` take
``clock=`` — the tier-1 tests drive full backoff schedules and breaker
state machines with fakes and zero real sleeping. Jitter is
*deterministic* (seeded) so a recorded schedule is reproducible in a
postmortem.
"""

import random
import time

from ..obs import flight

__all__ = ["RetryPolicy", "RetryError", "CircuitBreaker", "Deadline",
           "DeadlineExpired"]


class RetryError(RuntimeError):
    """The retry budget (or its deadline) is spent; ``.last`` is the
    final exception and ``.attempts`` how many were actually made —
    fewer than ``max_attempts`` when a deadline cut the schedule short."""

    def __init__(self, attempts, last):
        super().__init__("gave up after %d attempt(s); last: %r"
                         % (attempts, last))
        self.attempts = attempts
        self.last = last


class RetryPolicy:
    """Exponential backoff with bounded, deterministic jitter.

    ``delays()`` is the full schedule (``max_attempts - 1`` waits):
    ``base_delay_s * multiplier**k``, capped at ``max_delay_s``, each
    scaled by ``1 + U(-jitter, +jitter)`` drawn from a seeded stream —
    two policies built with the same arguments produce the same
    schedule.

    ``call(fn)`` runs ``fn`` up to ``max_attempts`` times, sleeping the
    schedule between failures, and raises :class:`RetryError` wrapping
    the last exception when the budget is spent.
    """

    def __init__(self, max_attempts=3, base_delay_s=0.05, max_delay_s=2.0,
                 multiplier=2.0, jitter=0.1, seed=0, sleep=None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if base_delay_s < 0 or max_delay_s < 0:
            raise ValueError("delays must be >= 0")
        if multiplier < 1.0:
            raise ValueError("multiplier must be >= 1")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.sleep = sleep or time.sleep

    def delays(self):
        """The deterministic backoff schedule as a list of seconds."""
        rng = random.Random(self.seed)
        out = []
        for k in range(self.max_attempts - 1):
            d = min(self.base_delay_s * (self.multiplier ** k),
                    self.max_delay_s)
            if self.jitter:
                d *= 1.0 + rng.uniform(-self.jitter, self.jitter)
            out.append(d)
        return out

    def call(self, fn, retry_on=(Exception,), on_retry=None,
             deadline=None):
        """Run ``fn()`` with retries. ``retry_on`` limits which exception
        types are retried (others propagate immediately); ``on_retry``
        is called as ``on_retry(attempt_index, exc, delay_s)`` before
        each sleep; a :class:`Deadline` stops early (the remaining
        schedule is skipped and :class:`RetryError` raised) instead of
        sleeping past it."""
        schedule = self.delays()
        last = None
        attempts = 0
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except retry_on as e:
                last = e
                attempts = attempt + 1
                if attempt == self.max_attempts - 1:
                    break
                delay = schedule[attempt]
                if deadline is not None:
                    rem = deadline.remaining()
                    if rem <= delay:
                        break
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                if delay > 0:
                    self.sleep(delay)
        raise RetryError(attempts, last) from last


class CircuitBreaker:
    """Consecutive-failure breaker: CLOSED -> OPEN after
    ``failure_threshold`` consecutive failures; OPEN -> HALF_OPEN after
    ``reset_timeout_s`` (one probe allowed); a probe success closes it,
    a probe failure re-opens. Not thread-safe by itself — callers that
    share one breaker across threads (the serving engine does not: one
    breaker per replica, touched only by that replica's worker thread)
    must lock around it."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(self, failure_threshold=3, reset_timeout_s=30.0,
                 clock=None, name=None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.clock = clock or time.monotonic
        self.name = name  # flight-recorder label; anonymous if None
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.opened_at = None

    def allow(self):
        """May the next call proceed? OPEN turns HALF_OPEN (allowing one
        probe) once the reset timeout has passed."""
        if self.state == self.OPEN:
            if self.clock() - self.opened_at >= self.reset_timeout_s:
                self.state = self.HALF_OPEN
                return True
            return False
        return True

    def record_success(self):
        if self.state != self.CLOSED:
            flight.record("breaker.close", breaker=self.name)
        self.consecutive_failures = 0
        self.state = self.CLOSED
        self.opened_at = None

    def record_failure(self):
        """Returns True exactly when this failure TRIPS the breaker
        (transition into OPEN) — the caller's cue to evict/rebuild."""
        self.consecutive_failures += 1
        was_open = self.state == self.OPEN
        if (self.state == self.HALF_OPEN
                or self.consecutive_failures >= self.failure_threshold):
            self.state = self.OPEN
            self.opened_at = self.clock()
            if not was_open:
                flight.record("breaker.open", breaker=self.name,
                              failures=self.consecutive_failures)
                return True
            return False
        return False

    def reset(self):
        self.record_success()


class DeadlineExpired(TimeoutError):
    pass


class Deadline:
    """A wall-clock budget carried through a call chain."""

    def __init__(self, timeout_s, clock=None):
        self.clock = clock or time.monotonic
        self.timeout_s = None if timeout_s is None else float(timeout_s)
        self._t0 = self.clock()

    def remaining(self):
        """Seconds left (may be negative); +inf for a None budget."""
        if self.timeout_s is None:
            return float("inf")
        return self.timeout_s - (self.clock() - self._t0)

    def expired(self):
        return self.remaining() <= 0

    def require(self, what="operation"):
        """Raise :class:`DeadlineExpired` when the budget is spent."""
        rem = self.remaining()
        if rem <= 0:
            raise DeadlineExpired(
                "%s exceeded its %.3fs deadline by %.3fs"
                % (what, self.timeout_s, -rem))
        return rem

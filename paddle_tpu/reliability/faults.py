"""Deterministic fault injection (``PADDLE_TPU_FAULTS``).

Every recovery path in the framework is exercised against *injected*
failures rather than hoped-for ones. Production code marks its fault
sites with a single cheap call::

    from paddle_tpu.reliability import faults
    ...
    faults.trip("predictor.run")   # no-op unless a plan is active

and a test (or an operator reproducing an incident) activates a plan::

    plan = faults.FaultPlan.from_spec("predictor.run:error@1-3")
    with faults.fault_scope(plan):
        ...   # invocations 1..3 of the site raise InjectedFault

Sites in-tree: ``predictor.run`` (serving batch dispatch),
``serving.worker`` (worker-thread top of loop — thread-death drills for
the supervisor), ``checkpoint.write`` (array-file writes; ``corrupt``
flips bytes post-write), ``recordio.read`` (async ingest; ``corrupt``
truncates the record so the bounded-skip path engages), and the
multi-process serving path (``serving/rpc.py`` + ``serving/router.py``):
``rpc.send`` (before a frame is written; ``corrupt`` ships a damaged
payload the peer rejects), ``rpc.recv`` (before a frame is read;
``corrupt`` damages the received payload pre-parse), ``router.dispatch``
(router -> worker hop; ``error`` exercises the one-cross-worker-retry
path, ``hang(s)`` burns the propagated deadline in the router), and
``worker.heartbeat`` (tripped in the router's health loop before each
ping — ``error`` fakes a missed heartbeat, feeding the per-worker
breaker and the respawn path). The streaming train-to-serve loop
(``streaming/``) adds ``stream.tail`` (tripped per tail-follow poll;
``error`` kills the tailer, ``hang(s)`` stalls it, ``corrupt`` damages
the first record the poll delivers — a torn tail read) and
``checkpoint.publish`` (tripped per trainer publish attempt; ``error``
models a publish dying mid-flight — counted, training continues —
``corrupt`` lands a damaged version so the swap plane's
fallback-to-previous-intact path and its circuit breaker engage).
Fleet-coordinated streaming adds four more: ``lease.renew`` (tripped
per partition-lease renewal in the coordinator; ``error`` models a
missed heartbeat — enough of them and survivors reclaim the
partition), ``cursor.write`` (tripped when the trainer captures its
ingest cursor for a publish; ``error`` fails that publish whole —
a version must never land without its cursor — ``corrupt`` zeroes the
offsets, forcing a full but *counted* replay on resume), and the
two-phase swap pair ``swap.prepare`` / ``swap.commit`` (tripped in the
serving engine per phase; a prepare ``error`` aborts the whole fleet
round — nothing swaps — while a commit ``error`` after successful
prepares exercises the retry-then-quarantine path and the
``fleet_version_skew`` gauge; ``swap.prepare:corrupt`` models a
staged-bytes CRC mismatch).

Multi-process note: the env grammar is how faults cross a process
boundary — the router passes ``worker_env={"PADDLE_TPU_FAULTS":
"predictor.run:error@1"}`` to chaos a whole worker tier, e.g.::

    PADDLE_TPU_FAULTS="rpc.send:error@2"           # 2nd frame send dies
    PADDLE_TPU_FAULTS="router.dispatch:hang(0.3)@1" # burn 1st dispatch
    PADDLE_TPU_FAULTS="worker.heartbeat:error@1-3"  # 3 missed pings

Determinism: explicit specs name 1-based invocation numbers per site.
Random ("chaos") plans draw per-(site, invocation) decisions from a
stream seeded by ``(seed, site)`` — the decision for invocation *i*
does not depend on thread interleaving across sites.

Env activation: ``PADDLE_TPU_FAULTS="site:kind@invs[;site:kind@invs]"``
with ``invs`` like ``1,3,5-7`` and ``kind`` one of ``error``,
``hang(seconds)``, ``corrupt``. ``FaultPlan.from_env().install()`` is
called by consumers lazily via :func:`maybe_install_from_env`.
"""

import os
import random
import re
import threading
import time

__all__ = ["InjectedFault", "FaultSpec", "FaultPlan", "trip",
           "corrupt_bytes", "fault_scope", "active_plan",
           "maybe_install_from_env"]

ENV_VAR = "PADDLE_TPU_FAULTS"

_KINDS = ("error", "hang", "corrupt")


class InjectedFault(RuntimeError):
    """Raised by a fault site the active plan chose to fail.

    A typed, recognizable error: recovery paths (retry, eviction,
    checkpoint fallback) treat it like any other failure; assertions in
    tests can tell it apart from genuine bugs."""

    def __init__(self, site, invocation):
        super().__init__("injected fault at %r (invocation %d)"
                         % (site, invocation))
        self.site = site
        self.invocation = invocation


class FaultSpec:
    """One deterministic rule: fail ``site`` on the given 1-based
    ``invocations`` with ``kind`` (error | hang | corrupt)."""

    __slots__ = ("site", "kind", "invocations", "hang_s")

    def __init__(self, site, kind, invocations, hang_s=0.05):
        if kind not in _KINDS:
            raise ValueError("kind must be one of %s, got %r"
                             % (_KINDS, kind))
        self.site = str(site)
        self.kind = kind
        self.invocations = frozenset(int(i) for i in invocations)
        if any(i < 1 for i in self.invocations):
            raise ValueError("invocations are 1-based")
        self.hang_s = float(hang_s)

    def __repr__(self):
        return "FaultSpec(%r, %r, %s)" % (
            self.site, self.kind, sorted(self.invocations))


def _parse_invocations(text):
    out = set()
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            out.update(range(int(lo), int(hi) + 1))
        else:
            out.add(int(part))
    if not out:
        raise ValueError("empty invocation list")
    return out


_SPEC_RE = re.compile(
    r"^(?P<site>[\w.\-]+):(?P<kind>error|corrupt|hang(?:\("
    r"(?P<hang>[0-9.]+)\))?)@(?P<invs>[0-9,\-\s]+)$")


class FaultPlan:
    """A set of :class:`FaultSpec` rules plus an optional seeded chaos
    mode (``rate`` probability of an ``error`` per invocation on each of
    ``chaos_sites``). Thread-safe; counters are per-site and 1-based."""

    def __init__(self, specs=(), seed=0, rate=0.0, chaos_sites=(),
                 chaos_hang_s=0.002, chaos_kinds=("error",)):
        self.specs = list(specs)
        self.seed = int(seed)
        self.rate = float(rate)
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError("rate must be in [0, 1]")
        self.chaos_sites = tuple(chaos_sites)
        self.chaos_hang_s = float(chaos_hang_s)
        for k in chaos_kinds:
            if k not in _KINDS:
                raise ValueError("chaos kind %r not in %s" % (k, _KINDS))
        self.chaos_kinds = tuple(chaos_kinds)
        self._lock = threading.Lock()
        self._counts = {}
        # per-site decision streams, extended lazily: decision i depends
        # only on (seed, site, i), never on cross-site interleaving
        self._chaos = {}

    # -- construction -------------------------------------------------------
    @classmethod
    def from_spec(cls, text, **kwargs):
        """Parse ``site:kind@invs[;site:kind@invs...]`` (the env grammar)."""
        specs = []
        for clause in str(text).split(";"):
            clause = clause.strip()
            if not clause:
                continue
            m = _SPEC_RE.match(clause)
            if not m:
                raise ValueError(
                    "bad fault spec %r (want site:kind@invocations, e.g. "
                    "predictor.run:error@1-3 or checkpoint.write:"
                    "hang(0.1)@2)" % clause)
            kind = m.group("kind")
            hang_s = 0.05
            if kind.startswith("hang"):
                if m.group("hang"):
                    hang_s = float(m.group("hang"))
                kind = "hang"
            specs.append(FaultSpec(m.group("site"), kind,
                                   _parse_invocations(m.group("invs")),
                                   hang_s=hang_s))
        return cls(specs, **kwargs)

    @classmethod
    def from_env(cls, environ=None):
        """Build a plan from ``PADDLE_TPU_FAULTS``; None when unset."""
        text = (environ or os.environ).get(ENV_VAR)
        if not text:
            return None
        return cls.from_spec(text)

    # -- the hot path -------------------------------------------------------
    def trip(self, site):
        """Record one invocation of ``site`` and act on any matching rule:
        ``error`` raises :class:`InjectedFault`, ``hang`` sleeps the
        rule's seconds then returns None, ``corrupt`` returns the string
        ``"corrupt"`` for the caller to apply (a site that ignores the
        return value simply cannot be corrupted)."""
        with self._lock:
            inv = self._counts.get(site, 0) + 1
            self._counts[site] = inv
            kind, hang_s = self._decide_locked(site, inv)
        if kind is None:
            return None
        # only DECISIONS reach the flight ring — the no-fault path above
        # stays lock+dict-increment only
        from ..obs import flight as _flight
        _flight.record("fault.trip", site=site, invocation=inv, fault=kind)
        if kind == "error":
            raise InjectedFault(site, inv)
        if kind == "hang":
            time.sleep(hang_s)
            return None
        return "corrupt"

    def _decide_locked(self, site, inv):
        for spec in self.specs:
            if spec.site == site and inv in spec.invocations:
                return spec.kind, spec.hang_s
        if self.rate > 0.0 and site in self.chaos_sites:
            stream = self._chaos.get(site)
            if stream is None:
                stream = self._chaos[site] = {
                    "rng": random.Random("%d:%s" % (self.seed, site)),
                    "decisions": []}
            dec = stream["decisions"]
            rng = stream["rng"]
            while len(dec) < inv:
                if rng.random() < self.rate:
                    dec.append(self.chaos_kinds[
                        rng.randrange(len(self.chaos_kinds))])
                else:
                    dec.append(None)
            kind = dec[inv - 1]
            if kind is not None:
                return kind, self.chaos_hang_s
        return None, None

    # -- introspection / lifecycle ------------------------------------------
    def counts(self):
        """Snapshot: site -> invocations recorded so far."""
        with self._lock:
            return dict(self._counts)

    def reset(self):
        with self._lock:
            self._counts.clear()
            self._chaos.clear()

    def install(self):
        """Make this the process-global active plan (see module
        :func:`trip`). Returns self; prefer :func:`fault_scope` in tests."""
        global _ACTIVE
        _ACTIVE = self
        return self

    def uninstall(self):
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None

    def __repr__(self):
        return ("FaultPlan(specs=%r, seed=%d, rate=%g, chaos_sites=%r)"
                % (self.specs, self.seed, self.rate, self.chaos_sites))


_ACTIVE = None
_env_checked = False


def active_plan():
    return _ACTIVE


def maybe_install_from_env():
    """Install a plan from ``PADDLE_TPU_FAULTS`` once per process (no-op
    when the var is unset or a plan is already active). Called lazily by
    fault-site owners so plain imports stay side-effect-free."""
    global _env_checked
    if _env_checked or _ACTIVE is not None:
        return _ACTIVE
    _env_checked = True
    plan = FaultPlan.from_env()
    if plan is not None:
        plan.install()
    return _ACTIVE


def trip(site):
    """Module-level fast path: no active plan -> pure no-op."""
    plan = _ACTIVE
    if plan is None:
        return None
    return plan.trip(site)


def corrupt_bytes(data):
    """Deterministically damage one record's bytes: drop the last byte
    (guaranteed size mismatch under fixed-size record schemas) and flip
    the first. Empty input comes back empty."""
    if not data:
        return data
    first = bytes([data[0] ^ 0xFF])
    return first + data[1:-1]


class fault_scope:
    """``with fault_scope(plan):`` — install for the block, restore the
    previous active plan after (exception-safe; nestable)."""

    def __init__(self, plan):
        self.plan = plan
        self._prev = None

    def __enter__(self):
        global _ACTIVE
        self._prev = _ACTIVE
        _ACTIVE = self.plan
        return self.plan

    def __exit__(self, *exc):
        global _ACTIVE
        _ACTIVE = self._prev
        return False

"""Model persistence (ref ``python/paddle/fluid/io.py``: ``save_vars:92``,
``save_params:213``, ``save_persistables:441``, ``load_persistables:658``,
``save_inference_model:863``, ``load_inference_model:1015``).

Format: one ``.npz`` bundle per save (atomic tmp+rename) + a JSON manifest —
the capability of the reference's save/load-combine ops. Inference export
serializes the pruned symbolic program with pickle alongside the params and
also exports StableHLO text when shapes are concrete (the XLA-native
"program binary").
"""

import json
import os
import pickle
import tempfile
import warnings

import numpy as np

from .core import framework
from .core.executor import global_scope
from .core.framework import Parameter

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "save_checkpoint", "load_checkpoint",
]


def save_checkpoint(*args, **kwargs):
    """Ref ``fluid.io`` checkpoint family; see ``paddle_tpu.checkpoint``."""
    from .checkpoint import save_checkpoint as impl

    return impl(*args, **kwargs)


def load_checkpoint(*args, **kwargs):
    from .checkpoint import load_checkpoint as impl

    return impl(*args, **kwargs)


def _collect(program, predicate):
    return [v for v in program.list_vars() if predicate(v)]


def _atomic_savez(path, arrays):
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    os.close(fd)
    try:
        np.savez(tmp, **arrays)
        os.replace(tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = _collect(main_program, predicate or (lambda v: v.persistable))
    scope = global_scope()
    arrays = {}
    for v in vars:
        if v.name in scope:
            arrays[v.name] = np.asarray(scope.get(v.name))
    path = os.path.join(dirname, filename or "__model_params__.npz")
    _atomic_savez(path, arrays)
    meta = {name: {"shape": list(a.shape), "dtype": str(a.dtype)}
            for name, a in arrays.items()}
    with open(os.path.join(dirname, "manifest.json"), "w") as f:
        json.dump(meta, f, indent=1)
    return path


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: isinstance(v, Parameter),
                     filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: v.persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = _collect(main_program, predicate or (lambda v: v.persistable))
    path = os.path.join(dirname, filename or "__model_params__.npz")
    data = np.load(path, allow_pickle=False)
    scope = global_scope()
    import jax.numpy as jnp
    for v in vars:
        if v.name in data:
            scope.set(v.name, jnp.asarray(data[v.name]))


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: isinstance(v, Parameter), filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable, filename=filename)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True):
    """Prune to the fetch targets, save program + params (ref ``io.py:863``).
    Also exports StableHLO when the feed shapes are fully static."""
    main_program = main_program or framework.default_main_program()
    inference_program = main_program.clone(for_test=True)
    targets = [inference_program.global_block().var(v.name)
               for v in target_vars]
    pruned = inference_program.prune(targets)
    os.makedirs(dirname, exist_ok=True)
    save_persistables(executor, dirname, pruned,
                      filename=params_filename or "params.npz")
    model = {
        "feed_names": list(feeded_var_names),
        "fetch_names": [v.name for v in target_vars],
        "program": pruned,
    }
    with open(os.path.join(dirname, model_filename or "__model__"), "wb") as f:
        pickle.dump(model, f)

    # StableHLO export (the XLA-native serialized program). Loud on
    # failure — a missing artifact must not be discovered at serve time
    # (ref parity: CreatePaddlePredictor serves from the serialized
    # program alone, analysis_predictor.cc:734).
    try:
        _export_stablehlo(dirname, pruned, feeded_var_names,
                          [v.name for v in target_vars])
    except Exception as e:  # noqa: BLE001 — export is best-effort, but loud
        warnings.warn("StableHLO export skipped: %s: %s"
                      % (type(e).__name__, e))
    return [v.name for v in target_vars]


def _export_stablehlo(dirname, program, feed_names, fetch_names):
    """Serialize the pruned inference program via ``jax.export``:
    ``model.stablehlo.bin`` (deserializable, executable artifact — see
    ``inference.load_stablehlo_predictor``) plus ``model.stablehlo.mlir``
    (human-readable text). The batch dim exports SYMBOLICALLY ('b') when
    the program supports shape polymorphism; otherwise it falls back to a
    pinned batch of 1 with a warning, recorded in the manifest."""
    import jax
    from jax import export as jexport
    from .core.op_registry import run_op, RNG_KEY, RNG0_KEY

    gb = program.global_block()
    for n in feed_names:
        v = gb.var(n)
        if v.shape is None or any(s < 0 for s in v.shape[1:]):
            raise ValueError(
                "feed %r has non-static non-batch dims %s" % (n, v.shape))
    scope = global_scope()
    state = {v.name: scope.get(v.name) for v in program.list_vars()
             if v.persistable and v.name in scope}

    def fn(state, feed):
        env = dict(state)
        env.update(feed)
        env[RNG_KEY] = jax.random.PRNGKey(0)
        env[RNG0_KEY] = env[RNG_KEY]
        for op in gb.ops:
            if op.type == "print":
                # debug prints are host callbacks — unserializable and
                # not part of the served computation; identity them out
                env[op.output("Out").name] = env[op.input("In").name]
                continue
            run_op(env, op)
        return tuple(env[n] for n in fetch_names)

    def feed_spec(batch):
        out = {}
        for n in feed_names:
            v = gb.var(n)
            out[n] = jax.ShapeDtypeStruct((batch,) + tuple(v.shape[1:]),
                                          v.dtype)
        return out

    try:
        b = jexport.symbolic_shape("b")[0]
        exported = jexport.export(jax.jit(fn))(state, feed_spec(b))
        batch_mode = "symbolic"
    except Exception as e:  # noqa: BLE001 — fall back to a pinned batch
        warnings.warn(
            "symbolic-batch StableHLO export failed (%s: %s); exporting "
            "with batch pinned to 1" % (type(e).__name__, e))
        exported = jexport.export(jax.jit(fn))(state, feed_spec(1))
        batch_mode = "pinned-1"

    with open(os.path.join(dirname, "model.stablehlo.bin"), "wb") as f:
        f.write(exported.serialize())
    with open(os.path.join(dirname, "model.stablehlo.mlir"), "w") as f:
        f.write(exported.mlir_module())
    with open(os.path.join(dirname, "stablehlo_manifest.json"), "w") as f:
        json.dump({"feed_names": list(feed_names),
                   "fetch_names": list(fetch_names),
                   "batch_mode": batch_mode,
                   "state_names": sorted(state)}, f, indent=1)


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    with open(os.path.join(dirname, model_filename or "__model__"), "rb") as f:
        model = pickle.load(f)
    program = model["program"]
    load_persistables(executor, dirname, program,
                      filename=params_filename or "params.npz")
    gb = program.global_block()
    fetch_vars = [gb.var(n) for n in model["fetch_names"]]
    return program, model["feed_names"], fetch_vars

"""Continuous batching for autoregressive decode: a slot-recycled
scheduler over a KV-cached one-token step program.

The one-shot engine (``engine.py``) serves whole requests: a batch rides
until its LONGEST member finishes, so one 512-token generation stalls
every 8-token request batched with it. This scheduler makes the decode
STEP the scheduling quantum instead (the established continuous-batching
design the reference, whose serving story ends at
``AnalysisPredictor::Clone``, has no analog for):

  * a **slot table** of ``bucket_batch`` rows, each row one in-flight
    request with its own fill level ``pos`` into fixed-capacity per-slot
    KV-cache tensors ([B, C, ...] per layer, carried between steps as
    device-resident fetch->feed state — never a host round trip);
  * one compiled step per ``(bucket_batch, bucket_ctx)`` on the pow2
    ladders (``buckets.py``), so the XLA compile cache stays bounded at
    ``len(ladder) * len(ctx_ladder)`` executables;
  * **slot recycling**: new requests are admitted into free slots BETWEEN
    steps and finished sequences retire immediately — a long generation
    never blocks short co-riders, it just keeps its one slot;
  * **re-bucketing** when occupancy crosses a ladder boundary: the slot
    table compacts/grows and caches are copied row-wise into the new
    geometry (rare, host-side, O(B*C*D));
  * prompt tokens are ingested through the same step function (one forced
    token per step) — no separate prefill executable, so the compile
    cache bound holds and a long prompt shares steps with everyone else.

**Exact-parity guarantee.** Every op in a step program is strictly
per-row (``cached_attention`` masks each row to its own fill level;
``kv_cache_write`` writes only the row's own slot; matmul/layernorm
reduce over feature axes only). A dead or stranger row therefore cannot
perturb a live row: at a fixed (bucket_batch, bucket_ctx) geometry,
batched-with-strangers output is BITWISE-identical to solo decode —
``tests/test_serving.py`` pins this for greedy (here) and beam (the
one-shot path). Across different bucket geometries the math is identical
per row but runs in different executables, so parity there is
floating-point-deterministic, not contractual.

Sampling is host-side greedy argmax over the fetched next-token logits
row: deterministic, per-row, and it keeps eos/length control flow out of
the compiled step.
"""

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from ..obs import trace
from .admission import AdmissionController, DeadlineExceededError
from .buckets import bucket_for, pow2_ladder
from .engine import EngineShutdownError
from .metrics import ServingMetrics

__all__ = ["DecodeBatcher", "DecodeRequest", "save_decode_spec",
           "load_decode_spec", "default_ctx_ladder"]

DECODE_SPEC_FILE = "decode_spec.json"


def default_ctx_ladder(spec):
    """The ctx-capacity rung ladder a decode spec gets when the caller
    passes none: pow2 rungs up to the spec's cache capacity, floored at
    16. THE single derivation — ``DecodeBatcher.__init__`` and
    ``ServingEngine``'s build-time compile-cache verdict both call it,
    so the proved executable bound can never desynchronize from the
    ladder the batcher actually compiles."""
    cap = int(spec.get("ctx_cap", 256) or 256)
    return tuple(r for r in pow2_ladder(cap) if r >= 16) or (cap,)


def save_decode_spec(dirname, spec):
    """Write a step builder's decode-spec dict next to a
    ``save_inference_model`` export, so ``ServingEngine(dir, decode=True)``
    can serve continuous-batching decode straight from the directory."""
    import json
    import os

    path = os.path.join(dirname, DECODE_SPEC_FILE)
    with open(path, "w") as f:
        json.dump(spec, f, indent=1)
    return path


def load_decode_spec(dirname):
    import json
    import os

    with open(os.path.join(dirname, DECODE_SPEC_FILE)) as f:
        return json.load(f)


class DecodeRequest:
    """One decode request: ``prompt`` (1-D int token ids, non-empty),
    ``max_new_tokens``, optional ``eos_id`` (stop token, also emitted),
    the caller's future (resolves to the generated ids as int64 ndarray),
    and the admission timestamps the TTFT/TPOT metrics read."""

    __slots__ = ("prompt", "max_new", "eos_id", "future", "enqueue_t",
                 "deadline", "n_ctx")

    def __init__(self, prompt, max_new, eos_id, future, enqueue_t,
                 deadline=None):
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.future = future
        self.enqueue_t = enqueue_t
        self.deadline = deadline
        # cache capacity this request needs: every prompt token is written
        # once, then at most max_new-1 generated tokens are fed back (the
        # last sampled token never re-enters the cache), so the highest
        # index written is prompt+max_new-2
        self.n_ctx = len(self.prompt) + self.max_new - 1

    def __repr__(self):
        return ("DecodeRequest(prompt=%d toks, max_new=%d)"
                % (len(self.prompt), self.max_new))


class _Slot:
    """One occupied slot-table row."""

    __slots__ = ("req", "pos", "k", "out", "next_token", "first_tok_t")

    def __init__(self, req):
        self.req = req
        self.pos = 0            # next cache index == tokens ingested
        self.k = 1              # prompt cursor: prompt[0] feeds first
        self.out = []           # generated ids
        self.next_token = req.prompt[0]
        self.first_tok_t = None

    @property
    def forcing(self):
        """Still ingesting prompt tokens (logits ignored)."""
        return self.k < len(self.req.prompt)


class DecodeBatcher:
    """The continuous batcher. Same client surface as ``ServingEngine``
    (``submit``/``predict``/``metrics``/``warmup``/``shutdown``) so the
    engine can put it behind one API.

    ``predictor``: anything with ``run(feed, return_numpy=False) -> list``
    in fetch order and ``fetch_names`` — a ``Predictor`` over a saved
    step-program dir, an in-process ``ProgramPredictor``, or a test fake.
    ``spec``: the decode-spec dict a step builder returns
    (``models.transformer.transformer_lm_step``): token/pos feed names,
    logits fetch, per-layer cache feed/fetch pairs with tail shapes.

    ``start=False`` skips the loop thread: tests then call
    :meth:`drive` to run the scheduler synchronously — fully
    deterministic, zero sleeps (the injectable-``clock`` contract the
    rest of the serving tier follows)."""

    def __init__(self, predictor, spec, ladder=None, ctx_ladder=None,
                 max_batch_size=8, max_queue_depth=256,
                 default_timeout_s=None, default_max_new_tokens=64,
                 eos_id=None, clock=None, metrics=None, start=True):
        self._predictor = predictor
        self._spec = dict(spec)
        self._tok_feed = self._spec["token_feed"]
        self._pos_feed = self._spec["pos_feed"]
        fetch_names = list(predictor.fetch_names)
        self._logits_idx = fetch_names.index(self._spec["logits_fetch"])
        self._cache_feeds = []
        for cf in self._spec["cache_feeds"]:
            self._cache_feeds.append(
                (cf["feed"], fetch_names.index(cf["fetch"]),
                 tuple(cf["tail"]), np.dtype(cf.get("dtype", "float32"))))
        self.ladder = tuple(sorted(set(
            ladder if ladder is not None else pow2_ladder(max_batch_size))))
        if ctx_ladder is None:
            ctx_ladder = default_ctx_ladder(self._spec)
        self.ctx_ladder = tuple(sorted(set(int(c) for c in ctx_ladder)))
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.default_timeout_s = default_timeout_s
        self.eos_id = eos_id
        self._clock = clock or time.monotonic
        self._admission = AdmissionController(max_queue_depth)
        if metrics is not None:
            # shared instance (the engine's): the OWNER binds aggregate
            # gauges across every batcher — binding here would leave the
            # gauges reading whichever replica bound last
            self.metrics_ = metrics
        else:
            self.metrics_ = ServingMetrics()
            self.metrics_.bind_gauges(lambda: len(self._pending),
                                      lambda: self._admission.in_flight)

        self._pending = deque()
        self._slots = []          # list[_Slot | None], len == bucket_batch
        self._caches = {}         # feed name -> [B, C, *tail] array
        self._bucket = (0, 0)     # (bucket_batch, bucket_ctx)
        self.seen_signatures = set()
        self._cv = threading.Condition()
        self._closed = False
        self._aborted = False
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="paddle-tpu-decode", daemon=True)
            self._thread.start()

    # -- client surface -----------------------------------------------------
    def now(self):
        return self._clock()

    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               timeout_s=None):
        """Enqueue one decode request; returns a Future resolving to the
        generated token ids (int64 ndarray, eos included when hit).
        Raises ``BucketError`` when prompt+max_new exceeds the top ctx
        rung, ``ServerOverloadedError`` when the bounded queue is full."""
        if self._closed:
            raise RuntimeError("DecodeBatcher is shut down")
        prompt = np.asarray(prompt).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must hold at least one token")
        max_new = (int(max_new_tokens) if max_new_tokens is not None
                   else self.default_max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        eos = eos_id if eos_id is not None else self.eos_id
        # matches DecodeRequest.n_ctx: the last sampled token never
        # re-enters the cache
        n_ctx = int(prompt.size) + max_new - 1
        bucket_for(n_ctx, self.ctx_ladder)  # validates at the door
        timeout_s = (timeout_s if timeout_s is not None
                     else self.default_timeout_s)
        now = self._clock()
        deadline = now + timeout_s if timeout_s is not None else None
        self._admission.acquire(1)
        req = DecodeRequest(prompt, max_new, eos, Future(), now,
                            deadline=deadline)
        with self._cv:
            if self._closed:
                self._admission.release(1)
                raise RuntimeError("DecodeBatcher is shut down")
            self._pending.append(req)
            self._cv.notify_all()
        return req.future

    def predict(self, prompt, max_new_tokens=None, eos_id=None,
                timeout_s=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           eos_id=eos_id,
                           timeout_s=timeout_s).result(timeout_s)

    def metrics(self):
        return self.metrics_.snapshot()

    def metrics_report(self):
        return self.metrics_.report()

    def compiled_shape_counts(self):
        """Distinct (bucket_batch, bucket_ctx) geometries dispatched —
        bounded at ``len(ladder) * len(ctx_ladder)`` by construction."""
        return [len(self.seen_signatures)]

    def compile_cache_bound(self):
        """The PROVED executable-count bound (ISSUE 15): the static
        compile-cache verdict from the decode spec — dispatched
        geometries (:meth:`compiled_shape_counts`) can never exceed it."""
        from ..analysis.resources import decode_cache_verdict

        bound, _result = decode_cache_verdict(self._spec, self.ladder,
                                              self.ctx_ladder)
        return bound

    def warmup(self):
        """Pre-compile every (batch rung, ctx rung) geometry with a
        zero-token synthetic step, so live traffic never compiles.
        Returns the number of geometries warmed."""
        warmed = 0
        for b in self.ladder:
            for c in self.ctx_ladder:
                feed = self._synth_feed(b, c)
                self._predictor.run(feed, return_numpy=False)
                self.seen_signatures.add((b, c))
                warmed += 1
        return warmed

    def drive(self, max_steps=None):
        """Run the scheduler loop synchronously on the CALLING thread
        until idle (or ``max_steps`` decode steps). Only valid with
        ``start=False`` — the deterministic test/bench mode. Returns the
        number of steps executed."""
        if self._thread is not None:
            raise RuntimeError("drive() requires start=False "
                               "(the loop thread owns the slot table)")
        steps = 0
        while max_steps is None or steps < max_steps:
            self._admit()
            if not any(s is not None for s in self._slots):
                break
            self._step_once()
            steps += 1
        return steps

    def shutdown(self, drain=True, timeout_s=None):
        """Stop intake. ``drain=True`` serves everything already
        submitted — queued requests included — to completion;
        ``drain=False`` aborts: in-flight generation and queued requests
        both fail with :class:`EngineShutdownError`."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._aborted = not drain
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout_s if timeout_s is not None else 30.0)
        else:
            if drain:
                while True:
                    self._admit()
                    if not any(s is not None for s in self._slots):
                        break
                    self._step_once()
            else:
                self._abort_live()
        self._fail_pending()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    # -- scheduler ----------------------------------------------------------
    def _loop(self):
        while True:
            with self._cv:
                while (not self._closed and not self._pending
                       and not any(s is not None for s in self._slots)):
                    self._cv.wait()
                if self._closed:
                    if self._aborted:
                        break
                    if (not any(s is not None for s in self._slots)
                            and not self._pending):
                        break
            try:
                self._admit()
                if any(s is not None for s in self._slots):
                    self._step_once()
                elif self._closed:
                    break
            except BaseException as e:  # noqa: BLE001 — fail loudly, once
                self._poison(e)
                return
        if self._aborted:
            self._abort_live()

    def _poison(self, exc):
        """The step function itself threw (a replica fault, not a request
        fault): fail everything in flight — a decode loop cannot retry
        mid-sequence without replaying the whole cache."""
        for i, slot in enumerate(self._slots):
            if slot is not None:
                self._resolve_exc(slot.req, exc)
                self._slots[i] = None
        self._fail_pending(exc)

    def _fail_pending(self, exc=None):
        with self._cv:
            pending = list(self._pending)
            self._pending.clear()
        for req in pending:
            self._resolve_exc(req, exc or EngineShutdownError(
                "DecodeBatcher shut down before this request started"))
            self.metrics_.observe_failed()

    def _abort_live(self):
        for i, slot in enumerate(self._slots):
            if slot is not None:
                self._resolve_exc(slot.req, EngineShutdownError(
                    "DecodeBatcher aborted mid-generation"))
                self.metrics_.observe_failed()
                self._slots[i] = None

    def _resolve_exc(self, req, exc):
        try:
            req.future.set_exception(exc)
        except Exception:
            pass
        self._admission.release(1)

    # admission + re-bucketing — runs BETWEEN steps only (slot recycling)
    def _admit(self):
        now = self._clock()
        admitted = []
        with self._cv:
            live = sum(1 for s in self._slots if s is not None)
            room = max(self.ladder) - live
            while self._pending and room > 0:
                req = self._pending.popleft()
                if req.deadline is not None and now > req.deadline:
                    self._resolve_exc(req, DeadlineExceededError(
                        "request waited %.1f ms, deadline was %.1f ms"
                        % ((now - req.enqueue_t) * 1e3,
                           (req.deadline - req.enqueue_t) * 1e3)))
                    self.metrics_.observe_expired()
                    continue
                admitted.append(req)
                room -= 1
        if not admitted and self._bucket == self._target_bucket([]):
            return
        self._rebucket(admitted)

    def _target_bucket(self, admitting):
        live = [s.req for s in self._slots if s is not None]
        reqs = live + list(admitting)
        if not reqs:
            return (0, 0)
        b = bucket_for(len(reqs), self.ladder)
        c = bucket_for(max(r.n_ctx for r in reqs), self.ctx_ladder)
        return (b, c)

    def _rebucket(self, admitting):
        """Place admissions and re-shape the caches when the (batch, ctx)
        bucket moved.

        Same geometry: admitted requests drop into free HOLES — zero
        cache traffic, because a recycled row needs no cleaning (its new
        occupant starts at pos 0 and a row's attention mask never reaches
        past its own fill level, so the previous occupant's leftovers are
        unreachable). This is what keeps steady-state slot recycling off
        the host.

        Geometry moved (occupancy crossed a ladder rung, or a longer
        request raised the ctx rung): live rows compact into fresh
        zero arrays — the one host-side copy re-bucketing costs."""
        new_b, new_c = self._target_bucket(admitting)
        if (new_b, new_c) == self._bucket:
            if admitting:
                free = [i for i, s in enumerate(self._slots) if s is None]
                for req, i in zip(admitting, free):
                    self._slots[i] = _Slot(req)
            return
        old_c = self._bucket[1]
        live = [(i, s) for i, s in enumerate(self._slots) if s is not None]
        new_slots = [s for _, s in live]
        for req in admitting:
            new_slots.append(_Slot(req))
        new_slots += [None] * (new_b - len(new_slots))
        copy_c = min(old_c, new_c)
        for feed, _idx, tail, dtype in self._cache_feeds:
            old = self._caches.get(feed)
            new = np.zeros((new_b, new_c) + tail, dtype)
            if old is not None and live:
                old = np.asarray(old)
                for j, (i, _s) in enumerate(live):
                    new[j, :copy_c] = old[i, :copy_c]
            self._caches[feed] = new
        self._slots = new_slots
        self._bucket = (new_b, new_c)

    def _synth_feed(self, b, c):
        feed = {self._tok_feed: np.zeros((b,), np.int64),
                self._pos_feed: np.zeros((b,), np.int32)}
        for name, _idx, tail, dtype in self._cache_feeds:
            feed[name] = np.zeros((b, c) + tail, dtype)
        return feed

    def _step_once(self):
        with trace.span("decode.step") as sp:
            self._step_once_traced(sp)

    def _step_once_traced(self, sp):
        b, c = self._bucket
        toks = np.zeros((b,), np.int64)
        pos = np.zeros((b,), np.int32)
        for i, slot in enumerate(self._slots):
            if slot is not None:
                toks[i] = slot.next_token
                pos[i] = slot.pos
        feed = dict(self._caches)
        feed[self._tok_feed] = toks
        feed[self._pos_feed] = pos
        outs = self._predictor.run(feed, return_numpy=False)
        sig = (b, c)
        self.seen_signatures.add(sig)
        # carried state: fetched cache arrays feed the next step as-is
        # (device-resident jax arrays round-trip through the feed dict
        # without touching the host)
        for name, idx, _tail, _dtype in self._cache_feeds:
            self._caches[name] = outs[idx]
        logits = np.asarray(outs[self._logits_idx])
        now = self._clock()
        live = 0
        generated = 0
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            live += 1
            slot.pos += 1
            if slot.forcing:
                slot.next_token = slot.req.prompt[slot.k]
                slot.k += 1
                continue
            nxt = int(np.argmax(logits[i]))
            generated += 1
            slot.out.append(nxt)
            if slot.first_tok_t is None:
                slot.first_tok_t = now
                self.metrics_.observe_ttft(now - slot.req.enqueue_t)
            done = (len(slot.out) >= slot.req.max_new
                    or (slot.req.eos_id is not None
                        and nxt == slot.req.eos_id))
            if done:
                self._retire(i, slot, now)
            else:
                slot.next_token = nxt
        self.metrics_.observe_decode_step(live, b, generated)
        if sp:
            # slot occupancy rides on every step span (ISSUE 17)
            sp.set(live=live, bucket=b, ctx=c, generated=generated)

    def _retire(self, i, slot, now):
        """Finished sequence: resolve, free the slot IMMEDIATELY (the
        next ``_admit`` recycles it — no drain barrier)."""
        self._slots[i] = None
        if len(slot.out) > 1:
            self.metrics_.observe_tpot(
                (now - slot.first_tok_t) / (len(slot.out) - 1))
        try:
            slot.req.future.set_result(np.asarray(slot.out, np.int64))
        except Exception:
            pass
        self.metrics_.observe_completed(now - slot.req.enqueue_t)
        self._admission.release(1)

"""Continuous batching for autoregressive decode: a slot-recycled
scheduler over a KV-cached one-token step program.

The one-shot engine (``engine.py``) serves whole requests: a batch rides
until its LONGEST member finishes, so one 512-token generation stalls
every 8-token request batched with it. This scheduler makes the decode
STEP the scheduling quantum instead (the established continuous-batching
design the reference, whose serving story ends at
``AnalysisPredictor::Clone``, has no analog for):

  * a **slot table** of ``bucket_batch`` rows, each row one in-flight
    request with its own fill level ``pos`` into fixed-capacity per-slot
    KV-cache tensors ([B, C, ...] per layer, carried between steps as
    device-resident fetch->feed state — never a host round trip);
  * one compiled step per ``(bucket_batch, bucket_ctx)`` on the pow2
    ladders (``buckets.py``), so the XLA compile cache stays bounded at
    ``len(ladder) * len(ctx_ladder)`` executables;
  * **slot recycling**: new requests are admitted into free slots BETWEEN
    steps and finished sequences retire immediately — a long generation
    never blocks short co-riders, it just keeps its one slot;
  * **re-bucketing** when occupancy crosses a ladder boundary: the slot
    table compacts/grows and caches are copied row-wise into the new
    geometry (rare, host-side, O(B*C*D));
  * prompt tokens are ingested through the same step function (one forced
    token per step) — by default no separate prefill executable, so the
    compile cache bound holds and a long prompt shares steps with
    everyone else.

Three optional fast paths ride on top (ISSUE 20), all off unless
configured:

  * **prefix cache** (``prefix_cache=``): a hash-trie over token-id
    prefixes (``prefix_cache.PrefixCache``) maps shared prompt prefixes
    to the KV rows the slot table already computed for them; ``submit``
    matches the longest cached prefix, admission CLONES the rows into
    the new slot (one device-side copy) and the slot starts at
    ``pos = prefix_len`` — a request whose prefix is cached skips that
    many step dispatches of TTFT. Entries are harvested when a prompt
    finishes ingesting, LRU-evicted under byte/entry budgets, and
    ref-counted against pending admissions; since live slots hold
    CLONES, eviction can never corrupt an in-flight request.
  * **chunked prefill** (``prefill=``): a K-token chunk program
    (``transformer_lm_chunk``) ingests K prompt tokens per dispatch on
    its own pow2 prefill ladder, interleaved chunk-by-chunk with decode
    steps (when some live rows aren't covered by a chunk, the scheduler
    alternates chunk/step ticks) so a long prompt neither pays
    step-per-token TTFT nor stalls its co-riders. The compile cache
    gains one executable per (batch rung, ctx rung, prefill rung) —
    proved by ``analysis.resources.decode_cache_verdict`` via
    :meth:`DecodeBatcher.compile_cache_bound`.
  * **speculative decode** (``speculative=``): a small draft LM proposes
    k-1 tokens per generating row; ONE pass of the chunk program scores
    all k positions (the weight-sharing family makes the verifier free)
    and each row accepts greedily — a draft token is emitted only while
    it equals the target model's own argmax at that position, then the
    verifier's next argmax is emitted as the bonus token. Rejected
    drafts' cache writes are simply rewound (rows past a slot's fill
    level are unreachable by the attention mask — the same property
    slot recycling rests on). Greedy accept therefore emits exactly the
    target model's greedy chain: output is identical to plain decode
    (pinned bitwise on CPU by ``tests/test_serving.py``), regardless of
    how bad the draft is — draft quality only moves throughput.

**Exact-parity guarantee.** Every op in a step program is strictly
per-row (``cached_attention`` masks each row to its own fill level;
``kv_cache_write`` writes only the row's own slot; matmul/layernorm
reduce over feature axes only). A dead or stranger row therefore cannot
perturb a live row: at a fixed (bucket_batch, bucket_ctx) geometry,
batched-with-strangers output is BITWISE-identical to solo decode —
``tests/test_serving.py`` pins this for greedy (here) and beam (the
one-shot path). Across different bucket geometries the math is identical
per row but runs in different executables, so parity there is
floating-point-deterministic, not contractual.

Sampling is host-side greedy argmax over the fetched next-token logits
row: deterministic, per-row, and it keeps eos/length control flow out of
the compiled step.
"""

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from ..obs import trace
from .admission import AdmissionController, DeadlineExceededError
from .buckets import bucket_for, pow2_ladder
from .engine import EngineShutdownError
from .metrics import ServingMetrics
from .prefix_cache import PrefixCache

__all__ = ["DecodeBatcher", "DecodeRequest", "DraftLM", "save_decode_spec",
           "load_decode_spec", "default_ctx_ladder",
           "default_prefill_ladder"]

DECODE_SPEC_FILE = "decode_spec.json"


def default_ctx_ladder(spec):
    """The ctx-capacity rung ladder a decode spec gets when the caller
    passes none: pow2 rungs up to the spec's cache capacity, floored at
    16. THE single derivation — ``DecodeBatcher.__init__`` and
    ``ServingEngine``'s build-time compile-cache verdict both call it,
    so the proved executable bound can never desynchronize from the
    ladder the batcher actually compiles."""
    cap = int(spec.get("ctx_cap", 256) or 256)
    return tuple(r for r in pow2_ladder(cap) if r >= 16) or (cap,)


def default_prefill_ladder(spec):
    """The chunk-length rung ladder a prefill/verify chunk program gets
    when the caller passes none: pow2 rungs from 4 up to half the cache
    capacity (a chunk near the full capacity would serve exactly one
    prompt shape — not worth an executable). Shared by
    ``DecodeBatcher.__init__`` and the engine's build-time verdict, same
    single-derivation rule as :func:`default_ctx_ladder`."""
    cap = int(spec.get("ctx_cap", 256) or 256)
    top = min(cap, max(4, cap // 2))
    return tuple(r for r in pow2_ladder(top) if r >= 4) or (min(4, cap),)


class DraftLM:
    """Greedy draft proposer over a small FULL ``transformer_lm``
    program: no KV cache of its own — each draft token is one pass of
    the full causal program over the row's (window of) token history,
    taking the argmax at the last real position. Deliberately simple:
    the speculative accept rule guarantees output parity with plain
    decode for ANY proposer, so the draft model only has to be cheap
    and usually-right, not exact.

    ``predictor``: ``run``/``fetch_names`` over a full-program build
    (``transformer_lm``; fetch the ``logits`` extra). ``seq_len``: the
    program's sequence length — longer histories are drafted from their
    last ``seq_len`` tokens (a sliding window; quality detail only).
    ``ladder``: pow2 batch rungs the draft batch is padded to, bounding
    the draft program's own compile cache."""

    def __init__(self, predictor, logits_fetch, seq_len, ids_feed="ids",
                 lbl_feed="lbl", ladder=(1, 2, 4, 8)):
        self._pred = predictor
        self._logits_idx = list(predictor.fetch_names).index(logits_fetch)
        self.seq_len = int(seq_len)
        self.ids_feed = ids_feed
        self.lbl_feed = lbl_feed
        self.ladder = tuple(sorted(set(ladder)))

    def propose(self, histories, n):
        """``n`` greedy continuations for each token history. Returns a
        list of n-token lists, one per history."""
        hists = [list(h) for h in histories]
        out = [[] for _ in histories]
        for _ in range(int(n)):
            b = bucket_for(len(hists), self.ladder) \
                if len(hists) <= max(self.ladder) else len(hists)
            ids = np.zeros((b, self.seq_len), np.int64)
            lens = []
            for j, h in enumerate(hists):
                t = h[-self.seq_len:]
                ids[j, :len(t)] = t
                lens.append(len(t))
            feed = {self.ids_feed: ids}
            if self.lbl_feed:
                feed[self.lbl_feed] = ids
            outs = self._pred.run(feed, return_numpy=False)
            logits = np.asarray(outs[self._logits_idx])
            for j, fill in enumerate(lens):
                t = int(np.argmax(logits[j, fill - 1]))
                out[j].append(t)
                hists[j].append(t)
        return out


def save_decode_spec(dirname, spec):
    """Write a step builder's decode-spec dict next to a
    ``save_inference_model`` export, so ``ServingEngine(dir, decode=True)``
    can serve continuous-batching decode straight from the directory."""
    import json
    import os

    path = os.path.join(dirname, DECODE_SPEC_FILE)
    with open(path, "w") as f:
        json.dump(spec, f, indent=1)
    return path


def load_decode_spec(dirname):
    import json
    import os

    with open(os.path.join(dirname, DECODE_SPEC_FILE)) as f:
        return json.load(f)


class DecodeRequest:
    """One decode request: ``prompt`` (1-D int token ids, non-empty),
    ``max_new_tokens``, optional ``eos_id`` (stop token, also emitted),
    the caller's future (resolves to the generated ids as int64 ndarray),
    and the admission timestamps the TTFT/TPOT metrics read."""

    __slots__ = ("prompt", "max_new", "eos_id", "future", "enqueue_t",
                 "deadline", "n_ctx", "prefix")

    def __init__(self, prompt, max_new, eos_id, future, enqueue_t,
                 deadline=None, prefix=None):
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.future = future
        self.enqueue_t = enqueue_t
        self.deadline = deadline
        # pinned PrefixEntry matched at submit (cloned + released at
        # admission), or None
        self.prefix = prefix
        # cache capacity this request needs: every prompt token is written
        # once, then at most max_new-1 generated tokens are fed back (the
        # last sampled token never re-enters the cache), so the highest
        # index written is prompt+max_new-2
        self.n_ctx = len(self.prompt) + self.max_new - 1

    def __repr__(self):
        return ("DecodeRequest(prompt=%d toks, max_new=%d)"
                % (len(self.prompt), self.max_new))


class _Slot:
    """One occupied slot-table row."""

    __slots__ = ("req", "pos", "k", "out", "next_token", "first_tok_t",
                 "harvested")

    def __init__(self, req):
        self.req = req
        # a matched prefix starts the slot past its cloned rows: the
        # first m tokens are already in the cache, so ingestion resumes
        # at prompt[m] (m <= len(prompt)-1 — the last token always feeds
        # through the model to produce first-generation logits)
        m = req.prefix.length if req.prefix is not None else 0
        self.pos = m            # next cache index == tokens ingested
        self.k = m + 1          # prompt cursor: prompt[m] feeds first
        self.out = []           # generated ids
        self.next_token = req.prompt[m]
        self.first_tok_t = None
        self.harvested = False  # this prompt's rows offered to the cache

    @property
    def forcing(self):
        """Still ingesting prompt tokens (logits ignored)."""
        return self.k < len(self.req.prompt)


class DecodeBatcher:
    """The continuous batcher. Same client surface as ``ServingEngine``
    (``submit``/``predict``/``metrics``/``warmup``/``shutdown``) so the
    engine can put it behind one API.

    ``predictor``: anything with ``run(feed, return_numpy=False) -> list``
    in fetch order and ``fetch_names`` — a ``Predictor`` over a saved
    step-program dir, an in-process ``ProgramPredictor``, or a test fake.
    ``spec``: the decode-spec dict a step builder returns
    (``models.transformer.transformer_lm_step``): token/pos feed names,
    logits fetch, per-layer cache feed/fetch pairs with tail shapes.

    ``start=False`` skips the loop thread: tests then call
    :meth:`drive` to run the scheduler synchronously — fully
    deterministic, zero sleeps (the injectable-``clock`` contract the
    rest of the serving tier follows)."""

    def __init__(self, predictor, spec, ladder=None, ctx_ladder=None,
                 max_batch_size=8, max_queue_depth=256,
                 default_timeout_s=None, default_max_new_tokens=64,
                 eos_id=None, clock=None, metrics=None, start=True,
                 prefix_cache=None, prefill=None, speculative=None):
        self._predictor = predictor
        self._spec = dict(spec)
        self._tok_feed = self._spec["token_feed"]
        self._pos_feed = self._spec["pos_feed"]
        fetch_names = list(predictor.fetch_names)
        self._logits_idx = fetch_names.index(self._spec["logits_fetch"])
        self._cache_feeds = []
        for cf in self._spec["cache_feeds"]:
            self._cache_feeds.append(
                (cf["feed"], fetch_names.index(cf["fetch"]),
                 tuple(cf["tail"]), np.dtype(cf.get("dtype", "float32"))))
        self.ladder = tuple(sorted(set(
            ladder if ladder is not None else pow2_ladder(max_batch_size))))
        if ctx_ladder is None:
            ctx_ladder = default_ctx_ladder(self._spec)
        self.ctx_ladder = tuple(sorted(set(int(c) for c in ctx_ladder)))
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.default_timeout_s = default_timeout_s
        self.eos_id = eos_id
        self._clock = clock or time.monotonic
        self._admission = AdmissionController(max_queue_depth)
        if metrics is not None:
            # shared instance (the engine's): the OWNER binds aggregate
            # gauges across every batcher — binding here would leave the
            # gauges reading whichever replica bound last
            self.metrics_ = metrics
        else:
            self.metrics_ = ServingMetrics()
            self.metrics_.bind_gauges(lambda: len(self._pending),
                                      lambda: self._admission.in_flight)

        # -- prefix cache (optional): True / kwargs dict builds an owned
        # instance; a PrefixCache instance is shared (the engine's)
        self.prefix_cache = None
        # NOT a truthiness test: an EMPTY PrefixCache is len()==0/falsy
        if prefix_cache is not None and prefix_cache is not False:
            if isinstance(prefix_cache, PrefixCache):
                self.prefix_cache = prefix_cache
            else:
                kw = (dict(prefix_cache) if isinstance(prefix_cache, dict)
                      else {})
                kw.setdefault("metrics", self.metrics_)
                self.prefix_cache = PrefixCache(**kw)
                if metrics is None:
                    self.metrics_.bind_prefix_bytes(
                        lambda: self.prefix_cache.nbytes)

        # -- chunked prefill / speculative verify (optional): the chunk
        # program must share the step program's cache feed names so the
        # carried cache dict feeds both
        self._prefill = None
        self.prefill_ladder = ()
        if prefill is not None:
            p = dict(prefill)
            cpred = p["predictor"]
            cspec = dict(p["spec"])
            cfetch = list(cpred.fetch_names)
            step_feeds = {cf["feed"] for cf in self._spec["cache_feeds"]}
            cmap = []
            for cf in cspec["cache_feeds"]:
                if cf["feed"] not in step_feeds:
                    raise ValueError(
                        "chunk cache feed %r has no step-program "
                        "counterpart — the chunk program must share the "
                        "step's cache feed names" % cf["feed"])
                cmap.append((cf["feed"], cfetch.index(cf["fetch"])))
            if len(cmap) != len(step_feeds):
                raise ValueError(
                    "chunk program covers %d of the step program's %d "
                    "cache feeds" % (len(cmap), len(step_feeds)))
            pl = p.get("ladder")
            if pl is None:
                pl = default_prefill_ladder(self._spec)
            self.prefill_ladder = tuple(sorted(set(int(k) for k in pl)))
            self._prefill = {
                "pred": cpred, "tok": cspec["token_feed"],
                "pos": cspec["pos_feed"],
                "logits_idx": cfetch.index(cspec["logits_fetch"]),
                "cache_map": cmap}
        self._alt_chunk = False

        # -- speculative decode (optional, rides the chunk program)
        self._draft = None
        self._spec_k = 0
        if speculative is not None:
            if self._prefill is None:
                raise ValueError("speculative decode needs the chunk "
                                 "program (pass prefill= as well)")
            s = dict(speculative)
            self._draft = s["draft"]
            k = int(s.get("k", 4))
            if k < 2:
                raise ValueError("speculative k must be >= 2 "
                                 "(k-1 drafts + the committed token)")
            self._spec_k = k

        self._pending = deque()
        self._slots = []          # list[_Slot | None], len == bucket_batch
        self._caches = {}         # feed name -> [B, C, *tail] array
        self._bucket = (0, 0)     # (bucket_batch, bucket_ctx)
        self.seen_signatures = set()
        self._cv = threading.Condition()
        self._closed = False
        self._aborted = False
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="paddle-tpu-decode", daemon=True)
            self._thread.start()

    # -- client surface -----------------------------------------------------
    def now(self):
        return self._clock()

    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               timeout_s=None):
        """Enqueue one decode request; returns a Future resolving to the
        generated token ids (int64 ndarray, eos included when hit).
        Raises ``BucketError`` when prompt+max_new exceeds the top ctx
        rung, ``ServerOverloadedError`` when the bounded queue is full."""
        if self._closed:
            raise RuntimeError("DecodeBatcher is shut down")
        prompt = np.asarray(prompt).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must hold at least one token")
        max_new = (int(max_new_tokens) if max_new_tokens is not None
                   else self.default_max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        eos = eos_id if eos_id is not None else self.eos_id
        # matches DecodeRequest.n_ctx: the last sampled token never
        # re-enters the cache
        n_ctx = int(prompt.size) + max_new - 1
        bucket_for(n_ctx, self.ctx_ladder)  # validates at the door
        timeout_s = (timeout_s if timeout_s is not None
                     else self.default_timeout_s)
        now = self._clock()
        deadline = now + timeout_s if timeout_s is not None else None
        self._admission.acquire(1)
        prefix = None
        if self.prefix_cache is not None and prompt.size > 1:
            # match capped at len-1: the last prompt token must feed
            # through the step to produce first-generation logits
            prefix = self.prefix_cache.lookup(prompt,
                                              limit=int(prompt.size) - 1)
        req = DecodeRequest(prompt, max_new, eos, Future(), now,
                            deadline=deadline, prefix=prefix)
        with self._cv:
            if self._closed:
                self._admission.release(1)
                self._release_prefix(req)
                raise RuntimeError("DecodeBatcher is shut down")
            self._pending.append(req)
            self._cv.notify_all()
        return req.future

    def predict(self, prompt, max_new_tokens=None, eos_id=None,
                timeout_s=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           eos_id=eos_id,
                           timeout_s=timeout_s).result(timeout_s)

    def metrics(self):
        return self.metrics_.snapshot()

    def metrics_report(self):
        return self.metrics_.report()

    def compiled_shape_counts(self):
        """Distinct step ``(bucket_batch, bucket_ctx)`` and chunk
        ``(bucket_batch, bucket_ctx, chunk_rung)`` geometries dispatched
        — bounded at :meth:`compile_cache_bound` by construction."""
        return [len(self.seen_signatures)]

    def compile_cache_bound(self):
        """The PROVED executable-count bound (ISSUE 15): the static
        compile-cache verdict from the decode spec — dispatched
        geometries (:meth:`compiled_shape_counts`) can never exceed it.
        With a chunk program attached the bound covers the prefill
        ladder too: ``len(ladder) * len(ctx_ladder) *
        (1 + len(prefill_ladder))``."""
        from ..analysis.resources import decode_cache_verdict

        bound, _result = decode_cache_verdict(
            self._spec, self.ladder, self.ctx_ladder,
            prefill_ladder=self.prefill_ladder)
        return bound

    def warmup(self):
        """Pre-compile every (batch rung, ctx rung) step geometry — and,
        when a chunk program rides along, every (batch, ctx, chunk rung)
        chunk geometry — with a zero-token synthetic dispatch, so live
        traffic never compiles. Returns the number of geometries
        warmed."""
        warmed = 0
        for b in self.ladder:
            for c in self.ctx_ladder:
                feed = self._synth_feed(b, c)
                self._predictor.run(feed, return_numpy=False)
                self.seen_signatures.add((b, c))
                warmed += 1
                if self._prefill is not None:
                    for k in self.prefill_ladder:
                        cfeed = self._synth_chunk_feed(b, c, k)
                        self._prefill["pred"].run(cfeed,
                                                  return_numpy=False)
                        self.seen_signatures.add((b, c, k))
                        warmed += 1
        return warmed

    def drive(self, max_steps=None):
        """Run the scheduler loop synchronously on the CALLING thread
        until idle (or ``max_steps`` decode steps). Only valid with
        ``start=False`` — the deterministic test/bench mode. Returns the
        number of steps executed."""
        if self._thread is not None:
            raise RuntimeError("drive() requires start=False "
                               "(the loop thread owns the slot table)")
        steps = 0
        while max_steps is None or steps < max_steps:
            self._admit()
            if not any(s is not None for s in self._slots):
                break
            self._tick()
            steps += 1
        return steps

    def shutdown(self, drain=True, timeout_s=None):
        """Stop intake. ``drain=True`` serves everything already
        submitted — queued requests included — to completion;
        ``drain=False`` aborts: in-flight generation and queued requests
        both fail with :class:`EngineShutdownError`."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._aborted = not drain
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout_s if timeout_s is not None else 30.0)
        else:
            if drain:
                while True:
                    self._admit()
                    if not any(s is not None for s in self._slots):
                        break
                    self._tick()
            else:
                self._abort_live()
        self._fail_pending()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    # -- scheduler ----------------------------------------------------------
    def _loop(self):
        while True:
            with self._cv:
                while (not self._closed and not self._pending
                       and not any(s is not None for s in self._slots)):
                    self._cv.wait()
                if self._closed:
                    if self._aborted:
                        break
                    if (not any(s is not None for s in self._slots)
                            and not self._pending):
                        break
            try:
                self._admit()
                if any(s is not None for s in self._slots):
                    self._tick()
                elif self._closed:
                    break
            except BaseException as e:  # noqa: BLE001 — fail loudly, once
                self._poison(e)
                return
        if self._aborted:
            self._abort_live()

    def _poison(self, exc):
        """The step function itself threw (a replica fault, not a request
        fault): fail everything in flight — a decode loop cannot retry
        mid-sequence without replaying the whole cache."""
        for i, slot in enumerate(self._slots):
            if slot is not None:
                self._resolve_exc(slot.req, exc)
                self._slots[i] = None
        self._fail_pending(exc)

    def _fail_pending(self, exc=None):
        with self._cv:
            pending = list(self._pending)
            self._pending.clear()
        for req in pending:
            self._resolve_exc(req, exc or EngineShutdownError(
                "DecodeBatcher shut down before this request started"))
            self.metrics_.observe_failed()

    def _abort_live(self):
        for i, slot in enumerate(self._slots):
            if slot is not None:
                self._resolve_exc(slot.req, EngineShutdownError(
                    "DecodeBatcher aborted mid-generation"))
                self.metrics_.observe_failed()
                self._slots[i] = None

    def _release_prefix(self, req):
        """Drop a request's pinned prefix entry (cloned, failed, or
        expired — the pin must never outlive the request)."""
        if self.prefix_cache is not None and req.prefix is not None:
            self.prefix_cache.release(req.prefix.entry)
            req.prefix = None

    def _resolve_exc(self, req, exc):
        self._release_prefix(req)
        try:
            req.future.set_exception(exc)
        except Exception:
            pass
        self._admission.release(1)

    # admission + re-bucketing — runs BETWEEN steps only (slot recycling)
    def _admit(self):
        now = self._clock()
        admitted = []
        with self._cv:
            live = sum(1 for s in self._slots if s is not None)
            room = max(self.ladder) - live
            while self._pending and room > 0:
                req = self._pending.popleft()
                if req.deadline is not None and now > req.deadline:
                    self._resolve_exc(req, DeadlineExceededError(
                        "request waited %.1f ms, deadline was %.1f ms"
                        % ((now - req.enqueue_t) * 1e3,
                           (req.deadline - req.enqueue_t) * 1e3)))
                    self.metrics_.observe_expired()
                    continue
                admitted.append(req)
                room -= 1
        if not admitted and self._bucket == self._target_bucket([]):
            return
        self._rebucket(admitted)

    def _target_bucket(self, admitting):
        live = [s.req for s in self._slots if s is not None]
        reqs = live + list(admitting)
        if not reqs:
            return (0, 0)
        b = bucket_for(len(reqs), self.ladder)
        c = bucket_for(max(r.n_ctx for r in reqs), self.ctx_ladder)
        return (b, c)

    def _rebucket(self, admitting):
        """Place admissions and re-shape the caches when the (batch, ctx)
        bucket moved.

        Same geometry: admitted requests drop into free HOLES — zero
        cache traffic, because a recycled row needs no cleaning (its new
        occupant starts at pos 0 and a row's attention mask never reaches
        past its own fill level, so the previous occupant's leftovers are
        unreachable). This is what keeps steady-state slot recycling off
        the host.

        Geometry moved (occupancy crossed a ladder rung, or a longer
        request raised the ctx rung): live rows compact into fresh
        zero arrays — the one host-side copy re-bucketing costs."""
        new_b, new_c = self._target_bucket(admitting)
        if (new_b, new_c) == self._bucket:
            if admitting:
                free = [i for i, s in enumerate(self._slots) if s is None]
                for req, i in zip(admitting, free):
                    self._slots[i] = _Slot(req)
                    self._install_prefix(i, req)
            return
        old_c = self._bucket[1]
        live = [(i, s) for i, s in enumerate(self._slots) if s is not None]
        new_slots = [s for _, s in live]
        for req in admitting:
            new_slots.append(_Slot(req))
        new_slots += [None] * (new_b - len(new_slots))
        copy_c = min(old_c, new_c)
        for feed, _idx, tail, dtype in self._cache_feeds:
            old = self._caches.get(feed)
            new = np.zeros((new_b, new_c) + tail, dtype)
            if old is not None and live:
                old = np.asarray(old)
                for j, (i, _s) in enumerate(live):
                    new[j, :copy_c] = old[i, :copy_c]
            self._caches[feed] = new
        self._slots = new_slots
        self._bucket = (new_b, new_c)
        for j, req in enumerate(admitting, start=len(live)):
            self._install_prefix(j, req)

    def _install_prefix(self, i, req):
        """Clone a matched prefix's leading rows into slot row ``i`` and
        release the pin. CLONE, never alias: after this the slot owns
        its rows, so evicting the entry can never corrupt the slot."""
        match = req.prefix
        if match is None:
            return
        m = match.length
        for feed, _idx, _tail, _dtype in self._cache_feeds:
            rows = match.entry.rows.get(feed)
            if rows is None:
                continue
            rows = np.asarray(rows)[:m]
            cache = self._caches[feed]
            if isinstance(cache, np.ndarray):
                cache[i, :m] = rows
            else:  # device-resident jax array: one device-side copy
                self._caches[feed] = cache.at[i, :m].set(rows)
        self._release_prefix(req)

    def _synth_feed(self, b, c):
        feed = {self._tok_feed: np.zeros((b,), np.int64),
                self._pos_feed: np.zeros((b,), np.int32)}
        for name, _idx, tail, dtype in self._cache_feeds:
            feed[name] = np.zeros((b, c) + tail, dtype)
        return feed

    def _tick(self):
        """One scheduler quantum: a chunk dispatch (prefill and/or
        speculative verify) when the chunk program has work, else one
        decode step. When some live rows can't ride the chunk (they are
        generating and speculation is off), chunk and step ticks
        ALTERNATE so a long prompt is ingested chunk-by-chunk without
        stalling its co-riders."""
        if self._prefill is None:
            self._step_once()
            return
        plan = self._chunk_plan()
        if plan is None:
            self._alt_chunk = False
            self._step_once()
            return
        rows, has_uncovered, verifying = plan
        if has_uncovered and self._alt_chunk:
            self._alt_chunk = False
            self._step_once()
            return
        self._alt_chunk = True
        with trace.span("spec.verify" if verifying
                        else "prefill.chunk") as sp:
            self._chunk_once(rows, sp)

    def _chunk_plan(self):
        """This tick's chunk rows as ``(rows, has_uncovered, verifying)``
        — or None when no live row wants the chunk program. Each row is
        ``(i, slot, tokens, n_forced)``: lane j of the chunk feeds
        ``tokens[j]`` at cache index ``slot.pos + j``; the first
        ``n_forced`` tokens are committed (prompt or already-emitted),
        the rest are speculative drafts judged against the chunk's own
        logits."""
        spec = self._spec_k > 0
        top = self.prefill_ladder[-1]
        ingest = []     # (i, slot, want) — rows with prompt left
        verify = []     # (i, slot) — generating rows (spec mode)
        uncovered = 0
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            pending = len(slot.req.prompt) - slot.pos
            if pending >= 2 or (spec and pending == 1):
                # plain prefill leaves the LAST prompt token for the
                # step program (sampling stays on the step path —
                # bitwise parity with plain decode); spec mode ingests
                # through it and samples from the chunk's own logits
                want = pending if spec else pending - 1
                ingest.append((i, slot, min(want, top)))
            elif spec:
                verify.append((i, slot))
            else:
                uncovered += 1
        if not ingest and not verify:
            return None
        rows = []
        for i, slot, want in ingest:
            toks = slot.req.prompt[slot.pos:slot.pos + want]
            rows.append((i, slot, toks, len(toks)))
        if verify:
            needs = []
            for i, slot in verify:
                real = min(self._spec_k,
                           slot.req.max_new - len(slot.out), top)
                needs.append(max(0, real - 1))
            drafts = self._draft_for([s for _i, s in verify], max(needs))
            for (i, slot), d, n in zip(verify, drafts, needs):
                rows.append((i, slot, [slot.next_token] + d[:n], 1))
        return rows, uncovered > 0, bool(verify)

    def _draft_for(self, slots, n):
        """``n`` draft continuations per generating slot, from the small
        draft LM over each slot's committed history. Any proposal is
        SAFE — the accept rule only ever emits the target model's own
        greedy chain — so draft failures degrade to 1-token progress
        rather than failing the tick."""
        if n <= 0:
            return [[] for _ in slots]
        hists = [s.req.prompt + s.out for s in slots]
        try:
            return self._draft.propose(hists, n)
        except Exception:
            return [[] for _ in slots]

    def _chunk_once(self, rows, sp):
        """Dispatch one chunk: K-token lanes per covered row, pad lanes
        carry the pad sentinel ``pos == bucket_ctx`` (their cache writes
        drop via the op's out-of-range mode and their logits are
        ignored). Commits forced tokens, then emits each verify row's
        greedy chain: drafts are accepted while they equal the chunk's
        own argmax, the first disagreement is replaced by the argmax
        itself (always >= 1 token of progress), and rejected lanes are
        REWOUND by pointer arithmetic — rows past a slot's fill level
        are unreachable by the attention mask, the same property slot
        recycling rests on."""
        pf = self._prefill
        b, c = self._bucket
        k = bucket_for(max(len(t) for _i, _s, t, _f in rows),
                       self.prefill_ladder)
        tok = np.zeros((b, k), np.int64)
        cpos = np.full((b, k), c, np.int32)
        for i, slot, tokens, _f in rows:
            n = len(tokens)
            tok[i, :n] = tokens
            cpos[i, :n] = np.arange(slot.pos, slot.pos + n, dtype=np.int32)
        feed = dict(self._caches)
        feed[pf["tok"]] = tok
        feed[pf["pos"]] = cpos
        outs = pf["pred"].run(feed, return_numpy=False)
        self.seen_signatures.add((b, c, k))
        for name, idx in pf["cache_map"]:
            self._caches[name] = outs[idx]
        greedy = None
        now = self._clock()
        live = sum(1 for s in self._slots if s is not None)
        generated = 0
        accepted = rejected = 0
        chunk_rows = 0
        chunk_toks = 0
        for i, slot, tokens, n_forced in rows:
            real = len(tokens)
            base = slot.pos
            L = len(slot.req.prompt)
            ingested = max(0, min(L - base, n_forced))
            if ingested:
                chunk_rows += 1
                chunk_toks += ingested
            if n_forced == real and base + real < L:
                # pure prompt ingestion, prompt not finished
                slot.pos = base + real
                slot.k = slot.pos + 1
                slot.next_token = slot.req.prompt[slot.pos]
                continue
            # the chunk covered through the last prompt token (spec
            # prefill) or this is a verify row: emit the greedy chain
            if greedy is None:
                greedy = np.argmax(
                    np.asarray(outs[pf["logits_idx"]]), axis=-1)
            req = slot.req
            j = n_forced - 1
            emitted = [int(greedy[i, j])]
            while (j + 1 < real
                   and len(slot.out) + len(emitted) < req.max_new
                   and (req.eos_id is None or emitted[-1] != req.eos_id)
                   and tokens[j + 1] == emitted[-1]):
                j += 1
                emitted.append(int(greedy[i, j]))
            if n_forced < real:
                accepted += j - (n_forced - 1)
                rejected += (real - n_forced) - (j - (n_forced - 1))
            slot.pos = base + j + 1
            for t in emitted:
                slot.out.append(t)
                generated += 1
                if slot.first_tok_t is None:
                    slot.first_tok_t = now
                    self.metrics_.observe_ttft(now - req.enqueue_t)
            if not slot.harvested and slot.pos >= L:
                self._maybe_harvest(i, slot)
            done = (len(slot.out) >= req.max_new
                    or (req.eos_id is not None
                        and slot.out[-1] == req.eos_id))
            if done:
                self._retire(i, slot, now)
            else:
                slot.next_token = slot.out[-1]
        if chunk_rows:
            self.metrics_.observe_prefill_chunk(chunk_rows, chunk_toks)
        if accepted or rejected:
            self.metrics_.observe_spec(accepted, rejected)
        self.metrics_.observe_decode_step(live, b, generated)
        if sp:
            sp.set(live=live, bucket=b, ctx=c, chunk=k,
                   generated=generated, accepted=accepted,
                   rejected=rejected)

    def _maybe_harvest(self, i, slot):
        """First full ingestion of this prompt: offer its KV rows [0:L]
        to the prefix cache (one host copy per request, once; the cache
        keeps them only if the exact prompt isn't already an entry)."""
        slot.harvested = True
        if self.prefix_cache is None:
            return
        key = slot.req.prompt
        if len(key) < 2 or key in self.prefix_cache:
            return
        rows = {}
        for feed, _idx, _tail, _dtype in self._cache_feeds:
            rows[feed] = np.array(np.asarray(self._caches[feed])[i,
                                                                 :len(key)])
        self.prefix_cache.insert(key, rows)

    def _synth_chunk_feed(self, b, c, k):
        pf = self._prefill
        feed = {pf["tok"]: np.zeros((b, k), np.int64),
                pf["pos"]: np.full((b, k), c, np.int32)}
        for name, _idx, tail, dtype in self._cache_feeds:
            feed[name] = np.zeros((b, c) + tail, dtype)
        return feed

    def _step_once(self):
        with trace.span("decode.step") as sp:
            self._step_once_traced(sp)

    def _step_once_traced(self, sp):
        b, c = self._bucket
        toks = np.zeros((b,), np.int64)
        pos = np.zeros((b,), np.int32)
        for i, slot in enumerate(self._slots):
            if slot is not None:
                toks[i] = slot.next_token
                pos[i] = slot.pos
        feed = dict(self._caches)
        feed[self._tok_feed] = toks
        feed[self._pos_feed] = pos
        outs = self._predictor.run(feed, return_numpy=False)
        sig = (b, c)
        self.seen_signatures.add(sig)
        # carried state: fetched cache arrays feed the next step as-is
        # (device-resident jax arrays round-trip through the feed dict
        # without touching the host)
        for name, idx, _tail, _dtype in self._cache_feeds:
            self._caches[name] = outs[idx]
        logits = np.asarray(outs[self._logits_idx])
        now = self._clock()
        live = 0
        generated = 0
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            live += 1
            slot.pos += 1
            if slot.forcing:
                slot.next_token = slot.req.prompt[slot.k]
                slot.k += 1
                continue
            if not slot.harvested and slot.pos >= len(slot.req.prompt):
                self._maybe_harvest(i, slot)
            nxt = int(np.argmax(logits[i]))
            generated += 1
            slot.out.append(nxt)
            if slot.first_tok_t is None:
                slot.first_tok_t = now
                self.metrics_.observe_ttft(now - slot.req.enqueue_t)
            done = (len(slot.out) >= slot.req.max_new
                    or (slot.req.eos_id is not None
                        and nxt == slot.req.eos_id))
            if done:
                self._retire(i, slot, now)
            else:
                slot.next_token = nxt
        self.metrics_.observe_decode_step(live, b, generated)
        if sp:
            # slot occupancy rides on every step span (ISSUE 17)
            sp.set(live=live, bucket=b, ctx=c, generated=generated)

    def _retire(self, i, slot, now):
        """Finished sequence: resolve, free the slot IMMEDIATELY (the
        next ``_admit`` recycles it — no drain barrier)."""
        self._slots[i] = None
        if len(slot.out) > 1:
            self.metrics_.observe_tpot(
                (now - slot.first_tok_t) / (len(slot.out) - 1))
        try:
            slot.req.future.set_result(np.asarray(slot.out, np.int64))
        except Exception:
            pass
        self.metrics_.observe_completed(now - slot.req.enqueue_t)
        self._admission.release(1)

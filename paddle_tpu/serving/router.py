"""Multi-process serving front door: a socket router over N workers.

The reference scales serving by putting processes behind gRPC (pserver
topology: ``distribute_transpiler.py:161`` + ``listen_and_serv``; SURVEY
§3.3) — the process boundary is the scaling unit AND the blast-radius
unit. This module is that boundary for the serving tier, built as a
*reliability* component on the PR-3 machinery rather than a dumb proxy:

  * **Admission at the door** — a bounded in-router budget
    (``max_queue_depth``); a full door EDF-sheds an admitted-but-
    undispatched request with a strictly later deadline (mirroring
    ``DynamicBatcher.shed_for``) or answers a typed
    ``ServerOverloadedError``. Overload is a first-class answer, never
    an unbounded queue.
  * **Deadline propagation** — the client stamps ``deadline_s``
    (remaining budget) into the frame; the router re-derives a local
    :class:`~paddle_tpu.reliability.policy.Deadline`, burns queue time
    against it, and forwards the *recomputed* remainder to the worker —
    which refuses already-expired work without executing it
    (``deadline_refused``). Expired requests are deliberately NOT
    dropped at the router: the refusal at the worker is the proof the
    budget made the full trip.
  * **Health-checked workers** — a heartbeat thread pings every worker
    (fault site ``worker.heartbeat``); misses and dispatch failures feed
    a per-worker :class:`~paddle_tpu.reliability.policy.CircuitBreaker`;
    a tripped breaker or dead process marks the worker unhealthy and
    schedules a **respawn** on a
    :class:`~paddle_tpu.reliability.policy.RetryPolicy` backoff
    schedule.
  * **No silent loss** — a request whose dispatch hop fails (connection
    torn, worker SIGKILLed mid-request, injected ``router.dispatch``
    fault) gets exactly ONE cross-worker retry (``rerouted``) and then a
    typed :class:`WorkerFailedError`. Every accepted frame is answered.

Routing is least-loaded by live in-flight count (heartbeat-reported
engine depth breaks ties) or consistent-hash on a caller-supplied
``key`` (md5 ring with virtual nodes — sticky sessions that survive a
respawn); a skipped first choice counts ``rerouted``.

Wire protocol and framing live in :mod:`paddle_tpu.serving.rpc`; the
worker half in :mod:`paddle_tpu.serving.worker`. Everything is stdlib —
the gRPC plane of the reference collapses to length-prefixed JSON+npz
frames over TCP.

Quickstart::

    router = Router("builtin:fc", num_workers=2)
    router.start()
    client = RouterClient(router.address)
    out = client.predict({"x": np.zeros((1, 8), "float32")},
                         timeout_s=2.0)
    client.close(); router.shutdown()

or standalone::

    python -m paddle_tpu.serving.router --model path/to/model --workers 4
"""

import argparse
import hashlib
import json
import os
import select
import signal
import socketserver
import subprocess
import sys
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..distributed.launch import reap_procs
from ..obs import flight, trace
from ..reliability import faults
from ..reliability.policy import CircuitBreaker, Deadline, RetryError, \
    RetryPolicy
from . import rpc
from .admission import DeadlineExceededError, ServerOverloadedError
from .metrics import ServingMetrics
from .worker import READY_PREFIX

__all__ = ["Router", "RouterClient", "WorkerFailedError",
           "RouterShutdownError", "main"]

ROUTER_READY_PREFIX = "PADDLE_TPU_ROUTER_READY "

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class WorkerFailedError(RuntimeError):
    """The dispatch hop failed and the one cross-worker retry did too
    (or no healthy worker existed) — the typed end of the no-silent-loss
    guarantee, never a hang."""


class RouterShutdownError(RuntimeError):
    """The router is closing; the request was not executed."""


class _Entry:
    """One admitted request's door state, guarded by ``Router._cv``.
    ``deadline_key`` orders EDF shedding (None budget = +inf = most
    sheddable); ``shed`` is flipped by a displacing arrival and observed
    by the owning handler thread."""

    __slots__ = ("deadline", "shed")

    def __init__(self, deadline):
        self.deadline = deadline
        self.shed = False

    def deadline_key(self):
        if self.deadline is None:
            return float("inf")
        return self.deadline.remaining()


class _WorkerHandle:
    """One supervised worker process: its Popen, announced address,
    breaker, live in-flight count, idle-socket pool, and a ring buffer
    of its recent stdout for postmortems."""

    def __init__(self, index, breaker):
        self.index = index
        self.breaker = breaker
        self.proc = None
        self.address = None
        self.pid = None
        self.in_flight = 0
        self.healthy = False
        self.draining = False
        self.respawning = False
        self.restarts = 0
        self.hb_misses = 0
        self.stats = {}
        self.generation = 0
        self.sockets = deque()
        self.sockets_lock = threading.Lock()
        self.tail = deque(maxlen=50)

    def close_sockets(self):
        with self.sockets_lock:
            socks, self.sockets = list(self.sockets), deque()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass


class Router:
    """Front-door process manager + request router. ``start()`` spawns
    ``num_workers`` worker processes (each one :class:`ServingEngine`),
    binds the client-facing server on ``(host, port)`` — port 0, the
    default, binds ephemeral; read ``.address`` after ``start()`` — and
    runs the heartbeat/supervision loop until ``shutdown()``.

    ``model`` is passed through to the workers (saved-model dir or
    ``builtin:<name>``). ``routing`` is ``"least_loaded"`` (default) or
    ``"hash"`` (consistent-hash on the request ``key`` header).
    ``worker_args`` appends raw CLI args to every worker (e.g.
    ``["--replicas", "2"]``); ``worker_env`` overlays the child env
    (e.g. ``{"PADDLE_TPU_FAULTS": "predictor.run:error@1"}`` to chaos
    one whole tier). ``respawn_policy`` is the
    :class:`~paddle_tpu.reliability.policy.RetryPolicy` for restart
    backoff; exhausting it leaves the worker down (the rest of the fleet
    keeps serving)."""

    def __init__(self, model, num_workers=1, host="127.0.0.1", port=0,
                 max_queue_depth=64, inflight_per_worker=32,
                 routing="least_loaded", hash_vnodes=16,
                 heartbeat_interval_s=0.5, heartbeat_timeout_s=2.0,
                 max_heartbeat_misses=3, breaker_threshold=3,
                 respawn_policy=None, worker_args=None, worker_env=None,
                 spawn_timeout_s=120.0, queue_wait_timeout_s=30.0,
                 clock=None):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if routing not in ("least_loaded", "hash"):
            raise ValueError("routing must be 'least_loaded' or 'hash', "
                             "got %r" % (routing,))
        faults.maybe_install_from_env()
        self.model = model
        self.num_workers = int(num_workers)
        self.host = host
        self.port = int(port)
        self.max_queue_depth = int(max_queue_depth)
        self.inflight_per_worker = int(inflight_per_worker)
        self.routing = routing
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.max_heartbeat_misses = int(max_heartbeat_misses)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.queue_wait_timeout_s = float(queue_wait_timeout_s)
        self.worker_args = list(worker_args or [])
        self.worker_env = dict(worker_env or {})
        self.respawn_policy = respawn_policy or RetryPolicy(
            max_attempts=3, base_delay_s=0.2, max_delay_s=5.0)
        self.clock = clock or time.monotonic
        self.metrics_ = ServingMetrics()

        self._cv = threading.Condition()
        self._entries = set()        # admitted, undispatched (EDF pool)
        self._dispatched = 0
        self._closed = False
        self._workers = [
            _WorkerHandle(i, CircuitBreaker(
                failure_threshold=breaker_threshold,
                reset_timeout_s=self.heartbeat_interval_s,
                clock=self.clock))
            for i in range(self.num_workers)
        ]
        self._ring = self._build_ring(hash_vnodes)
        self._server = None
        self._server_thread = None
        self._health_thread = None
        self._stop = threading.Event()
        self._respawn_threads = []
        self.metrics_.bind_gauges(lambda: len(self._entries),
                                  lambda: self._dispatched)

    # -- process management -------------------------------------------------

    def _spawn_cmd(self):
        return [sys.executable, "-u", "-m", "paddle_tpu.serving.worker",
                "--model", str(self.model), "--host", self.host,
                "--port", "0", *self.worker_args]

    def _spawn_env(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.update(self.worker_env)
        return env

    def _spawn_worker(self, w):
        """Start one worker process and block until its READY line (or
        raise). Called at start() and from the respawn path."""
        proc = subprocess.Popen(
            self._spawn_cmd(), env=self._spawn_env(),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        deadline = time.monotonic() + self.spawn_timeout_s
        address = None
        while True:
            if proc.poll() is not None:
                reap_procs([proc], grace_s=1.0)
                raise WorkerFailedError(
                    "worker %d exited %s before READY; tail: %s"
                    % (w.index, proc.returncode, list(w.tail)[-5:]))
            budget = deadline - time.monotonic()
            if budget <= 0:
                reap_procs([proc], grace_s=1.0)
                raise WorkerFailedError(
                    "worker %d not READY within %.0fs"
                    % (w.index, self.spawn_timeout_s))
            ready, _, _ = select.select([proc.stdout], [], [],
                                        min(budget, 0.5))
            if not ready:
                continue
            line = proc.stdout.readline()
            if not line:
                continue
            w.tail.append(line.rstrip())
            if line.startswith(READY_PREFIX):
                info = json.loads(line[len(READY_PREFIX):])
                address = (self.host, int(info["port"]))
                break
        with self._cv:
            w.proc = proc
            w.address = address
            w.pid = proc.pid
            w.healthy = True
            w.hb_misses = 0
            w.generation += 1
            w.breaker.reset()
            self._cv.notify_all()
        t = threading.Thread(target=self._drain_stdout, args=(w, proc),
                             daemon=True,
                             name="router-stdout-%d" % w.index)
        t.start()

    def _drain_stdout(self, w, proc):
        # keep the child's pipe from filling (a full pipe blocks the
        # worker's prints) and keep a postmortem tail
        try:
            for line in proc.stdout:
                w.tail.append(line.rstrip())
        except (OSError, ValueError):
            pass

    def _schedule_respawn(self, w, why):
        """Restart ``w`` on the RetryPolicy schedule, off-thread. The
        worker serves no traffic (``healthy=False``) until READY again;
        its in-flight requests fail their hop and take the cross-worker
        retry path."""
        with self._cv:
            if self._closed or w.respawning:
                return
            w.respawning = True
            w.healthy = False
            self._cv.notify_all()
        w.close_sockets()

        def _run():
            def _attempt():
                if self._closed:
                    raise RouterShutdownError("router closed mid-respawn")
                reap_procs([w.proc], grace_s=2.0)
                self._spawn_worker(w)

            try:
                self.respawn_policy.call(
                    _attempt, retry_on=(WorkerFailedError,))
                with self._cv:
                    w.restarts += 1
                    w.respawning = False
                    self._cv.notify_all()
                self.metrics_.observe_respawn()
                flight.record("worker.respawn", worker=w.index, why=why)
            except (RetryError, RouterShutdownError) as e:
                # budget spent: the worker stays down, the rest of the
                # fleet keeps serving; operators see it in metrics()
                with self._cv:
                    w.respawning = False
                w.tail.append("respawn gave up (%s): %r" % (why, e))
                flight.record("worker.respawn_gave_up", worker=w.index,
                              why=why, error=repr(e)[:200])

        t = threading.Thread(target=_run, daemon=True,
                             name="router-respawn-%d" % w.index)
        t.start()
        self._respawn_threads.append(t)

    # -- health -------------------------------------------------------------

    def _ping_worker(self, w):
        sock = rpc.connect(w.address, timeout=self.heartbeat_timeout_s)
        try:
            rpc.send_msg(sock, {"type": "ping"})
            header, _ = rpc.recv_msg(sock)
            if header.get("type") != "pong":
                raise rpc.RpcError("bad ping reply: %r" % header)
            return header.get("stats", {})
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _health_loop(self):
        while not self._stop.wait(self.heartbeat_interval_s):
            for w in self._workers:
                if self._stop.is_set():
                    return
                with self._cv:
                    if w.respawning:
                        continue
                    proc = w.proc
                if proc is not None and proc.poll() is not None:
                    # the process is DEAD (crash/SIGKILL) — no need to
                    # wait for breaker consensus
                    self._schedule_respawn(
                        w, "process exited %s" % proc.returncode)
                    continue
                try:
                    faults.trip("worker.heartbeat")
                    stats = self._ping_worker(w)
                except (OSError, rpc.RpcError, faults.InjectedFault) as e:
                    self.metrics_.observe_heartbeat_miss()
                    with self._cv:
                        w.hb_misses += 1
                        misses = w.hb_misses
                        tripped = w.breaker.record_failure()
                    if tripped or misses >= self.max_heartbeat_misses:
                        self._schedule_respawn(
                            w, "heartbeat lost (%d misses, last %r)"
                               % (misses, e))
                else:
                    with self._cv:
                        w.hb_misses = 0
                        w.stats = stats
                        w.breaker.record_success()
                        if not w.healthy and not w.respawning:
                            w.healthy = True
                        self._cv.notify_all()

    # -- admission + routing ------------------------------------------------

    def _admit(self, deadline):
        """Door admission under ``_cv``: free slot, EDF displacement, or
        typed overload. Returns the admitted :class:`_Entry`."""
        entry = _Entry(deadline)
        with self._cv:
            if self._closed:
                raise RouterShutdownError("router is shut down")
            if len(self._entries) + self._dispatched \
                    < self.max_queue_depth:
                self._entries.add(entry)
                return entry
            # full door: displace the waiting request with the LATEST
            # deadline, and only if it is strictly later than ours —
            # EDF exactly like DynamicBatcher.shed_for, one layer out
            victim = None
            mine = entry.deadline_key()
            for e in self._entries:
                if e.shed:
                    continue
                if victim is None or e.deadline_key() \
                        > victim.deadline_key():
                    victim = e
            if victim is not None and victim.deadline_key() > mine:
                victim.shed = True
                self._entries.discard(victim)
                self._entries.add(entry)
                self.metrics_.observe_door_shed()
                flight.record("edf.shed", where="router.door")
                self._cv.notify_all()
                return entry
            self.metrics_.observe_rejected()
            raise ServerOverloadedError(
                "router door full (%d in flight)" % self.max_queue_depth)

    def _build_ring(self, vnodes):
        ring = []
        for i in range(self.num_workers):
            for v in range(vnodes):
                h = hashlib.md5(
                    ("%d:%d" % (i, v)).encode()).hexdigest()
                ring.append((h, i))
        ring.sort()
        return ring

    def _hash_order(self, key):
        """Worker indices in consistent-hash preference order for
        ``key``: the ring successor first, then successors of
        successors — a respawn moves no keys, a dead worker only moves
        its own."""
        h = hashlib.md5(str(key).encode()).hexdigest()
        seen, order = set(), []
        start = 0
        while start < len(self._ring) and self._ring[start][0] < h:
            start += 1
        for off in range(len(self._ring)):
            idx = self._ring[(start + off) % len(self._ring)][1]
            if idx not in seen:
                seen.add(idx)
                order.append(idx)
        return order

    def _eligible_locked(self, w, exclude):
        return (w is not exclude and w.healthy and not w.draining
                and not w.respawning
                and w.in_flight < self.inflight_per_worker)

    def _pick_locked(self, key, exclude):
        """Choose a worker (holding ``_cv``) or return None. Counts
        ``rerouted`` when a hash-preferred worker had to be skipped."""
        if self.routing == "hash" and key is not None:
            order = self._hash_order(key)
            for rank, idx in enumerate(order):
                w = self._workers[idx]
                if self._eligible_locked(w, exclude):
                    if rank > 0:
                        self.metrics_.observe_rerouted()
                    return w
            return None
        best = None
        for w in self._workers:
            if not self._eligible_locked(w, exclude):
                continue
            if best is None or w.in_flight < best.in_flight or (
                    w.in_flight == best.in_flight
                    and w.stats.get("queue_depth", 0)
                    < best.stats.get("queue_depth", 0)):
                best = w
        return best

    def _acquire(self, entry, key, exclude=None):
        """Block (bounded) until a worker slot is granted, the entry is
        shed, or the router closes. An EXPIRED deadline does not stop
        the grant — the worker is the one that refuses expired work, and
        ``deadline_refused`` is the proof the budget propagated."""
        t0 = self.clock()
        with self._cv:
            while True:
                if entry.shed:
                    raise ServerOverloadedError(
                        "shed at the door for an earlier deadline")
                if self._closed:
                    self._entries.discard(entry)
                    raise RouterShutdownError("router is shut down")
                w = self._pick_locked(key, exclude)
                if w is not None:
                    w.in_flight += 1
                    self._entries.discard(entry)
                    self._dispatched += 1
                    return w
                if self.clock() - t0 > self.queue_wait_timeout_s:
                    self._entries.discard(entry)
                    raise WorkerFailedError(
                        "no healthy worker within %.1fs"
                        % self.queue_wait_timeout_s)
                self._cv.wait(0.05)

    def _release(self, w):
        with self._cv:
            w.in_flight -= 1
            self._dispatched -= 1
            self._cv.notify_all()

    # -- dispatch -----------------------------------------------------------

    def _send_to_worker(self, w, header, arrays, deadline):
        """One hop: borrow/return a pooled connection, forward the frame
        with the RECOMPUTED remaining budget, read the reply. Raises
        OSError/RpcError/InjectedFault on a torn hop."""
        faults.trip("router.dispatch")
        fwd = dict(header)
        if deadline is not None:
            fwd["deadline_s"] = deadline.remaining()
        # re-parent the propagated trace onto OUR current span (the
        # dispatch span) so the worker's spans nest under this hop; with
        # router tracing off the client's context forwards verbatim,
        # since fwd already carries the original "trace" key
        trace.inject(fwd)
        with w.sockets_lock:
            sock = w.sockets.popleft() if w.sockets else None
        generation = w.generation
        if sock is None:
            sock = rpc.connect(w.address, timeout=self.spawn_timeout_s)
        try:
            rpc.send_msg(sock, fwd, arrays)
            reply = rpc.recv_msg(sock)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        if generation == w.generation:
            with w.sockets_lock:
                w.sockets.append(sock)
        else:
            sock.close()
        return reply

    def _hop_failed(self, w, exc):
        """Breaker bookkeeping for a failed dispatch hop; trips schedule
        a respawn."""
        with self._cv:
            tripped = w.breaker.record_failure()
        if tripped:
            self._schedule_respawn(w, "dispatch failures (%r)" % exc)

    def _dispatch(self, entry, header, arrays, deadline):
        """Admitted request -> reply, with the one cross-worker retry.
        Always returns a reply pair; typed errors, never silence."""
        key = header.get("key")
        with trace.span("router.queue") as sp:
            w = self._acquire(entry, key)
            if sp:
                sp.set(worker=w.index)
        try:
            try:
                reply = self._send_to_worker(w, header, arrays, deadline)
                with self._cv:
                    w.breaker.record_success()
                return reply
            except (OSError, rpc.RpcError, faults.InjectedFault) as e:
                self._hop_failed(w, e)
                first_err = e
        finally:
            self._release(w)
        # the single retry: re-admit against the door (our slot was
        # released), prefer a DIFFERENT worker, count the reroute
        self.metrics_.observe_rerouted()
        with self._cv:
            self._entries.add(entry)
        with trace.span("router.queue") as sp:
            w2 = self._acquire(entry, key, exclude=w
                               if self.num_workers > 1 else None)
            if sp:
                sp.set(worker=w2.index, retry=1)
        try:
            reply = self._send_to_worker(w2, header, arrays, deadline)
            with self._cv:
                w2.breaker.record_success()
            return reply
        except (OSError, rpc.RpcError, faults.InjectedFault) as e:
            self._hop_failed(w2, e)
            raise WorkerFailedError(
                "dispatch failed twice (worker %d: %r; worker %d: %r)"
                % (w.index, first_err, w2.index, e)) from e
        finally:
            self._release(w2)

    def _handle_infer(self, header, arrays):
        t0 = self.clock()
        budget = header.get("deadline_s")
        deadline = None if budget is None \
            else Deadline(budget, clock=self.clock)
        # adopt the client's propagated trace context as this handler
        # thread's ambient parent, so the door/dispatch spans (and the
        # context _send_to_worker re-injects) stitch onto ONE trace
        tracer = trace.active()
        token = None
        if tracer is not None:
            ctx = trace.extract(header)
            if ctx is not None:
                token = tracer.activate(ctx)
        try:
            try:
                with trace.span("router.door") as sp:
                    entry = self._admit(deadline)
                    if sp:
                        sp.set(budget_s=budget)
                with trace.span("router.dispatch"):
                    reply_header, reply_arrays = self._dispatch(
                        entry, header, arrays, deadline)
            except ServerOverloadedError as e:
                flight.record("request.outcome", outcome="ServerOverloaded")
                return {"type": "error", "error": "ServerOverloaded",
                        "message": str(e)}, None
            except RouterShutdownError as e:
                flight.record("request.outcome", outcome="RouterShutdown")
                return {"type": "error", "error": "RouterShutdown",
                        "message": str(e)}, None
            except WorkerFailedError as e:
                self.metrics_.observe_failed()
                flight.record("request.outcome", outcome="WorkerFailed")
                return {"type": "error", "error": "WorkerFailed",
                        "message": str(e)}, None
            if reply_header.get("type") == "error":
                kind = reply_header.get("error")
                if kind == "DeadlineRefused":
                    self.metrics_.observe_deadline_refused()
                    self.metrics_.observe_expired()
                elif kind == "DeadlineExceeded":
                    # budget survived to the worker but died in its engine
                    # queue: a deadline outcome, not a worker failure
                    self.metrics_.observe_expired()
                else:
                    self.metrics_.observe_failed()
                flight.record("request.outcome", outcome=kind)
            else:
                self.metrics_.observe_completed(self.clock() - t0)
                flight.record("request.outcome", outcome="completed")
            return reply_header, reply_arrays
        finally:
            if token is not None:
                tracer.deactivate(token)

    def _worker_states(self):
        with self._cv:
            return [{
                "index": w.index, "pid": w.pid, "healthy": w.healthy,
                "respawning": w.respawning, "restarts": w.restarts,
                "in_flight": w.in_flight, "hb_misses": w.hb_misses,
                "breaker": w.breaker.state, "stats": dict(w.stats),
            } for w in self._workers]

    def _handle_reload(self, header):
        """Broadcast the hot-swap verb to every live worker (the
        streaming publish plane's fleet-wide reload). Each worker stages
        its own CRC-verified load and flips between micro-batches;
        in-flight requests finish on the old weights. Per-worker results
        ride back; the call fails typed only when NO worker swapped."""
        with self._cv:
            workers = list(self._workers)
        fwd = {"type": "reload", "dir": header.get("dir"),
               "version": header.get("version")}
        results = []
        for w in workers:
            if not w.healthy:
                results.append({"index": w.index, "error": "unhealthy"})
                continue
            try:
                rh, _ = self._send_to_worker(w, dict(fwd), None, None)
            except Exception as e:  # noqa: BLE001 — per-worker verdicts
                results.append({"index": w.index,
                                "error": "%s: %s" % (type(e).__name__, e)})
                continue
            if rh.get("type") == "reloaded":
                results.append({"index": w.index,
                                "version": rh.get("version")})
            else:
                results.append({"index": w.index,
                                "error": rh.get("message",
                                                rh.get("error"))})
        swapped = [r["version"] for r in results if "version" in r]
        if not swapped:
            flight.record("model.swap_failed", where="router",
                          workers=len(results))
            return {"type": "error", "error": "ReloadFailed",
                    "message": "no worker swapped: %s" % (results,)}, None
        version = min(swapped)
        flight.record("model.swap", where="router", version=version,
                      workers=len(swapped))
        return {"type": "reloaded", "version": version,
                "workers": results}, None

    def _broadcast_verb(self, verb, fwd, ok_type):
        """Drive one swap verb across every live worker; per-worker
        verdicts ride back. Returns (results, versions_that_succeeded)."""
        with self._cv:
            workers = list(self._workers)
        results = []
        for w in workers:
            if not w.healthy:
                results.append({"index": w.index, "error": "unhealthy"})
                continue
            try:
                rh, _ = self._send_to_worker(w, dict(fwd), None, None)
            except Exception as e:  # noqa: BLE001 — per-worker verdicts
                results.append({"index": w.index,
                                "error": "%s: %s" % (type(e).__name__, e)})
                continue
            if rh.get("type") == ok_type:
                results.append({"index": w.index,
                                "version": rh.get("version")})
            else:
                results.append({"index": w.index,
                                "error": rh.get("message",
                                                rh.get("error"))})
        return results, [r["version"] for r in results if "version" in r]

    def _handle_prepare(self, header):
        """Phase 1, router-local all-or-nothing: EVERY worker must stage
        the version or the router aborts its own workers and reports
        typed failure — a router either joins the fleet's swap whole or
        not at all (no intra-router mixed staging)."""
        fwd = {"type": "prepare", "dir": header.get("dir"),
               "version": header.get("version")}
        results, prepared = self._broadcast_verb(
            "prepare", fwd, "prepared")
        if len(prepared) < len(results) or not prepared:
            self._broadcast_verb("abort", {"type": "abort"}, "aborted")
            flight.record("swap.prepare_failed", where="router",
                          prepared=len(prepared), workers=len(results))
            return {"type": "error", "error": "PrepareFailed",
                    "message": "staged %d/%d workers: %s"
                               % (len(prepared), len(results), results)}, \
                None
        flight.record("swap.prepare", where="router",
                      version=min(prepared), workers=len(prepared))
        return {"type": "prepared", "version": min(prepared),
                "workers": results}, None

    def _handle_commit(self, header):
        """Phase 2: all live workers must flip (idempotent per worker,
        so a retried commit converges). Partial worker commit is a typed
        failure — the fleet publisher retries/quarantines the router."""
        fwd = {"type": "commit", "version": header.get("version")}
        results, committed = self._broadcast_verb(
            "commit", fwd, "committed")
        if len(committed) < len(results) or not committed:
            flight.record("swap.commit_failed", where="router",
                          committed=len(committed), workers=len(results))
            return {"type": "error", "error": "CommitFailed",
                    "message": "committed %d/%d workers: %s"
                               % (len(committed), len(results),
                                  results)}, None
        flight.record("swap.commit", where="router",
                      version=min(committed), workers=len(committed))
        return {"type": "committed", "version": min(committed),
                "workers": results}, None

    # -- front server -------------------------------------------------------

    def _make_server(self):
        router = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                while not router._stop.is_set():
                    try:
                        header, arrays = rpc.recv_msg(sock)
                    except rpc.ConnectionClosed:
                        return
                    except rpc.RpcError as e:
                        try:
                            rpc.send_msg(sock, {"type": "error",
                                                "error": "Rpc",
                                                "message": str(e)})
                        except Exception:
                            pass
                        return
                    kind = header.get("type")
                    if kind == "infer":
                        resp, out = router._handle_infer(header, arrays)
                    elif kind == "ping":
                        # the ping path doubles as the scrape endpoint:
                        # Prometheus exposition text rides in the pong
                        resp, out = {
                            "type": "pong",
                            "prometheus": router.metrics_.prometheus_text(),
                        }, None
                    elif kind == "metrics":
                        resp, out = {
                            "type": "metrics",
                            "snapshot": router.metrics_.snapshot(),
                            "workers": router._worker_states(),
                            "prometheus": router.metrics_.prometheus_text(),
                        }, None
                    elif kind == "reload":
                        resp, out = router._handle_reload(header)
                    elif kind == "prepare":
                        resp, out = router._handle_prepare(header)
                    elif kind == "commit":
                        resp, out = router._handle_commit(header)
                    elif kind == "abort":
                        router._broadcast_verb(
                            "abort", {"type": "abort"}, "aborted")
                        resp, out = {"type": "aborted"}, None
                    else:
                        resp, out = {"type": "error", "error": "Rpc",
                                     "message": "unknown message type %r"
                                                % kind}, None
                    try:
                        rpc.send_msg(sock, resp, out)
                    except rpc.RpcError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        return Server((self.host, self.port), Handler)

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        # parallel spawn: worker startup is ~2s each; serial would make
        # a 4-worker router pay 8s at every start
        failures = []

        def _spawn_capture(w):
            try:
                self._spawn_worker(w)
            except Exception as e:
                failures.append(e)

        spawners = [threading.Thread(target=_spawn_capture, args=(w,),
                                     daemon=True)
                    for w in self._workers]
        for t in spawners:
            t.start()
        for t in spawners:
            t.join(self.spawn_timeout_s + 5.0)
        if failures:
            self.shutdown()
            raise failures[0]
        self._server = self._make_server()
        self._server_thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True, name="router-server")
        self._server_thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="router-health")
        self._health_thread.start()
        return self

    @property
    def address(self):
        if self._server is None:
            raise RuntimeError("router not started")
        return (self.host, self._server.server_address[1])

    def shutdown(self, grace_s=5.0):
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        if self._health_thread is not None:
            self._health_thread.join(grace_s)
        for t in self._respawn_threads:
            t.join(grace_s)
        for w in self._workers:
            w.close_sockets()
        reap_procs([w.proc for w in self._workers], grace_s=grace_s)
        trace.flush()
        flight.maybe_dump(reason="router-shutdown")

    def __enter__(self):
        if self._server is None:
            self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


class RouterClient:
    """Client for a :class:`Router`: sync ``predict`` and future-based
    ``submit`` (mirroring ``ServingEngine.submit``), with a small idle
    connection pool. ``submit`` stamps the deadline at CALL time, so
    time spent queued in the client's own thread pool burns the same
    budget everything else does."""

    _ERRORS = {
        "ServerOverloaded": ServerOverloadedError,
        "DeadlineExceeded": DeadlineExceededError,
        "DeadlineRefused": DeadlineExceededError,
        "WorkerFailed": WorkerFailedError,
        "RouterShutdown": RouterShutdownError,
        "ReloadFailed": WorkerFailedError,
        "PrepareFailed": WorkerFailedError,
        "CommitFailed": WorkerFailedError,
        "Rpc": rpc.RpcError,
    }

    def __init__(self, address, pool_size=8, default_timeout_s=None,
                 clock=None):
        self.address = tuple(address)
        self.default_timeout_s = default_timeout_s
        self.clock = clock or time.monotonic
        self._pool = ThreadPoolExecutor(
            max_workers=pool_size, thread_name_prefix="router-client")
        self._idle = deque()
        self._idle_lock = threading.Lock()
        self._closed = False

    def _roundtrip(self, header, arrays):
        with self._idle_lock:
            sock = self._idle.popleft() if self._idle else None
        if sock is None:
            sock = rpc.connect(self.address)
        try:
            rpc.send_msg(sock, header, arrays)
            reply = rpc.recv_msg(sock)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        with self._idle_lock:
            self._idle.append(sock)
        return reply

    def _raise_typed(self, header):
        kind = header.get("error")
        exc_type = self._ERRORS.get(kind, rpc.RpcError)
        exc = exc_type(header.get("message", kind))
        exc.kind = kind
        raise exc

    def _infer(self, feed, deadline, key, extra=None):
        header = {"type": "infer"}
        if key is not None:
            header["key"] = key
        if deadline is not None:
            header["deadline_s"] = deadline.remaining()
        if extra:
            header.update(extra)
        with trace.span("client.predict") as sp:
            # the root of the cross-process trace: inject THIS span's
            # context so every hop downstream stitches onto one trace id
            trace.inject(header)
            reply_header, arrays = self._roundtrip(
                header, {k: np.asarray(v) for k, v in feed.items()})
            if sp and reply_header.get("type") == "error":
                sp.set(error=reply_header.get("error"))
        if reply_header.get("type") == "error":
            self._raise_typed(reply_header)
        n = reply_header.get("n_out", 0)
        return [arrays["o%d" % i] for i in range(n)]

    def predict(self, feed, timeout_s=None, key=None, **decode_kw):
        """Synchronous inference -> list of fetch arrays. Raises the
        same typed errors the in-process engine does
        (:class:`ServerOverloadedError`, :class:`DeadlineExceededError`)
        plus :class:`WorkerFailedError` / :class:`RouterShutdownError`.

        Extra keyword args (``max_new_tokens``, ``eos_id``) ride the
        infer header verbatim — the router forwards unknown header
        fields untouched, and decode workers read them per request."""
        t = timeout_s if timeout_s is not None else self.default_timeout_s
        deadline = None if t is None else Deadline(t, clock=self.clock)
        return self._infer(feed, deadline, key, decode_kw or None)

    def submit(self, feed, timeout_s=None, key=None, **decode_kw):
        """Async inference -> ``concurrent.futures.Future`` resolving to
        the fetch list (or raising the typed error)."""
        if self._closed:
            raise RouterShutdownError("client closed")
        t = timeout_s if timeout_s is not None else self.default_timeout_s
        deadline = None if t is None else Deadline(t, clock=self.clock)
        return self._pool.submit(self._infer, feed, deadline, key,
                                 decode_kw or None)

    def metrics(self):
        """Router-side metrics snapshot + per-worker health states."""
        header, _ = self._roundtrip({"type": "metrics"}, None)
        if header.get("type") == "error":
            self._raise_typed(header)
        return {"snapshot": header["snapshot"],
                "workers": header["workers"]}

    def reload(self, ckpt_dir, version=None):
        """Hot-swap every worker to a published checkpoint version
        (``version=None`` = each worker's newest intact). Returns the
        router's reply dict: ``{"version": N, "workers": [...]}`` with
        per-worker verdicts. Raises :class:`WorkerFailedError` (kind
        ``ReloadFailed``) only when no worker swapped."""
        header, _ = self._roundtrip(
            {"type": "reload", "dir": ckpt_dir, "version": version}, None)
        if header.get("type") == "error":
            self._raise_typed(header)
        return {"version": header.get("version"),
                "workers": header.get("workers", [])}

    def prepare(self, ckpt_dir, version=None):
        """Phase 1 of the fleet's two-phase swap: CRC-stage ``version``
        on EVERY worker without swapping. All-or-nothing per router —
        a partial stage aborts the router's workers and raises
        :class:`WorkerFailedError` (kind ``PrepareFailed``)."""
        header, _ = self._roundtrip(
            {"type": "prepare", "dir": ckpt_dir, "version": version},
            None)
        if header.get("type") == "error":
            self._raise_typed(header)
        return {"version": header.get("version"),
                "workers": header.get("workers", [])}

    def commit(self, version=None):
        """Phase 2: flip every worker to its staged version (idempotent
        under retry). Raises :class:`WorkerFailedError` (kind
        ``CommitFailed``) when any worker failed to flip."""
        header, _ = self._roundtrip(
            {"type": "commit", "version": version}, None)
        if header.get("type") == "error":
            self._raise_typed(header)
        return {"version": header.get("version"),
                "workers": header.get("workers", [])}

    def abort(self):
        """Drop any staged-but-uncommitted version on every worker."""
        header, _ = self._roundtrip({"type": "abort"}, None)
        if header.get("type") == "error":
            self._raise_typed(header)
        return True

    def prometheus(self):
        """Scrape the router's Prometheus exposition text (ping path)."""
        header, _ = self._roundtrip({"type": "ping"}, None)
        if header.get("type") == "error":
            self._raise_typed(header)
        return header.get("prometheus", "")

    def close(self):
        self._closed = True
        self._pool.shutdown(wait=True)
        with self._idle_lock:
            socks, self._idle = list(self._idle), deque()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.serving.router",
        description=__doc__.splitlines()[0])
    ap.add_argument("--model", required=True)
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--routing", default="least_loaded",
                    choices=["least_loaded", "hash"])
    ap.add_argument("--max-queue-depth", type=int, default=64)
    ap.add_argument("--worker-arg", action="append", default=[],
                    help="extra CLI arg forwarded to every worker "
                         "(repeatable)")
    args = ap.parse_args(argv)

    trace.maybe_start_from_env()
    flight.install()
    router = Router(args.model, num_workers=args.workers, host=args.host,
                    port=args.port, routing=args.routing,
                    max_queue_depth=args.max_queue_depth,
                    worker_args=args.worker_arg)
    router.start()
    print(ROUTER_READY_PREFIX + json.dumps(
        {"port": router.address[1], "pid": os.getpid(),
         "workers": args.workers}), flush=True)

    done = threading.Event()

    def _on_term(signum, frame):
        done.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)
    except ValueError:
        pass
    try:
        done.wait()
    finally:
        router.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())

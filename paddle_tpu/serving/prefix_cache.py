"""Prefix-KV cache for the continuous-batching decode tier.

Millions of requests share system prompts and few-shot prefixes, yet a
prefix-blind scheduler re-prefills every prompt from token zero. This
module keeps a **hash-trie over token-id prefixes** mapping to the KV
rows the slot table already computed for them: admission walks the trie
for the longest cached prefix and CLONES those rows into the new slot's
cache (one device-side copy) instead of re-ingesting the prefix token
by token — TTFT for a request with a P-token cached prefix drops by P
step dispatches.

Design points, in the order they matter for correctness:

  * **Clone, never alias.** A hit copies the stored rows into the slot's
    own cache rows; live slots never reference entry storage after
    admission, so LRU eviction can NEVER corrupt an in-flight request
    (``tests/test_serving.py`` pins this with an evict-under-the-slot
    test).
  * **Ref-counting against admissions.** ``lookup`` pins the entry
    (``refs += 1``) and the batcher releases it after the rows are
    cloned at admission; a pinned entry is skipped by eviction, so the
    bytes accounting can never free storage a pending admission is
    about to read.
  * **LRU over bytes AND entries.** ``max_bytes`` bounds device/host
    memory, ``max_entries`` bounds trie size; either limit evicts the
    least-recently-used unpinned entry (evictions are observable:
    ``ServingMetrics.prefix_evictions``).
  * **Match is capped at len(prompt)-1.** The step program needs to FEED
    the last prompt token to produce first-generation logits, so a full
    prompt match still leaves one token to ingest — the scheduler, not
    this module, enforces sampling correctness, but ``lookup(limit=...)``
    is how it asks.

The trie stores per-entry ``rows``: a dict of cache-feed name ->
``[prefix_len, *tail]`` array (numpy or device-resident jax — whatever
the slot table carried when the prefix was harvested)."""

import threading
from collections import OrderedDict

__all__ = ["PrefixCache", "PrefixEntry", "PrefixMatch"]


class PrefixEntry:
    """One cached prefix: the token key, the per-feed KV rows, and the
    ref-count admissions hold while cloning."""

    __slots__ = ("key", "rows", "nbytes", "refs")

    def __init__(self, key, rows):
        self.key = key
        self.rows = rows
        self.nbytes = int(sum(getattr(a, "nbytes", 0)
                              for a in rows.values()))
        self.refs = 0

    def __len__(self):
        return len(self.key)

    def __repr__(self):
        return ("PrefixEntry(%d toks, %d B, refs=%d)"
                % (len(self.key), self.nbytes, self.refs))


class PrefixMatch:
    """One lookup hit: the pinned donor ``entry`` plus how many of its
    LEADING rows (``length``) match the queried prompt. The donor may be
    deeper than the match — causal attention makes an entry's first m
    rows exactly the KV state of its first m tokens, so any entry below
    the deepest matched trie node can donate its leading rows. Release
    the entry (``PrefixCache.release``) after cloning ``rows[:length]``."""

    __slots__ = ("entry", "length")

    def __init__(self, entry, length):
        self.entry = entry
        self.length = int(length)

    def __repr__(self):
        return "PrefixMatch(%d of %d toks)" % (self.length,
                                               len(self.entry.key))


class _Node:
    __slots__ = ("children", "entry")

    def __init__(self):
        self.children = {}
        self.entry = None


class PrefixCache:
    """Hash-trie prefix -> KV-rows cache with pinned-aware LRU eviction.

    Thread-safe: ``submit`` (caller threads) looks prefixes up while the
    scheduler loop thread harvests and clones, so every public method
    serializes on an internal lock. Entries are immutable after insert —
    the lock guards the trie/LRU bookkeeping, never row data. A single
    instance can safely back several batchers (the engine shares one per
    fleet)."""

    def __init__(self, max_bytes=64 << 20, max_entries=512, metrics=None):
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self.metrics = metrics
        self._root = _Node()
        self._lru = OrderedDict()   # key tuple -> PrefixEntry
        self._lock = threading.RLock()
        self.nbytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self):
        with self._lock:
            return len(self._lru)

    def __contains__(self, tokens):
        with self._lock:
            return tuple(int(t) for t in tokens) in self._lru

    # -- read path ----------------------------------------------------------
    def lookup(self, tokens, limit=None):
        """Longest cached prefix of ``tokens`` (at most ``limit`` tokens
        long). Returns a :class:`PrefixMatch` whose entry is PINNED
        (caller MUST :meth:`release` after cloning) or ``None``. The
        donor entry may be DEEPER than the match: the walk descends the
        trie as far as ``tokens`` agree, then any entry in the subtree
        below the deepest matched node donates its first ``depth`` rows
        (valid because causal attention makes an entry's leading rows
        depend only on its leading tokens). Bumps LRU recency of the
        donor and the hit/miss counters."""
        limit = len(tokens) if limit is None else min(int(limit),
                                                      len(tokens))
        with self._lock:
            node = self._root
            depth = 0
            for i in range(limit):
                nxt = node.children.get(int(tokens[i]))
                if nxt is None:
                    break
                node = nxt
                depth = i + 1
            donor = self._find_entry(node) if depth else None
            if donor is None:
                self.misses += 1
                return None
            donor.refs += 1
            self._lru.move_to_end(donor.key)
            self.hits += 1
            if self.metrics is not None:
                self.metrics.observe_prefix_hit(depth)
            return PrefixMatch(donor, depth)

    @staticmethod
    def _find_entry(node):
        """Any entry at or below ``node`` (eviction prunes entry-less
        branches bottom-up, so every surviving node leads to one)."""
        while node.entry is None:
            if not node.children:
                return None  # pruning invariant broken — treat as miss
            node = next(iter(node.children.values()))
        return node.entry

    def release(self, entry):
        """Drop an admission's pin (rows are cloned; entry is evictable
        again)."""
        with self._lock:
            entry.refs = max(0, entry.refs - 1)

    # -- write path ---------------------------------------------------------
    def insert(self, tokens, rows):
        """Cache ``rows`` (feed name -> [len(tokens), *tail]) under the
        token prefix. No-op if the exact prefix is already cached (first
        writer wins — re-harvesting identical rows buys nothing).
        Evicts LRU unpinned entries until both budgets hold; an entry
        larger than ``max_bytes`` on its own is refused. Returns the
        entry or ``None``."""
        key = tuple(int(t) for t in tokens)
        entry = PrefixEntry(key, dict(rows))
        if not key or entry.nbytes > self.max_bytes:
            return None
        with self._lock:
            if key in self._lru:
                return None
            self.nbytes += entry.nbytes
            node = self._root
            for t in key:
                node = node.children.setdefault(t, _Node())
            node.entry = entry
            self._lru[key] = entry
            self._evict_to_budget()
        return entry

    def _evict_to_budget(self):
        while (self.nbytes > self.max_bytes
               or len(self._lru) > self.max_entries):
            victim = None
            for key, entry in self._lru.items():  # LRU order, oldest first
                if entry.refs == 0:
                    victim = key
                    break
            if victim is None:
                return  # everything left is pinned by a pending admission
            self._evict(victim)

    def _evict(self, key):
        entry = self._lru.pop(key)
        self.nbytes -= entry.nbytes
        self.evictions += 1
        # unlink from the trie, pruning now-empty nodes bottom-up
        path = [self._root]
        for t in key:
            path.append(path[-1].children[t])
        path[-1].entry = None
        for i in range(len(key), 0, -1):
            node = path[i]
            if node.entry is None and not node.children:
                del path[i - 1].children[key[i - 1]]
            else:
                break
        if self.metrics is not None:
            self.metrics.observe_prefix_eviction()
        return entry

    # -- introspection ------------------------------------------------------
    def stats(self):
        with self._lock:
            return {"entries": len(self._lru), "bytes": self.nbytes,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}

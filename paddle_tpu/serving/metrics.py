"""Serving observability, built on ``paddle_tpu.profiler``.

What a serving operator actually pages on: the latency tail (p50/p95/p99
via ``profiler.Histogram``'s sliding window), queue depth, batch occupancy
(real examples / bucket slots — the padding tax the ladder charges for a
bounded compile cache), and the compile-cache hit rate (misses after
warm-up mean a shape leaked past the bucketing). Exposed both as a plain
dict (``snapshot``) and a formatted table shaped like ``profiler._report``.
"""

import threading

from ..profiler import Histogram

__all__ = ["ServingMetrics"]


class ServingMetrics:
    def __init__(self, latency_window=8192):
        self.latency = Histogram(max_samples=latency_window)
        # decode-tier tails (continuous batcher): time-to-first-token and
        # time-per-output-token — THE serving-latency pair for
        # autoregressive workloads (whole-request latency hides which of
        # queueing vs generation is slow)
        self.ttft = Histogram(max_samples=latency_window)
        self.tpot = Histogram(max_samples=latency_window)
        self._lock = threading.Lock()
        self._decode_steps = 0
        self._decode_tokens = 0
        self._slot_live = 0
        self._slot_total = 0
        self._completed = 0
        self._failed = 0
        self._rejected = 0
        self._expired = 0
        self._shed = 0
        self._retried = 0
        self._evicted = 0
        self._respawned = 0
        # router-tier counters (multi-process front door, ISSUE 16):
        # the in-process counters above count what one engine did; these
        # count what the DOOR did across workers
        self._door_shed = 0
        self._rerouted = 0
        self._respawns = 0
        self._heartbeat_misses = 0
        self._deadline_refused = 0
        self._batches = 0
        self._batched_examples = 0
        self._bucket_slots = 0
        self._cache_hits = 0
        self._cache_misses = 0
        self._queue_depth_fn = lambda: 0
        self._in_flight_fn = lambda: 0

    # -- wiring (the engine hands us its live gauges) -----------------------
    def bind_gauges(self, queue_depth_fn, in_flight_fn):
        self._queue_depth_fn = queue_depth_fn
        self._in_flight_fn = in_flight_fn

    # -- observation points -------------------------------------------------
    def observe_completed(self, latency_s):
        self.latency.add(latency_s)
        with self._lock:
            self._completed += 1

    def observe_failed(self, n=1):
        with self._lock:
            self._failed += n

    def observe_rejected(self, n=1):
        with self._lock:
            self._rejected += n

    def observe_expired(self, n=1):
        with self._lock:
            self._expired += n

    def observe_shed(self, n=1):
        """An admitted request displaced under overload by a new arrival
        with an earlier deadline (EDF shedding)."""
        with self._lock:
            self._shed += n

    def observe_retried(self, n=1):
        """A request re-enqueued after its batch failed (cross-replica
        retry); it will also count completed/failed when it resolves."""
        with self._lock:
            self._retried += n

    def observe_evicted(self):
        """A replica's circuit breaker tripped: predictor evicted and
        rebuilt from the parent."""
        with self._lock:
            self._evicted += 1

    def observe_respawned(self):
        """The supervisor found a dead worker thread and restarted it."""
        with self._lock:
            self._respawned += 1

    def observe_door_shed(self, n=1):
        """An admitted request displaced AT THE ROUTER DOOR (EDF, before
        any worker saw it) by a new arrival with an earlier deadline."""
        with self._lock:
            self._door_shed += n

    def observe_rerouted(self, n=1):
        """A request sent to a different worker than first choice —
        either its preferred worker was unhealthy/at-capacity at pick
        time, or its dispatch failed and the one cross-worker retry ran."""
        with self._lock:
            self._rerouted += n

    def observe_respawn(self, n=1):
        """A worker PROCESS was restarted (crash, breaker trip, or
        heartbeat loss) and came back ready."""
        with self._lock:
            self._respawns += n

    def observe_heartbeat_miss(self, n=1):
        with self._lock:
            self._heartbeat_misses += n

    def observe_deadline_refused(self, n=1):
        """A worker refused a request whose propagated budget was already
        spent — deadline propagation doing its job (the alternative is
        executing work nobody is waiting for)."""
        with self._lock:
            self._deadline_refused += n

    def observe_decode_step(self, live, bucket, generated):
        """One pass of the continuous-batching decode loop: ``live``
        occupied slots out of ``bucket`` (the padded slot-table size),
        ``generated`` tokens actually sampled this step (forced prompt
        ingestion doesn't count)."""
        with self._lock:
            self._decode_steps += 1
            self._decode_tokens += generated
            self._slot_live += live
            self._slot_total += bucket

    def observe_ttft(self, latency_s):
        """Admission -> first sampled token for one request."""
        self.ttft.add(latency_s)

    def observe_tpot(self, latency_s):
        """Mean seconds per output token AFTER the first, for one
        completed request (the steady-state generation rate its caller
        saw, batching interference included)."""
        self.tpot.add(latency_s)

    def observe_batch(self, actual, bucket, cache_hit):
        with self._lock:
            self._batches += 1
            self._batched_examples += actual
            self._bucket_slots += bucket
            if cache_hit:
                self._cache_hits += 1
            else:
                self._cache_misses += 1

    # -- export -------------------------------------------------------------
    def snapshot(self):
        with self._lock:
            batches = self._batches
            occupancy = (self._batched_examples / self._bucket_slots
                         if self._bucket_slots else None)
            lookups = self._cache_hits + self._cache_misses
            snap = {
                "requests_completed": self._completed,
                "requests_failed": self._failed,
                "requests_rejected": self._rejected,
                "requests_expired": self._expired,
                "requests_shed": self._shed,
                "requests_retried": self._retried,
                "replicas_evicted": self._evicted,
                "workers_respawned": self._respawned,
                "door_shed": self._door_shed,
                "rerouted": self._rerouted,
                "respawns": self._respawns,
                "heartbeat_misses": self._heartbeat_misses,
                "deadline_refused": self._deadline_refused,
                "queue_depth": self._queue_depth_fn(),
                "in_flight": self._in_flight_fn(),
                "batches": batches,
                "batch_occupancy": occupancy,
                "avg_batch_size": (self._batched_examples / batches
                                   if batches else None),
                "compile_cache_hits": self._cache_hits,
                "compile_cache_misses": self._cache_misses,
                "compile_cache_hit_rate": (self._cache_hits / lookups
                                           if lookups else None),
                "decode_steps": self._decode_steps,
                "decode_tokens": self._decode_tokens,
                "slot_occupancy": (self._slot_live / self._slot_total
                                   if self._slot_total else None),
            }
        lat = self.latency.percentiles((50, 95, 99))
        snap["latency_s"] = {k: lat[k] for k in ("p50", "p95", "p99")}
        for name, hist in (("ttft_s", self.ttft), ("tpot_s", self.tpot)):
            ps = hist.percentiles((50, 95, 99))
            snap[name] = {k: ps[k] for k in ("p50", "p95", "p99")}
        return snap

    def report(self):
        """Formatted table in the ``profiler._report`` house style."""
        s = self.snapshot()
        lines = ["%-32s %14s" % ("Serving metric", "Value")]

        def fmt(v):
            if v is None:
                return "-"
            if isinstance(v, float):
                return "%.4f" % v
            return "%d" % v

        for key in ("requests_completed", "requests_failed",
                    "requests_rejected", "requests_expired",
                    "requests_shed", "requests_retried",
                    "replicas_evicted", "workers_respawned",
                    "door_shed", "rerouted", "respawns",
                    "heartbeat_misses", "deadline_refused", "queue_depth",
                    "in_flight", "batches", "avg_batch_size",
                    "batch_occupancy", "compile_cache_hits",
                    "compile_cache_misses", "compile_cache_hit_rate",
                    "decode_steps", "decode_tokens", "slot_occupancy"):
            lines.append("%-32s %14s" % (key, fmt(s[key])))
        for group in ("latency_s", "ttft_s", "tpot_s"):
            prefix = group[:-2]  # strip the _s unit suffix
            for k, v in s[group].items():
                lines.append("%-32s %14s" % (
                    "%s_%s_ms" % (prefix, k),
                    "-" if v is None else "%.3f" % (v * 1e3)))
        return "\n".join(lines)

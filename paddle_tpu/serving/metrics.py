"""Serving observability, built on ``paddle_tpu.obs.registry``.

What a serving operator actually pages on: the latency tail (p50/p95/p99
via ``profiler.Histogram``'s sliding window), queue depth, batch occupancy
(real examples / bucket slots — the padding tax the ladder charges for a
bounded compile cache), and the compile-cache hit rate (misses after
warm-up mean a shape leaked past the bucketing). Exposed three ways:

  * ``snapshot()`` — the plain dict the bench harness and tests pin
    (field names are a CONTRACT with ``tests/test_bench_contract.py``;
    do not rename);
  * ``report()`` — a formatted table shaped like ``profiler._report``;
  * ``prometheus_text()`` — the registry's Prometheus exposition (every
    counter under ``paddle_tpu_serving_*``, gauges, latency summaries)
    plus the live MFU gauge, served from the router's ping path and the
    worker ``stats`` verb.

Every counter is a named :class:`~paddle_tpu.obs.registry.Counter` in a
per-instance :class:`~paddle_tpu.obs.registry.Registry` — the observe_*
API and snapshot shape are unchanged from the pre-registry version.
"""

from ..obs.registry import Registry
from ..obs.registry import MFU as _MFU
from ..profiler import Histogram

__all__ = ["ServingMetrics"]

# snapshot field -> Prometheus help string; the registry metric name is
# paddle_tpu_serving_<field>. Order here is the exposition order.
_COUNTERS = (
    ("requests_completed", "requests answered with a result"),
    ("requests_failed", "requests answered with a non-deadline error"),
    ("requests_rejected", "requests refused at admission (no shed victim)"),
    ("requests_expired", "requests whose deadline passed before serving"),
    ("requests_shed", "queued requests displaced by EDF shedding"),
    ("requests_retried", "requests re-enqueued after a failed batch"),
    ("replicas_evicted", "replica predictors evicted and rebuilt"),
    ("workers_respawned", "dead engine worker threads restarted"),
    ("door_shed", "requests displaced at the router door (EDF)"),
    ("rerouted", "requests sent to a non-first-choice worker"),
    ("respawns", "worker processes restarted by the router"),
    ("heartbeat_misses", "worker heartbeat probes that failed"),
    ("deadline_refused", "expired requests refused by a worker"),
    ("batches", "micro-batches dispatched"),
    ("batched_examples", "real examples across all dispatched batches"),
    ("bucket_slots", "padded slots across all dispatched batches"),
    ("compile_cache_hits", "dispatches on an already-seen signature"),
    ("compile_cache_misses", "dispatches that compiled a new signature"),
    ("decode_steps", "continuous-batching decode loop passes"),
    ("decode_tokens", "tokens sampled by the decode loop"),
    ("slot_live", "occupied slots summed over decode steps"),
    ("slot_total", "total slots summed over decode steps"),
    ("prefix_hits", "admissions that cloned a cached KV prefix"),
    ("prefix_tokens_reused", "prompt tokens skipped via prefix-cache hits"),
    ("prefix_evictions", "prefix-cache entries evicted (LRU)"),
    ("prefill_chunks", "chunked-prefill / speculative-verify dispatches"),
    ("prefill_tokens", "prompt tokens ingested through chunk dispatches"),
    ("spec_accepted", "speculative draft tokens accepted by the verifier"),
    ("spec_rejected", "speculative draft tokens rejected by the verifier"),
)

_PREFIX = "paddle_tpu_serving_"


class ServingMetrics:
    def __init__(self, latency_window=8192):
        self.registry = Registry()
        self.latency = Histogram(max_samples=latency_window)
        # decode-tier tails (continuous batcher): time-to-first-token and
        # time-per-output-token — THE serving-latency pair for
        # autoregressive workloads (whole-request latency hides which of
        # queueing vs generation is slow)
        self.ttft = Histogram(max_samples=latency_window)
        self.tpot = Histogram(max_samples=latency_window)
        self._c = {}
        for field, help_text in _COUNTERS:
            self._c[field] = self.registry.counter(_PREFIX + field,
                                                   help=help_text)
        self._queue_depth_fn = lambda: 0
        self._in_flight_fn = lambda: 0
        self._prefix_bytes_fn = lambda: 0
        self.registry.gauge(_PREFIX + "queue_depth",
                            help="examples queued, not yet in a batch",
                            fn=lambda: self._queue_depth_fn())
        self.registry.gauge(_PREFIX + "in_flight",
                            help="admitted examples not yet resolved",
                            fn=lambda: self._in_flight_fn())
        self.registry.gauge(_PREFIX + "prefix_bytes",
                            help="bytes of KV rows held by the prefix "
                                 "cache",
                            fn=lambda: self._prefix_bytes_fn())
        self.registry.histogram(_PREFIX + "latency_seconds", self.latency,
                                help="request latency (sliding window)")
        self.registry.histogram(_PREFIX + "ttft_seconds", self.ttft,
                                help="time to first sampled token")
        self.registry.histogram(_PREFIX + "tpot_seconds", self.tpot,
                                help="time per output token after the first")

    # -- wiring (the engine hands us its live gauges) -----------------------
    def bind_gauges(self, queue_depth_fn, in_flight_fn):
        self._queue_depth_fn = queue_depth_fn
        self._in_flight_fn = in_flight_fn

    def bind_prefix_bytes(self, fn):
        """The prefix cache's live byte count (one cache may back many
        batchers, so the OWNER binds it, same as :meth:`bind_gauges`)."""
        self._prefix_bytes_fn = fn

    # -- observation points -------------------------------------------------
    def observe_completed(self, latency_s):
        self.latency.add(latency_s)
        self._c["requests_completed"].inc()

    def observe_failed(self, n=1):
        self._c["requests_failed"].inc(n)

    def observe_rejected(self, n=1):
        self._c["requests_rejected"].inc(n)

    def observe_expired(self, n=1):
        self._c["requests_expired"].inc(n)

    def observe_shed(self, n=1):
        """An admitted request displaced under overload by a new arrival
        with an earlier deadline (EDF shedding)."""
        self._c["requests_shed"].inc(n)

    def observe_retried(self, n=1):
        """A request re-enqueued after its batch failed (cross-replica
        retry); it will also count completed/failed when it resolves."""
        self._c["requests_retried"].inc(n)

    def observe_evicted(self):
        """A replica's circuit breaker tripped: predictor evicted and
        rebuilt from the parent."""
        self._c["replicas_evicted"].inc()

    def observe_respawned(self):
        """The supervisor found a dead worker thread and restarted it."""
        self._c["workers_respawned"].inc()

    def observe_door_shed(self, n=1):
        """An admitted request displaced AT THE ROUTER DOOR (EDF, before
        any worker saw it) by a new arrival with an earlier deadline."""
        self._c["door_shed"].inc(n)

    def observe_rerouted(self, n=1):
        """A request sent to a different worker than first choice —
        either its preferred worker was unhealthy/at-capacity at pick
        time, or its dispatch failed and the one cross-worker retry ran."""
        self._c["rerouted"].inc(n)

    def observe_respawn(self, n=1):
        """A worker PROCESS was restarted (crash, breaker trip, or
        heartbeat loss) and came back ready."""
        self._c["respawns"].inc(n)

    def observe_heartbeat_miss(self, n=1):
        self._c["heartbeat_misses"].inc(n)

    def observe_deadline_refused(self, n=1):
        """A worker refused a request whose propagated budget was already
        spent — deadline propagation doing its job (the alternative is
        executing work nobody is waiting for)."""
        self._c["deadline_refused"].inc(n)

    def observe_decode_step(self, live, bucket, generated):
        """One pass of the continuous-batching decode loop: ``live``
        occupied slots out of ``bucket`` (the padded slot-table size),
        ``generated`` tokens actually sampled this step (forced prompt
        ingestion doesn't count)."""
        self._c["decode_steps"].inc()
        self._c["decode_tokens"].inc(generated)
        self._c["slot_live"].inc(live)
        self._c["slot_total"].inc(bucket)

    def observe_prefix_hit(self, tokens_reused):
        """One admission cloned a cached KV prefix instead of
        re-prefilling ``tokens_reused`` prompt tokens step by step."""
        self._c["prefix_hits"].inc()
        self._c["prefix_tokens_reused"].inc(int(tokens_reused))

    def observe_prefix_eviction(self, n=1):
        self._c["prefix_evictions"].inc(n)

    def observe_prefill_chunk(self, rows, tokens):
        """One chunk dispatch (prefill and/or speculative verify):
        ``rows`` slot rows participated, ``tokens`` prompt tokens were
        ingested through it (verify lanes count under spec_*)."""
        self._c["prefill_chunks"].inc()
        self._c["prefill_tokens"].inc(int(tokens))

    def observe_spec(self, accepted, rejected):
        """One speculative verify outcome: ``accepted`` draft tokens
        matched the target model's greedy choice, ``rejected`` did not
        (the bonus token the verifier emits itself counts in neither)."""
        self._c["spec_accepted"].inc(int(accepted))
        self._c["spec_rejected"].inc(int(rejected))

    def observe_ttft(self, latency_s):
        """Admission -> first sampled token for one request."""
        self.ttft.add(latency_s)

    def observe_tpot(self, latency_s):
        """Mean seconds per output token AFTER the first, for one
        completed request (the steady-state generation rate its caller
        saw, batching interference included)."""
        self.tpot.add(latency_s)

    def observe_batch(self, actual, bucket, cache_hit):
        self._c["batches"].inc()
        self._c["batched_examples"].inc(actual)
        self._c["bucket_slots"].inc(bucket)
        if cache_hit:
            self._c["compile_cache_hits"].inc()
        else:
            self._c["compile_cache_misses"].inc()

    # -- export -------------------------------------------------------------
    def snapshot(self):
        c = {field: counter.value for field, counter in self._c.items()}
        batches = c["batches"]
        lookups = c["compile_cache_hits"] + c["compile_cache_misses"]
        snap = {
            "requests_completed": c["requests_completed"],
            "requests_failed": c["requests_failed"],
            "requests_rejected": c["requests_rejected"],
            "requests_expired": c["requests_expired"],
            "requests_shed": c["requests_shed"],
            "requests_retried": c["requests_retried"],
            "replicas_evicted": c["replicas_evicted"],
            "workers_respawned": c["workers_respawned"],
            "door_shed": c["door_shed"],
            "rerouted": c["rerouted"],
            "respawns": c["respawns"],
            "heartbeat_misses": c["heartbeat_misses"],
            "deadline_refused": c["deadline_refused"],
            "queue_depth": self._queue_depth_fn(),
            "in_flight": self._in_flight_fn(),
            "batches": batches,
            "batch_occupancy": (c["batched_examples"] / c["bucket_slots"]
                                if c["bucket_slots"] else None),
            "avg_batch_size": (c["batched_examples"] / batches
                               if batches else None),
            "compile_cache_hits": c["compile_cache_hits"],
            "compile_cache_misses": c["compile_cache_misses"],
            "compile_cache_hit_rate": (c["compile_cache_hits"] / lookups
                                       if lookups else None),
            "decode_steps": c["decode_steps"],
            "decode_tokens": c["decode_tokens"],
            "slot_occupancy": (c["slot_live"] / c["slot_total"]
                               if c["slot_total"] else None),
            "prefix_hits": c["prefix_hits"],
            "prefix_tokens_reused": c["prefix_tokens_reused"],
            "prefix_evictions": c["prefix_evictions"],
            "prefix_bytes": self._prefix_bytes_fn(),
            "prefill_chunks": c["prefill_chunks"],
            "prefill_tokens": c["prefill_tokens"],
            "spec_accepted": c["spec_accepted"],
            "spec_rejected": c["spec_rejected"],
            "spec_accept_rate": (
                c["spec_accepted"]
                / (c["spec_accepted"] + c["spec_rejected"])
                if (c["spec_accepted"] + c["spec_rejected"]) else None),
        }
        lat = self.latency.percentiles((50, 95, 99))
        snap["latency_s"] = {k: lat[k] for k in ("p50", "p95", "p99")}
        for name, hist in (("ttft_s", self.ttft), ("tpot_s", self.tpot)):
            ps = hist.percentiles((50, 95, 99))
            snap[name] = {k: ps[k] for k in ("p50", "p95", "p99")}
        return snap

    def prometheus_text(self):
        """Prometheus exposition: this instance's registry plus the
        process-wide MFU/roofline gauge (populated when ``Executor.run``
        executes under tracing)."""
        text = self.registry.prometheus_text()
        mfu = _MFU.prometheus_lines()
        if mfu:
            text += "\n".join(mfu) + "\n"
        return text

    def report(self):
        """Formatted table in the ``profiler._report`` house style."""
        s = self.snapshot()
        lines = ["%-32s %14s" % ("Serving metric", "Value")]

        def fmt(v):
            if v is None:
                return "-"
            if isinstance(v, float):
                return "%.4f" % v
            return "%d" % v

        for key in ("requests_completed", "requests_failed",
                    "requests_rejected", "requests_expired",
                    "requests_shed", "requests_retried",
                    "replicas_evicted", "workers_respawned",
                    "door_shed", "rerouted", "respawns",
                    "heartbeat_misses", "deadline_refused", "queue_depth",
                    "in_flight", "batches", "avg_batch_size",
                    "batch_occupancy", "compile_cache_hits",
                    "compile_cache_misses", "compile_cache_hit_rate",
                    "decode_steps", "decode_tokens", "slot_occupancy",
                    "prefix_hits", "prefix_tokens_reused",
                    "prefix_evictions", "prefix_bytes", "prefill_chunks",
                    "prefill_tokens", "spec_accepted", "spec_rejected",
                    "spec_accept_rate"):
            lines.append("%-32s %14s" % (key, fmt(s[key])))
        for group in ("latency_s", "ttft_s", "tpot_s"):
            prefix = group[:-2]  # strip the _s unit suffix
            for k, v in s[group].items():
                lines.append("%-32s %14s" % (
                    "%s_%s_ms" % (prefix, k),
                    "-" if v is None else "%.3f" % (v * 1e3)))
        return "\n".join(lines)

"""Length-prefixed socket framing for the multi-process serving tier.

The reference's process boundary is gRPC (``grpc_client.cc:66`` /
``grpc_server.cc`` behind ``listen_and_serv``); ours is deliberately
stdlib-only: a frame is an 8-byte big-endian payload length, a 4-byte
header length, a JSON header, and an ``np.savez`` blob carrying the
arrays. That is enough to move feeds/fetches between the router and its
workers with zero new dependencies, and — the point of this layer — it
gives the chaos harness two *wire-level* fault sites:

  * ``rpc.send``  — tripped before a frame is written. ``error`` raises
    :class:`~paddle_tpu.reliability.faults.InjectedFault` in the sender,
    ``corrupt`` damages the payload bytes so the PEER fails the decode
    (the torn-write drill), ``hang`` stalls the write.
  * ``rpc.recv``  — tripped before a frame is read; ``corrupt`` damages
    the received payload before parsing (the torn-read drill).

Every decode failure surfaces as a typed :class:`RpcError` (clean EOF at
a frame boundary is :class:`ConnectionClosed`) so recovery layers can
tell a broken peer from a broken program.

Deadline propagation convention: request headers carry
``deadline_s`` — the budget REMAINING at send time, in seconds (never an
absolute timestamp: the two processes do not share a clock). Each hop
re-derives a local :class:`~paddle_tpu.reliability.policy.Deadline` from
it, so queue time spent anywhere on the path keeps counting and a worker
can refuse already-expired work without doing it.

Trace propagation convention (``paddle_tpu.obs.trace``): request headers
may carry ``trace`` — ``{"tid": <64-bit-hex trace id>, "sid": <64-bit-hex
span id>}``. Unlike the deadline, the TRACE ID propagates verbatim for
the whole request (it names the trace); the SPAN ID is re-injected by
each hop with its own current span, so the receiver's spans parent under
the sender's. Peers that don't trace ignore the key (unknown header keys
are always ignored — that is what makes both conventions zero wire-format
changes) and, when forwarding, keep it intact by copying the header.
"""

import io
import json
import socket
import struct

import numpy as np

from ..reliability import faults

__all__ = ["RpcError", "ConnectionClosed", "send_msg", "recv_msg",
           "connect", "MAX_FRAME_BYTES"]

_LEN = struct.Struct("!Q")
_HDR = struct.Struct("!I")

#: refuse frames beyond this size instead of letting a corrupt length
#: prefix turn into an unbounded allocation
MAX_FRAME_BYTES = 1 << 30


class RpcError(RuntimeError):
    """A frame failed to move or decode (broken pipe, torn frame,
    corrupt payload). Retryable at the dispatch layer — the REQUEST is
    not known to be bad, the hop is."""


class ConnectionClosed(RpcError):
    """The peer closed cleanly at a frame boundary (EOF before the first
    length byte) — the normal end of a persistent connection."""


def encode_msg(header, arrays=None):
    """One wire frame's payload: 4-byte header length + JSON header +
    ``np.savez`` blob (empty when there are no arrays)."""
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    if arrays:
        buf = io.BytesIO()
        np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
        body = buf.getvalue()
    else:
        body = b""
    return _HDR.pack(len(hdr)) + hdr + body


def decode_msg(payload):
    """Inverse of :func:`encode_msg`; raises :class:`RpcError` on any
    malformed byte instead of leaking codec exceptions upward."""
    try:
        if len(payload) < _HDR.size:
            raise ValueError("frame shorter than its header-length field")
        (hlen,) = _HDR.unpack_from(payload)
        if hlen > len(payload) - _HDR.size:
            raise ValueError("header length %d overruns the frame" % hlen)
        header = json.loads(
            payload[_HDR.size:_HDR.size + hlen].decode("utf-8"))
        body = payload[_HDR.size + hlen:]
        arrays = {}
        if body:
            with np.load(io.BytesIO(body), allow_pickle=False) as z:
                arrays = {k: z[k] for k in z.files}
        return header, arrays
    except RpcError:
        raise
    except Exception as e:
        raise RpcError("bad frame: %s: %s" % (type(e).__name__, e)) from e


def _read_exact(sock, n, at_boundary=False):
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except socket.timeout as e:
            raise RpcError("recv timed out (%d/%d bytes)" % (got, n)) from e
        except OSError as e:
            raise RpcError("recv failed: %s" % e) from e
        if not chunk:
            if at_boundary and got == 0:
                raise ConnectionClosed("peer closed the connection")
            raise RpcError("peer closed mid-frame (%d/%d bytes)" % (got, n))
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_msg(sock, header, arrays=None):
    """Write one frame. Fault site ``rpc.send``: ``error`` raises in the
    sender, ``corrupt`` ships a damaged payload the peer will reject."""
    mode = faults.trip("rpc.send")
    payload = encode_msg(header, arrays)
    if mode == "corrupt":
        payload = faults.corrupt_bytes(payload)
    try:
        sock.sendall(_LEN.pack(len(payload)) + payload)
    except socket.timeout as e:
        raise RpcError("send timed out") from e
    except OSError as e:
        raise RpcError("send failed: %s" % e) from e


def recv_msg(sock):
    """Read one frame -> ``(header, arrays)``. Fault site ``rpc.recv``:
    ``corrupt`` damages the received payload before the parse."""
    mode = faults.trip("rpc.recv")
    raw = _read_exact(sock, _LEN.size, at_boundary=True)
    (n,) = _LEN.unpack(raw)
    if n > MAX_FRAME_BYTES:
        raise RpcError("frame length %d exceeds MAX_FRAME_BYTES (corrupt "
                       "length prefix?)" % n)
    payload = _read_exact(sock, n)
    if mode == "corrupt":
        payload = faults.corrupt_bytes(payload)
    return decode_msg(payload)


def connect(address, timeout=None):
    """TCP connect with ``TCP_NODELAY`` (frames are latency-sensitive and
    already coalesced — Nagle only adds tail)."""
    host, port = address
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    return sock

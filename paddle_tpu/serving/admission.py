"""Admission control: backpressure and per-request deadlines.

A serving engine that accepts unbounded work converts overload into
unbounded latency for *everyone*; the production idiom (and the reference's
bounded BlockingQueue in its reader/serving plumbing) is a bounded queue
that fast-fails new arrivals while in-flight work completes untouched.
Deadlines are enforced twice: an expired request still sitting in the queue
is dropped *before* it wastes a batch slot, and ``Future.result(timeout)``
covers the tail end for callers that block.
"""

import threading

__all__ = ["ServerOverloadedError", "DeadlineExceededError",
           "AdmissionController"]


class ServerOverloadedError(RuntimeError):
    """Queue depth limit hit — the request was rejected at the door.

    Callers should treat this as retryable-with-backoff (HTTP 429/503
    semantics), not as a server fault."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed before a worker could serve it."""


class AdmissionController:
    """Counting gate over the engine's queue depth.

    ``acquire`` admits up to ``max_queue_depth`` in-flight examples and
    raises :class:`ServerOverloadedError` beyond that — it never blocks,
    because blocking the submitter just moves the unbounded queue into the
    callers' threads. ``release`` returns capacity when a request leaves
    the system (served, failed, expired, or rejected by a later check).
    """

    def __init__(self, max_queue_depth):
        if max_queue_depth is not None and max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1 or None")
        self.max_queue_depth = max_queue_depth
        self._lock = threading.Lock()
        self._in_flight = 0

    @property
    def in_flight(self):
        return self._in_flight

    def acquire(self, n=1):
        with self._lock:
            limit = self.max_queue_depth
            if limit is not None and self._in_flight + n > limit:
                raise ServerOverloadedError(
                    "queue full: %d in flight + %d new > depth limit %d"
                    % (self._in_flight, n, limit))
            self._in_flight += n

    def release(self, n=1):
        with self._lock:
            self._in_flight = max(0, self._in_flight - n)

    def shortfall(self, n=1):
        """How many slots short the gate is of admitting ``n`` more
        examples right now (0 = would be admitted)."""
        with self._lock:
            if self.max_queue_depth is None:
                return 0
            return max(0, self._in_flight + n - self.max_queue_depth)

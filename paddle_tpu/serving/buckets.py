"""Shape-bucketing policy: bound the XLA compile cache.

Every distinct feed shape is a distinct XLA executable (the Executor's
program cache keys on the feed signature, ``core/executor.py:370``), so an
open-world serving frontend that forwards raw request shapes compiles
without bound. The classic fix — the reference bounds work per request by
server-side batching (its serving path pins shapes at graph-build time) —
is a *bucket ladder*: pad the batch dim (and optionally a sequence dim) up
to the nearest rung, so at most ``len(ladder)`` executables exist per fetch
program and warm-up can pre-compile every one of them.

Padding replicates the last real row (edge padding) rather than writing
zeros: integer feeds are usually embedding ids, and a fabricated id 0 is a
real vocabulary entry whose gather is fine, but zero-padding a feed with a
declared non-zero lower bound (or a mask convention) is a silent way to
feed the model out-of-distribution garbage. Replicated rows are sliced off
by :func:`unpad_fetch` before results leave the engine.
"""

import numpy as np

__all__ = ["pow2_ladder", "bucket_for", "pad_to_bucket", "unpad_fetch",
           "edge_pad", "BucketError"]


class BucketError(ValueError):
    """A request doesn't fit any rung of the ladder."""


def pow2_ladder(max_batch_size):
    """Powers of two up to and including ``max_batch_size`` (the default
    ladder: compile cache bounded at ~log2(max_batch_size) entries).

    >>> pow2_ladder(8)
    (1, 2, 4, 8)
    >>> pow2_ladder(6)
    (1, 2, 4, 6)
    """
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1, got %r"
                         % (max_batch_size,))
    ladder = []
    r = 1
    while r < max_batch_size:
        ladder.append(r)
        r *= 2
    ladder.append(int(max_batch_size))
    return tuple(ladder)


def _normalize(ladder):
    rungs = sorted(set(int(r) for r in ladder))
    if not rungs or rungs[0] < 1:
        raise ValueError("ladder must hold positive rungs, got %r"
                         % (ladder,))
    return rungs


def bucket_for(n, ladder):
    """Smallest rung >= n (the bucket a size-``n`` batch compiles as)."""
    for r in _normalize(ladder):
        if n <= r:
            return r
    raise BucketError("batch of %d exceeds the top ladder rung %d"
                      % (n, max(ladder)))


def edge_pad(a, target, axis):
    """Lengthen ``a`` to ``target`` along ``axis`` by replicating the last
    real entry (the in-distribution padding the module docstring argues
    for). No-op when already at or past ``target``."""
    if a.shape[axis] >= target:
        return a
    idx = np.minimum(np.arange(target), a.shape[axis] - 1)
    return np.take(a, idx, axis=axis)


def pad_to_bucket(feed, ladder, seq_ladder=None, seq_dim=1):
    """Pad every array in ``feed`` (dict name -> array with a leading batch
    dim) up to the ladder rung covering the actual batch size.

    With ``seq_ladder``, arrays of rank >= 2 also get ``seq_dim`` padded up
    its own rung (edge replication again — repeated trailing tokens), which
    bounds compiles for variable-length text serving at
    ``len(ladder) * len(seq_ladder)``.

    Returns ``(padded_feed, n)`` where ``n`` is the true batch size, for
    :func:`unpad_fetch`.
    """
    arrays = {k: np.asarray(v) for k, v in feed.items()}
    # 0-d feeds (scalars) carry no batch dim: excluded from the consensus
    # and passed through unpadded
    sizes = {k: a.shape[0] for k, a in arrays.items() if a.ndim}
    if len(set(sizes.values())) > 1:
        raise ValueError("feeds disagree on batch size: %s" % (sizes,))
    n = next(iter(sizes.values())) if sizes else 0
    if n < 1:
        raise ValueError("empty batch")
    rung = bucket_for(n, ladder)
    out = {}
    for k, a in arrays.items():
        if a.ndim == 0:
            out[k] = a
            continue
        a = edge_pad(a, rung, 0)
        if seq_ladder is not None and a.ndim >= 2:
            a = edge_pad(a, bucket_for(a.shape[seq_dim], seq_ladder),
                         seq_dim)
        out[k] = a
    return out, n


def unpad_fetch(fetches, n, padded_to=None):
    """Slice fetch results back to the true batch size ``n``. With
    ``padded_to`` (the rung the batch was padded to) only outputs whose
    leading dim IS the padded batch are sliced — a non-batch output that
    happens to be longer than ``n`` (a class-prior vector, say) passes
    through untouched, as do scalar summaries and already-reduced
    metrics."""
    out = []
    for f in fetches:
        a = np.asarray(f)
        if a.ndim >= 1 and (a.shape[0] == padded_to
                            if padded_to is not None else a.shape[0] >= n):
            a = a[:n]
        out.append(a)
    return out

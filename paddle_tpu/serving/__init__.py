"""``paddle_tpu.serving`` — in-process dynamic-batching inference serving.

The layer between the model zoo and "heavy traffic from millions of
users": the reference frames inference as a first-class subsystem
(``paddle/fluid/inference``, ``AnalysisPredictor::Init/Run/Clone``); this
package is that subsystem's TPU-native serving tier. See ``engine.py`` for
the architecture; quickstart:

    from paddle_tpu import serving

    eng = serving.ServingEngine("my/model/dir", num_replicas=2,
                                max_batch_size=8)
    eng.warmup()                       # pre-compile every bucket rung
    fut = eng.submit({"x": one_or_more_rows})
    prob, = fut.result(timeout=1.0)
    print(eng.metrics_report())
    eng.shutdown(drain=True)

Multi-process front door (``router.py`` / ``worker.py`` / ``rpc.py``):

    router = serving.Router("my/model/dir", num_workers=4)
    router.start()
    client = serving.RouterClient(router.address)
    prob, = client.predict({"x": rows}, timeout_s=1.0)
    client.close(); router.shutdown()
"""

from .admission import (AdmissionController, DeadlineExceededError,  # noqa: F401
                        ServerOverloadedError)
from .batcher import DynamicBatcher, Request  # noqa: F401
from .buckets import (BucketError, bucket_for, pad_to_bucket,  # noqa: F401
                      pow2_ladder, unpad_fetch)
from .decode_batcher import (DecodeBatcher, DecodeRequest,  # noqa: F401
                             DraftLM, default_prefill_ladder,
                             load_decode_spec, save_decode_spec)
from .engine import EngineShutdownError, ServingEngine  # noqa: F401
from .metrics import ServingMetrics  # noqa: F401
from .prefix_cache import PrefixCache, PrefixEntry, PrefixMatch  # noqa: F401
from .router import (Router, RouterClient, RouterShutdownError,  # noqa: F401
                     WorkerFailedError)

__all__ = ["ServingEngine", "EngineShutdownError", "DynamicBatcher",
           "Request", "ServingMetrics", "AdmissionController",
           "ServerOverloadedError", "DeadlineExceededError", "BucketError",
           "pow2_ladder", "bucket_for", "pad_to_bucket", "unpad_fetch",
           "DecodeBatcher", "DecodeRequest", "DraftLM",
           "default_prefill_ladder", "PrefixCache", "PrefixEntry",
           "PrefixMatch", "save_decode_spec", "load_decode_spec",
           "Router", "RouterClient", "WorkerFailedError",
           "RouterShutdownError"]

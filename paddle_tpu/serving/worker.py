"""Serving worker process: one :class:`ServingEngine` behind a socket.

Spawned and supervised by :mod:`paddle_tpu.serving.router`; runnable by
hand for debugging::

    python -m paddle_tpu.serving.worker --model path/to/saved_model \\
        --port 0 --replicas 1

The worker binds an EPHEMERAL port (``--port 0``, the default — never a
fixed port: parallel test runs and respawns must not race on one) and
announces it on stdout as a single machine-readable line::

    PADDLE_TPU_WORKER_READY {"port": 41123, "pid": 7}

which the router parses to learn the address. Everything after that line
is diagnostics.

Protocol (one ``rpc`` frame in, one out, persistent connections):

  * ``{"type": "ping"}`` -> ``{"type": "pong", "stats": {...}}`` — the
    health-check probe, answering with live engine gauges (queue depth,
    in-flight, deadline_refused) the router folds into routing.
  * ``{"type": "infer", "deadline_s": <remaining-or-null>, ...}`` plus
    the feed arrays -> ``{"type": "result", "n_out": N}`` plus fetch
    arrays ``o0..o{N-1}``, or a typed ``{"type": "error", "error":
    <kind>, "message": ...}``. A request whose propagated budget is
    already spent is REFUSED before it touches the engine
    (``error: "DeadlineRefused"``) — expired work must not occupy a
    batch slot anywhere on the path.
  * ``{"type": "reload", "dir": <checkpoint dir>, "version": <n-or-null>}``
    -> ``{"type": "reloaded", "version": N}`` — the streaming publish
    plane's hot-swap verb: stages a CRC-verified load of the newest
    intact (or given) ``checkpoint_<n>`` and flips every engine replica
    to the new parameters between micro-batches (in-flight requests
    finish on the old weights). A corrupt/mismatched version answers a
    typed ``{"error": "ReloadFailed"}`` and the old model keeps serving.
  * ``{"type": "prepare", "dir": ..., "version": <n-or-null>}`` ->
    ``{"type": "prepared", "version": N}`` — phase 1 of the fleet's
    two-phase swap: CRC-stage the version WITHOUT swapping (typed
    ``{"error": "PrepareFailed"}`` aborts the fleet's swap, nothing
    flips anywhere).
  * ``{"type": "commit", "version": <n-or-null>}`` ->
    ``{"type": "committed", "version": N}`` — phase 2: flip to the
    staged version; idempotent under retry (a lost ACK re-commits
    clean). ``{"type": "abort"}`` drops a staged version.
  * ``{"type": "shutdown"}`` -> acked, then the process drains and exits.

``--model`` takes a ``save_inference_model`` directory or a
``builtin:<name>`` spec (``builtin:fc`` tiny classifier,
``builtin:mt_greedy`` the machine-translation greedy While-loop decoder)
— the builtins exist so tests and the chaos harness can prove the door
is model-agnostic without shipping model files around.
"""

import argparse
import json
import os
import signal
import socketserver
import sys
import threading

import numpy as np

from ..obs import flight, trace
from ..reliability import faults
from . import rpc
from .admission import DeadlineExceededError, ServerOverloadedError

__all__ = ["READY_PREFIX", "main", "build_model"]

READY_PREFIX = "PADDLE_TPU_WORKER_READY "


def build_model(spec):
    """Resolve ``--model``: a saved-model directory passes through (the
    engine loads it); ``builtin:<name>`` builds a small in-process
    program + scope and returns a ``ProgramPredictor`` over it.
    ``builtin:lm_decode`` builds the KV-cache decode step program (plus
    its chunk sibling for chunked prefill) and tags the predictor with
    ``_decode_spec``/``_decode_prefill`` so ``main()`` can stand up a
    continuous-batching decode engine behind the same socket."""
    if not spec.startswith("builtin:"):
        return spec
    name = spec.split(":", 1)[1]
    import paddle_tpu as fluid
    from ..inference import ProgramPredictor

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 11
    scope = fluid.Scope()
    decode_spec = lm_cfg = None
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope):
        fluid.unique_name.switch()
        if name == "fc":
            x = fluid.layers.data("x", shape=[8])
            prob = fluid.layers.softmax(fluid.layers.fc(x, size=4))
            fetches, feeds = [prob], ["x"]
        elif name == "mt_greedy":
            from ..models import machine_translation as mt

            ids, scores = mt.seq2seq_attention_greedy_infer(
                src_vocab=32, trg_vocab=32, seq_len=6, emb_dim=8,
                hid_dim=8, max_out_len=4)
            fetches, feeds = [ids, scores], ["src_ids", "src_len"]
        elif name == "lm_decode":
            from ..models import transformer as tlm

            lm_cfg = tlm.lm_step_config(
                vocab=29, d_model=16, d_ff=32, n_head=2, n_layer=2,
                ctx_cap=32, pos_cap=64)
            fetches, decode_spec = tlm.transformer_lm_step(**lm_cfg)
            feeds = [decode_spec["token_feed"], decode_spec["pos_feed"]] \
                + [c["feed"] for c in decode_spec["cache_feeds"]]
        else:
            raise SystemExit("unknown builtin model %r (have: fc, "
                             "mt_greedy, lm_decode)" % name)
        exe = fluid.Executor(fluid.XLAPlace(0))
        exe.run(startup)
    pred = ProgramPredictor(main_prog, feeds, fetches, scope=scope)
    if decode_spec is not None:
        # chunk sibling: same ParamAttr names -> same scope entries, so
        # its startup is never run (the step startup initialized them)
        from ..models import transformer as tlm

        chunk_main, chunk_start = fluid.Program(), fluid.Program()
        chunk_main.random_seed = chunk_start.random_seed = 11
        with fluid.program_guard(chunk_main, chunk_start), \
                fluid.scope_guard(scope):
            fluid.unique_name.switch()
            cfetch, cspec = tlm.transformer_lm_chunk(**lm_cfg)
        cfeeds = [cspec["token_feed"], cspec["pos_feed"]] \
            + [c["feed"] for c in cspec["cache_feeds"]]
        cpred = ProgramPredictor(chunk_main, cfeeds, cfetch, scope=scope)
        pred._decode_spec = decode_spec
        pred._decode_prefill = {"predictor": cpred, "spec": cspec}
    return pred


class _WorkerState:
    """Counters + the engine, shared with every connection thread."""

    def __init__(self, engine):
        self.engine = engine
        self.lock = threading.Lock()
        self.deadline_refused = 0
        self.served = 0
        self.stop = threading.Event()


def _stats(state):
    eng = state.engine
    with state.lock:
        refused, served = state.deadline_refused, state.served
    return {
        "pid": os.getpid(),
        "queue_depth": eng._batcher.depth() if eng._batcher else 0,
        "in_flight": eng._admission.in_flight,
        "deadline_refused": refused,
        "served": served,
        # fleet version-skew must be auditable from OUTSIDE: every
        # ping/stats answer names the model version this worker serves
        "serve_version": eng.serve_version,
        "swap_count": eng.swap_count,
    }


def _handle_infer(state, header, arrays):
    """One request end-to-end; ALWAYS returns a response pair — an
    accepted frame never goes unanswered (zero-silent-loss starts here)."""
    remaining = header.get("deadline_s")
    if remaining is not None and remaining <= 0:
        # deadline propagation's whole point: budget spent in transit or
        # in the router queue is refused BEFORE an engine slot is wasted
        with state.lock:
            state.deadline_refused += 1
        flight.record("deadline.refused", where="worker",
                      overdue_s=-remaining)
        return {"type": "error", "error": "DeadlineRefused",
                "message": "request budget expired %.3fs before it "
                           "reached the worker" % -remaining}, None
    # adopt the router-propagated context so the queue span (and the
    # engine.batch span parented through req.trace_ctx) stitch onto the
    # client's trace id
    tracer = trace.active()
    token = None
    if tracer is not None:
        ctx = trace.extract(header)
        if ctx is not None:
            token = tracer.activate(ctx)
    try:
        with trace.span("worker.queue") as sp:
            fut = state.engine.submit(
                dict(arrays), timeout_s=remaining,
                max_new_tokens=header.get("max_new_tokens"),
                eos_id=header.get("eos_id"))
            outs = fut.result(remaining + 30.0 if remaining is not None
                              else 300.0)
            if sp:
                sp.set(pid=os.getpid())
    except ServerOverloadedError as e:
        return {"type": "error", "error": "ServerOverloaded",
                "message": str(e)}, None
    except DeadlineExceededError as e:
        return {"type": "error", "error": "DeadlineExceeded",
                "message": str(e)}, None
    except Exception as e:
        return {"type": "error", "error": "WorkerFailed",
                "message": "%s: %s" % (type(e).__name__, e)}, None
    finally:
        if token is not None:
            tracer.deactivate(token)
    with state.lock:
        state.served += 1
    if not isinstance(outs, (list, tuple)):
        outs = [outs]  # decode futures resolve to ONE ids array
    out_arrays = {"o%d" % i: np.asarray(o) for i, o in enumerate(outs)}
    return {"type": "result", "n_out": len(outs)}, out_arrays


def _handle_reload(state, header):
    """Hot-swap the engine to a published checkpoint version. Failure is
    typed, never fatal: the worker keeps serving the old weights and the
    caller (publisher/router) decides on fallback."""
    ckpt_dir = header.get("dir")
    if not ckpt_dir:
        return {"type": "error", "error": "Rpc",
                "message": "reload needs a 'dir' field"}
    try:
        version = state.engine.reload(ckpt_dir,
                                      version=header.get("version"))
    except Exception as e:
        return {"type": "error", "error": "ReloadFailed",
                "message": "%s: %s" % (type(e).__name__, e)}
    return {"type": "reloaded", "version": version,
            "swap_count": state.engine.swap_count}


def _handle_prepare(state, header):
    """Phase 1 of the fleet's two-phase swap: CRC-stage, don't touch the
    served weights. Typed failure aborts the whole fleet's swap."""
    ckpt_dir = header.get("dir")
    if not ckpt_dir:
        return {"type": "error", "error": "Rpc",
                "message": "prepare needs a 'dir' field"}
    try:
        version = state.engine.prepare(ckpt_dir,
                                       version=header.get("version"))
    except Exception as e:
        return {"type": "error", "error": "PrepareFailed",
                "message": "%s: %s" % (type(e).__name__, e)}
    return {"type": "prepared", "version": version}


def _handle_commit(state, header):
    """Phase 2: flip to the staged version (idempotent under retry).
    Typed failure = this worker stays on the old weights; the fleet
    publisher quarantines the target and makes the skew loud."""
    try:
        version = state.engine.commit(version=header.get("version"))
    except Exception as e:
        return {"type": "error", "error": "CommitFailed",
                "message": "%s: %s" % (type(e).__name__, e)}
    return {"type": "committed", "version": version,
            "swap_count": state.engine.swap_count}


def _make_server(host, port, state):
    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            sock = self.request
            while not state.stop.is_set():
                try:
                    header, arrays = rpc.recv_msg(sock)
                except rpc.ConnectionClosed:
                    return
                except rpc.RpcError as e:
                    # torn/corrupt frame: answer typed if the pipe still
                    # works, then drop the connection (framing is lost)
                    try:
                        rpc.send_msg(sock, {"type": "error",
                                            "error": "Rpc",
                                            "message": str(e)})
                    except Exception:
                        pass
                    return
                kind = header.get("type")
                if kind == "ping":
                    resp, out = {"type": "pong",
                                 "stats": _stats(state)}, None
                elif kind == "infer":
                    resp, out = _handle_infer(state, header, arrays)
                elif kind == "stats":
                    # the scrape verb: gauges plus the engine's full
                    # Prometheus exposition (including the MFU gauge)
                    resp, out = {
                        "type": "stats",
                        "stats": _stats(state),
                        "prometheus":
                            state.engine.metrics_.prometheus_text(),
                    }, None
                elif kind == "reload":
                    resp, out = _handle_reload(state, header), None
                elif kind == "prepare":
                    resp, out = _handle_prepare(state, header), None
                elif kind == "commit":
                    resp, out = _handle_commit(state, header), None
                elif kind == "abort":
                    resp, out = {"type": "aborted",
                                 "staged": state.engine.abort_swap()}, None
                elif kind == "shutdown":
                    resp, out = {"type": "ok"}, None
                else:
                    resp, out = {"type": "error", "error": "Rpc",
                                 "message": "unknown message type %r"
                                            % kind}, None
                try:
                    rpc.send_msg(sock, resp, out)
                except rpc.RpcError:
                    return
                if kind == "shutdown":
                    state.stop.set()
                    threading.Thread(target=self.server.shutdown,
                                     daemon=True).start()
                    return

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    return Server((host, port), Handler)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.serving.worker",
        description=__doc__.splitlines()[0])
    ap.add_argument("--model", required=True,
                    help="saved-model dir or builtin:<fc|mt_greedy>")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 (default) binds an ephemeral port, announced "
                         "on stdout")
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--ladder", default="1,2,4,8",
                    help="comma batch rungs for the engine bucket ladder")
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-queue-depth", type=int, default=256)
    ap.add_argument("--placement", default="single",
                    choices=["single", "per_device"])
    ap.add_argument("--mp", type=int, default=1)
    ap.add_argument("--warmup", action="store_true",
                    help="pre-compile every bucket rung before READY")
    args = ap.parse_args(argv)

    faults.maybe_install_from_env()
    trace.maybe_start_from_env()
    flight.install()
    from .engine import ServingEngine

    ladder = tuple(int(x) for x in args.ladder.split(",") if x.strip())
    model = build_model(args.model)
    # decode mode: a builtin:lm_decode predictor carries its spec; a
    # saved-model dir carrying decode_spec.json auto-serves decode.
    # Knobs ride env vars so the router's worker_env reaches them
    # without new CLI plumbing: PADDLE_TPU_PREFIX_CACHE_MB (>0 turns on
    # the shared prefix-KV cache), PADDLE_TPU_DECODE_MAX_NEW (default
    # max_new_tokens per request)
    decode_kw = {}
    decode_spec = getattr(model, "_decode_spec", None)
    if decode_spec is not None:
        decode_kw["decode"] = decode_spec
        prefill = getattr(model, "_decode_prefill", None)
        if prefill is not None:
            decode_kw["decode_prefill"] = prefill
    elif isinstance(model, str) and os.path.exists(
            os.path.join(model, "decode_spec.json")):
        decode_kw["decode"] = True
    if decode_kw:
        try:
            mb = float(os.environ.get("PADDLE_TPU_PREFIX_CACHE_MB",
                                      "0") or 0)
        except ValueError:
            mb = 0.0
        if mb > 0:
            decode_kw["prefix_cache"] = {
                "max_bytes": int(mb * (1 << 20))}
        try:
            decode_kw["default_max_new_tokens"] = int(os.environ.get(
                "PADDLE_TPU_DECODE_MAX_NEW", "16"))
        except ValueError:
            decode_kw["default_max_new_tokens"] = 16
    engine = ServingEngine(model,
                           num_replicas=args.replicas, ladder=ladder,
                           max_wait_ms=args.max_wait_ms,
                           max_queue_depth=args.max_queue_depth,
                           placement=args.placement, mp=args.mp,
                           **decode_kw)
    if args.warmup:
        try:
            engine.warmup()
        except Exception as e:  # warmup is an optimization, not a gate
            print("worker: warmup failed: %r" % e, file=sys.stderr)

    state = _WorkerState(engine)
    server = _make_server(args.host, args.port, state)
    port = server.server_address[1]

    def _on_term(signum, frame):
        state.stop.set()
        threading.Thread(target=server.shutdown, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)
    except ValueError:
        pass  # not the main thread (in-process test harness)

    print(READY_PREFIX + json.dumps(
        {"port": port, "pid": os.getpid(), "model": args.model}),
        flush=True)
    try:
        server.serve_forever(poll_interval=0.1)
    finally:
        server.server_close()
        engine.shutdown(drain=True, timeout_s=5.0)
        # SIGTERM from the router lands here via _on_term -> shutdown,
        # so a reaped worker still flushes its trace shard and flight ring
        trace.flush()
        flight.maybe_dump(reason="worker-shutdown")
    return 0


if __name__ == "__main__":
    sys.exit(main())

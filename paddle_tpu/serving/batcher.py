"""Dynamic micro-batcher: coalesce requests until size or deadline.

Fluid's server-side batching idiom, TPU-native: single-example requests
queue up and a worker cuts a batch when either ``max_batch_size`` examples
are waiting or the oldest request has waited ``max_wait_ms`` — the standard
throughput/latency knob pair. Cut batches land on bucket boundaries (the
engine pads them up a rung, ``buckets.pad_to_bucket``) so every dispatch
hits a warmed XLA executable.

Time is injected (``clock=``) instead of read from ``time.monotonic``
directly: the tier-1 unit test drives a fake clock through the deadline
logic with zero sleeping, and the condition-variable wait only engages when
a real clock says the deadline is genuinely in the future.
"""

import threading
import time
from collections import deque

__all__ = ["Request", "DynamicBatcher"]


class Request:
    """One queued inference request: ``feed`` (dict name -> array with a
    leading batch dim), its example count ``n``, the caller's ``future``,
    the admission timestamps the deadline checks read, and ``retries``
    (how many times a failed batch has re-enqueued it — the engine's
    cross-replica retry budget). ``trace_ctx`` carries the submitter's
    (trace_id, span_id) across the queue so the batch-serving thread can
    parent its span onto the request's trace (``obs/trace.py``)."""

    __slots__ = ("feed", "n", "future", "enqueue_t", "deadline", "retries",
                 "trace_ctx")

    def __init__(self, feed, n, future, enqueue_t, deadline=None):
        self.feed = feed
        self.n = n
        self.future = future
        self.enqueue_t = enqueue_t
        self.deadline = deadline
        self.retries = 0
        self.trace_ctx = None


class DynamicBatcher:
    """Thread-safe request queue with size-or-deadline batch cuts.

    ``get_batch`` blocks the calling worker until a batch is ready (or the
    batcher is closed and drained, returning ``None``); any number of
    workers may call it concurrently — each cut is exclusive under the
    queue lock.
    """

    def __init__(self, max_batch_size, max_wait_ms=5.0, clock=None):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        self.max_batch_size = int(max_batch_size)
        self.max_wait_s = max_wait_ms / 1000.0
        self._clock = clock or time.monotonic
        self._queue = deque()
        self._depth = 0  # queued examples (sum of request.n)
        self._closed = False
        self._cv = threading.Condition()

    def now(self):
        return self._clock()

    def put(self, request):
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._queue.append(request)
            self._depth += request.n
            self._cv.notify()

    def depth(self):
        """Queued examples not yet cut into a batch (the queue-depth
        gauge; in-flight batches are the engine's to count)."""
        with self._cv:
            return self._depth

    def close(self):
        """Stop the workers once the queue drains: after close, ``put``
        raises and ``get_batch`` returns ``None`` when nothing is left."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain(self):
        """Pop everything still queued (shutdown(drain=False) path)."""
        with self._cv:
            out = list(self._queue)
            self._queue.clear()
            self._depth = 0
            self._cv.notify_all()
        return out

    def shed_for(self, deadline, shortfall=1):
        """Earliest-deadline-first shedding: pop and return the queued
        request with the LATEST deadline, provided (a) it is strictly
        later than ``deadline`` (``None`` = no deadline = infinitely
        late, so deadline-less queue entries are shed first and a
        deadline-less arrival can never displace anything) and (b) the
        TOTAL sheddable depth — examples on strictly-later deadlines —
        covers ``shortfall``, so a victim is never killed for an arrival
        that could not be admitted anyway. Returns None otherwise — the
        caller then falls back to plain rejection."""
        inf = float("inf")
        incoming = inf if deadline is None else deadline
        with self._cv:
            worst_i = None
            worst_d = -inf
            sheddable = 0
            for i, r in enumerate(self._queue):
                d = inf if r.deadline is None else r.deadline
                if d <= incoming:
                    continue
                sheddable += r.n
                if d >= worst_d:  # ties shed the youngest (least sunk wait)
                    worst_i, worst_d = i, d
            if worst_i is None or sheddable < shortfall:
                return None
            victim = self._queue[worst_i]
            del self._queue[worst_i]
            self._depth -= victim.n
            return victim

    def _cut_locked(self):
        """Pop a batch: greedy fill up to max_batch_size examples."""
        batch = []
        n = 0
        while self._queue and n + self._queue[0].n <= self.max_batch_size:
            r = self._queue.popleft()
            self._depth -= r.n
            batch.append(r)
            n += r.n
        if not batch and self._queue:
            # head request alone exceeds max_batch_size: the engine
            # validates against the ladder at submit time, so this is a
            # defensive cut — serve it solo rather than deadlock
            r = self._queue.popleft()
            self._depth -= r.n
            batch.append(r)
        return batch

    def get_batch(self):
        with self._cv:
            while True:
                if self._queue:
                    if self._depth >= self.max_batch_size or self._closed:
                        return self._cut_locked()
                    deadline = self._queue[0].enqueue_t + self.max_wait_s
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return self._cut_locked()
                    # wall-clock wait even under an injected clock: the
                    # notify on put/close re-checks the injected time, and
                    # the real-time cap keeps the wait from overshooting a
                    # fake deadline by more than one max_wait quantum
                    self._cv.wait(remaining)
                else:
                    if self._closed:
                        return None
                    self._cv.wait()

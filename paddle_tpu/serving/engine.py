"""ServingEngine: the in-process serving layer over the predictor stack.

Reference shape: ``AnalysisPredictor::Init`` loads once, ``Clone()`` hands
each serving thread a predictor sharing the weights, and the server in
front batches requests. Here the same three-layer split is TPU-native:

  * load once — one ``Predictor`` (isolated ``Scope`` holding the weights)
    or one ``StableHLOPredictor`` (immutable exported computation);
  * replicate — ``clone()`` per worker thread: weights shared, per-worker
    Executor compile cache (``inference.py`` clone contract), so replicas
    never contend on a cache dict while XLA releases the GIL during runs;
  * batch — a ``DynamicBatcher`` cuts size-or-deadline micro-batches,
    ``buckets.pad_to_bucket`` pads them onto the ladder so every dispatch
    hits one of at most ``len(ladder)`` compiled executables, and
    ``warmup()`` pre-compiles every rung before traffic lands.

``submit(feed) -> Future`` is the whole client API; ``shutdown(drain=True)``
stops intake, serves what's queued, and joins the workers. A worker that
crashes mid-batch fails only that batch's futures and keeps serving.
"""

import threading
from concurrent.futures import Future

import numpy as np

from ..inference import AnalysisConfig, Predictor
from .admission import (AdmissionController, DeadlineExceededError,
                        ServerOverloadedError)
from .batcher import DynamicBatcher, Request
from .buckets import (bucket_for, edge_pad, pad_to_bucket, pow2_ladder,
                      unpad_fetch)
from .metrics import ServingMetrics

__all__ = ["ServingEngine"]


class _Worker:
    """One replica: a predictor clone plus the shape signatures it has
    dispatched (the engine-side view of its compile cache, valid for both
    predictor types)."""

    def __init__(self, predictor):
        self.predictor = predictor
        self.seen_signatures = set()
        self.thread = None


class ServingEngine:
    def __init__(self, model, num_replicas=1, max_batch_size=8,
                 ladder=None, seq_ladder=None, max_wait_ms=5.0,
                 max_queue_depth=256, default_timeout_s=None, clock=None,
                 latency_window=8192):
        """``model``: a model directory / ``AnalysisConfig`` (loaded via
        ``Predictor``), or an already-constructed predictor exposing
        ``run``/``clone``/``feed_names`` (``Predictor`` or
        ``StableHLOPredictor``)."""
        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        if isinstance(model, (str, AnalysisConfig)):
            model = Predictor(model)
        if not callable(getattr(model, "clone", None)):
            raise TypeError("model must be a dir/AnalysisConfig or a "
                            "predictor with clone(); got %r" % (model,))
        self.ladder = tuple(sorted(set(
            ladder if ladder is not None else pow2_ladder(max_batch_size))))
        self.seq_ladder = tuple(seq_ladder) if seq_ladder else None
        self.max_batch_size = max(self.ladder)
        self.feed_names = list(getattr(model, "feed_names", []))
        self.default_timeout_s = default_timeout_s

        self._batcher = DynamicBatcher(self.max_batch_size,
                                       max_wait_ms=max_wait_ms, clock=clock)
        self._admission = AdmissionController(max_queue_depth)
        self.metrics_ = ServingMetrics(latency_window=latency_window)
        self.metrics_.bind_gauges(self._batcher.depth,
                                  lambda: self._admission.in_flight)

        self._workers = [_Worker(model)]
        for _ in range(num_replicas - 1):
            self._workers.append(_Worker(model.clone()))
        self._closed = False
        self._shutdown_done = False
        for i, w in enumerate(self._workers):
            w.thread = threading.Thread(
                target=self._worker_loop, args=(w,),
                name="paddle-tpu-serve-%d" % i, daemon=True)
            w.thread.start()

    # -- client surface -----------------------------------------------------
    def submit(self, feed, timeout_s=None):
        """Enqueue one request; returns a ``concurrent.futures.Future``
        resolving to the fetch list (arrays sliced to this request's rows).

        Raises :class:`ServerOverloadedError` immediately when the bounded
        queue is full, ``BucketError`` when the request's batch exceeds the
        top rung, ``RuntimeError`` after shutdown."""
        if self._closed:
            raise RuntimeError("ServingEngine is shut down")
        if isinstance(feed, (list, tuple)):
            if len(feed) != len(self.feed_names):
                raise ValueError("expected %d inputs (%s), got %d"
                                 % (len(self.feed_names), self.feed_names,
                                    len(feed)))
            feed = dict(zip(self.feed_names, feed))
        feed = {k: np.asarray(v) for k, v in feed.items()}
        if self.feed_names:
            missing = set(self.feed_names) - set(feed)
            if missing:
                raise ValueError("missing feeds: %s" % sorted(missing))
        sizes = {k: a.shape[0] for k, a in feed.items() if a.ndim}
        if not sizes:
            raise ValueError("feeds need a leading batch dim to serve")
        if len(set(sizes.values())) > 1:
            raise ValueError("feeds disagree on batch size: %s" % sizes)
        n = next(iter(sizes.values()))
        bucket_for(n, self.ladder)  # validates n fits the ladder
        if self.seq_ladder:
            for a in feed.values():
                if a.ndim >= 2:
                    # reject an over-long sequence at the door, not inside
                    # a batch where it would fail innocent co-riders
                    bucket_for(a.shape[1], self.seq_ladder)
        try:
            self._admission.acquire(n)
        except ServerOverloadedError:
            self.metrics_.observe_rejected()
            raise
        timeout_s = (timeout_s if timeout_s is not None
                     else self.default_timeout_s)
        now = self._batcher.now()
        req = Request(feed, n, Future(), now,
                      deadline=(now + timeout_s
                                if timeout_s is not None else None))
        try:
            self._batcher.put(req)
        except RuntimeError:
            self._admission.release(n)
            raise RuntimeError("ServingEngine is shut down")
        return req.future

    def predict(self, feed, timeout_s=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(feed, timeout_s=timeout_s).result(timeout_s)

    def warmup(self, example_feed=None):
        """Pre-compile every (batch rung x seq rung) bucket on every
        replica, so the first real request at any bucket hits a warm
        executable. ``example_feed`` is one representative example
        (leading dim 1) — with a ``seq_ladder``, give it the SHORTEST
        sequence you expect, since padding can only lengthen it: seq
        rungs below the example's length can't be warmed from it and are
        skipped. Returns the number of (replica, bucket) compilations
        actually warmed."""
        from .buckets import BucketError

        feed = example_feed
        if feed is None:
            feed = self._synthesize_example()
        feed = {k: np.asarray(v) for k, v in feed.items()}
        warmed = 0
        seq_rungs = self.seq_ladder or (None,)
        for w in self._workers:
            for rung in self.ladder:
                for s in seq_rungs:
                    try:
                        padded, _ = pad_to_bucket(
                            feed, (rung,),
                            seq_ladder=None if s is None else (s,))
                    except BucketError:
                        continue  # example longer than this seq rung
                    w.predictor.run(padded)
                    w.seen_signatures.add(self._signature(padded))
                    warmed += 1
        return warmed

    def metrics(self):
        return self.metrics_.snapshot()

    def metrics_report(self):
        return self.metrics_.report()

    def compiled_shape_counts(self):
        """Distinct dispatched feed signatures per replica — the bound the
        ladder guarantees (<= len(ladder), or len(ladder)*len(seq_ladder)
        with sequence bucketing). For program-path replicas this mirrors
        the Executor's real compile-cache size."""
        return [len(w.seen_signatures) for w in self._workers]

    def shutdown(self, drain=True, timeout_s=None):
        """Stop intake; with ``drain`` serve everything queued, otherwise
        cancel it. Joins the worker threads. Idempotent."""
        self._closed = True
        if self._shutdown_done:
            return
        self._shutdown_done = True
        if not drain:
            for r in self._batcher.drain():
                if r.future.cancel():
                    self.metrics_.observe_expired()
                else:
                    self.metrics_.observe_failed()
                self._admission.release(r.n)
        self._batcher.close()
        for w in self._workers:
            if w.thread is not None:
                w.thread.join(timeout_s)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    # -- worker side --------------------------------------------------------
    @staticmethod
    def _signature(feed):
        return tuple(sorted((k, a.shape, str(a.dtype))
                            for k, a in feed.items()))

    def _synthesize_example(self):
        """Build a 1-example feed from the program's var metadata (program
        path only — the StableHLO manifest doesn't carry shapes)."""
        prog = getattr(self._workers[0].predictor, "_program", None)
        if prog is None or not self.feed_names:
            raise ValueError("warmup() needs example_feed for this "
                             "predictor type")
        feed = {}
        for name in self.feed_names:
            var = prog.global_block().var(name)
            shape = [1 if (d is None or d < 0) else int(d)
                     for d in (var.shape or (1,))]
            shape[0] = 1
            dtype = np.dtype(var.dtype or "float32")
            if dtype.kind in "iu":
                feed[name] = np.zeros(shape, dtype=dtype)
            else:
                feed[name] = np.full(shape, 0.5, dtype=dtype)
        return feed

    def _worker_loop(self, worker):
        while True:
            batch = self._batcher.get_batch()
            if batch is None:
                return
            try:
                self._serve_batch(worker, batch)
            except BaseException:
                # _serve_batch already failed the batch's futures; a throw
                # reaching here (e.g. from metrics accounting) must not
                # take the replica down with it
                pass

    def _serve_batch(self, worker, batch):
        now = self._batcher.now()
        live = []
        for r in batch:
            if r.future.cancelled():
                self._admission.release(r.n)
                continue
            if r.deadline is not None and now > r.deadline:
                self._fail(r, DeadlineExceededError(
                    "request waited %.1f ms, deadline was %.1f ms"
                    % ((now - r.enqueue_t) * 1e3,
                       (r.deadline - r.enqueue_t) * 1e3)))
                self.metrics_.observe_expired()
                continue
            live.append(r)
        if not live:
            return
        try:
            if len(live) == 1:
                merged = live[0].feed
            else:
                merged = {}
                for k in live[0].feed:
                    vals = [r.feed[k] for r in live]
                    if vals[0].ndim == 0:
                        # scalar feeds (temperature etc.) have no batch dim
                        # to concatenate on; they can share one XLA call
                        # only when every request agrees on the value
                        if any(not np.array_equal(v, vals[0])
                               for v in vals[1:]):
                            raise ValueError(
                                "scalar feed %r differs across batched "
                                "requests; scalars must be equal to "
                                "coalesce" % k)
                        merged[k] = vals[0]
                    else:
                        if self.seq_ladder and vals[0].ndim >= 2:
                            # different seq lengths in one micro-batch:
                            # pad each rider to the rung covering the
                            # longest before the rows can concatenate
                            tgt = bucket_for(
                                max(v.shape[1] for v in vals),
                                self.seq_ladder)
                            vals = [edge_pad(v, tgt, 1) for v in vals]
                        merged[k] = np.concatenate(vals, axis=0)
            padded, n = pad_to_bucket(merged, self.ladder,
                                      seq_ladder=self.seq_ladder)
            rung = bucket_for(n, self.ladder)
            sig = self._signature(padded)
            hit = sig in worker.seen_signatures
            worker.seen_signatures.add(sig)
            outs = worker.predictor.run(padded)
            outs = unpad_fetch(outs, n, padded_to=rung)
        except Exception as e:
            # fail only this batch; the replica (and its clone-shared
            # weights) keep serving
            for r in live:
                self._fail(r, e)
            self.metrics_.observe_failed(len(live))
            return
        self.metrics_.observe_batch(actual=n, bucket=rung, cache_hit=hit)
        done_t = self._batcher.now()
        off = 0
        for r in live:
            rows = [o[off:off + r.n]
                    if (getattr(o, "ndim", 0) >= 1 and o.shape[0] == n)
                    else o for o in outs]
            off += r.n
            try:
                r.future.set_result(rows)
            except Exception:
                pass  # racing cancel; capacity still returns below
            self.metrics_.observe_completed(done_t - r.enqueue_t)
            self._admission.release(r.n)

    def _fail(self, req, exc):
        try:
            req.future.set_exception(exc)
        except Exception:
            pass
        self._admission.release(req.n)

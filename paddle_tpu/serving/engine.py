"""ServingEngine: the in-process serving layer over the predictor stack.

Reference shape: ``AnalysisPredictor::Init`` loads once, ``Clone()`` hands
each serving thread a predictor sharing the weights, and the server in
front batches requests. Here the same three-layer split is TPU-native:

  * load once — one ``Predictor`` (isolated ``Scope`` holding the weights)
    or one ``StableHLOPredictor`` (immutable exported computation);
  * replicate — ``clone()`` per worker thread: weights shared, per-worker
    Executor compile cache (``inference.py`` clone contract), so replicas
    never contend on a cache dict while XLA releases the GIL during runs;
  * batch — a ``DynamicBatcher`` cuts size-or-deadline micro-batches,
    ``buckets.pad_to_bucket`` pads them onto the ladder so every dispatch
    hits one of at most ``len(ladder)`` compiled executables, and
    ``warmup()`` pre-compiles every rung before traffic lands.

``submit(feed) -> Future`` is the whole client API; ``shutdown(drain=True)``
stops intake, serves what's queued, and joins the workers.

Self-healing (``paddle_tpu.reliability``): a failed batch gets ONE
cross-replica retry before its futures fail (inference is idempotent —
``donate_state=False`` since PR 1 means no state mutation); each replica
carries a :class:`~paddle_tpu.reliability.CircuitBreaker` that, after K
consecutive batch failures, evicts the predictor and rebuilds it from the
parent via ``clone()``; a supervisor thread respawns worker threads that
die outright; and under overload new arrivals with *earlier* deadlines
displace the least-urgent queued request (EDF shedding) instead of being
turned away FIFO-blind. Every event is counted in ``ServingMetrics``
(shed / retried / evicted / respawned) — zero in a healthy run.
"""

import threading
import warnings
from concurrent.futures import Future

import numpy as np

from ..inference import AnalysisConfig, Predictor
from ..obs import flight, trace
from ..reliability import faults
from ..reliability.policy import CircuitBreaker
from .admission import (AdmissionController, DeadlineExceededError,
                        ServerOverloadedError)
from .batcher import DynamicBatcher, Request
from .buckets import (bucket_for, edge_pad, pad_to_bucket, pow2_ladder,
                      unpad_fetch)
from .metrics import ServingMetrics

__all__ = ["ServingEngine", "EngineShutdownError"]


class EngineShutdownError(RuntimeError):
    """The engine shut down before this admitted request could be served
    (a worker was stuck or dead at shutdown); safe to retry elsewhere."""


class _Worker:
    """One replica: a predictor clone, the shape signatures it has
    dispatched (the engine-side view of its compile cache, valid for both
    predictor types), and its circuit breaker (touched only by this
    replica's worker thread)."""

    def __init__(self, predictor, index, breaker):
        self.predictor = predictor
        self.index = index
        self.breaker = breaker
        self.seen_signatures = set()
        self.thread = None


class ServingEngine:
    def __init__(self, model, num_replicas=1, max_batch_size=8,
                 ladder=None, seq_ladder=None, max_wait_ms=5.0,
                 max_queue_depth=256, default_timeout_s=None, clock=None,
                 latency_window=8192, max_replica_failures=3,
                 cross_replica_retry=True, shed_on_overload=True,
                 supervisor_interval_s=0.05, placement="single", mp=1,
                 devices=None, decode=None, default_max_new_tokens=64,
                 eos_id=None, prefix_cache=None, decode_prefill=None,
                 decode_speculative=None):
        """``model``: a model directory / ``AnalysisConfig`` (loaded via
        ``Predictor``), or an already-constructed predictor exposing
        ``run``/``clone``/``feed_names`` (``Predictor``,
        ``ProgramPredictor`` or ``StableHLOPredictor``).

        Placement (ISSUE 14): ``placement="per_device"`` pins replicas
        round-robin over ``devices`` (default ``jax.devices()``) via
        ``clone(device=...)`` — each replica's weights live on its own
        chip instead of all landing on device 0. ``mp=k`` serves a
        tensor-parallel predictor sharded over a k-device ``("mp",)``
        mesh per the program's ``ParamAttr(sharding=...)`` annotations
        (``Predictor.shard``); at build the compiled step is asserted to
        KEEP annotated params sharded (``parallel/sharding_check``) — an
        accidental full replication fails construction, not production.
        With both, devices are partitioned into ``len(devices)//mp``
        groups and replicas round-robin the groups.

        Decode (continuous batching): pass ``decode=<decode-spec dict>``
        (a step builder's spec, e.g. ``transformer_lm_step``) — or
        ``decode=True`` with a model dir carrying ``decode_spec.json`` —
        and the engine serves autoregressive generation through a
        slot-recycled :class:`~.decode_batcher.DecodeBatcher` per replica
        behind the same ``submit()``/``predict()`` API: feeds become
        ``submit(prompt_ids, max_new_tokens=..., eos_id=...)`` and the
        future resolves to the generated ids. ``ladder`` bounds the slot
        table, ``seq_ladder`` the KV-cache capacity rungs.

        Decode fast paths (ISSUE 20): ``prefix_cache=True`` (or a kwargs
        dict / :class:`~.prefix_cache.PrefixCache`) shares ONE prefix-KV
        cache across every replica, with the engine's metrics carrying
        the hit/eviction/bytes counters. ``decode_prefill={"predictor":
        chunk_pred, "spec": chunk_spec, "ladder": ...}`` attaches the
        K-token chunk program (``transformer_lm_chunk``) — replica 0
        uses the predictor as-is, later replicas ``clone()`` it — and
        extends the build-time compile-cache verdict with the prefill
        ladder. ``decode_speculative={"draft": DraftLM, "k": ...}``
        turns on speculative decode (requires ``decode_prefill``); the
        draft proposer is SHARED across replicas, so multi-replica
        engines need a thread-safe draft predictor.

        Reliability knobs: ``max_replica_failures`` consecutive batch
        failures evict a replica and rebuild it from the parent
        (``None``/0 disables); ``cross_replica_retry`` re-enqueues a
        failed batch's requests once before failing their futures;
        ``shed_on_overload`` lets a full queue shed its least-urgent
        (latest-deadline) entry for a more urgent arrival;
        ``supervisor_interval_s`` is the dead-worker-thread sweep cadence
        (``None`` disables the supervisor)."""
        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        if placement not in ("single", "per_device"):
            raise ValueError("placement must be 'single' or 'per_device', "
                             "got %r" % (placement,))
        faults.maybe_install_from_env()
        model_dir = model.model_dir if isinstance(model, AnalysisConfig) \
            else (model if isinstance(model, str) else None)
        if isinstance(model, (str, AnalysisConfig)):
            model = Predictor(model)
        if not callable(getattr(model, "clone", None)):
            raise TypeError("model must be a dir/AnalysisConfig or a "
                            "predictor with clone(); got %r" % (model,))
        self.ladder = tuple(sorted(set(
            ladder if ladder is not None else pow2_ladder(max_batch_size))))
        # normalized exactly the way DecodeBatcher normalizes it, so the
        # build-time compile-cache verdict and the batcher's actual
        # ladder can never disagree
        self.seq_ladder = tuple(sorted(set(
            int(c) for c in seq_ladder))) if seq_ladder else None
        self.max_batch_size = max(self.ladder)
        self.feed_names = list(getattr(model, "feed_names", []))
        self.default_timeout_s = default_timeout_s
        self.placement = placement
        self.mp = int(mp)

        self._batcher = DynamicBatcher(self.max_batch_size,
                                       max_wait_ms=max_wait_ms, clock=clock)
        self._admission = AdmissionController(max_queue_depth)
        # serializes the shutdown/_closed transition against supervisor
        # respawns (see _maybe_respawn) — created before the decode-mode
        # early return so both construction paths have it
        self._lifecycle_lock = threading.Lock()
        self.metrics_ = ServingMetrics(latency_window=latency_window)
        self.metrics_.bind_gauges(self._batcher.depth,
                                  lambda: self._admission.in_flight)

        parents = self._build_parents(model, placement, self.mp, devices,
                                      num_replicas)
        self._parent = parents[0]
        # model-swap plane (streaming hot-reload): the checkpoint version
        # currently served + how many live swaps happened; reload() bumps
        # them after the per-replica scope flip
        self.serve_version = None
        self.swap_count = 0
        self._staged_swap = None  # (version, updates) held between
        # prepare and commit of a two-phase fleet swap
        self.max_replica_failures = max_replica_failures or 0
        self.cross_replica_retry = bool(cross_replica_retry)
        self.shed_on_overload = bool(shed_on_overload)

        # decode mode: continuous batching replaces the one-shot pipeline
        decode_spec = self._resolve_decode_spec(decode, model_dir)
        self._decoders = None
        if decode_spec is not None:
            from .decode_batcher import DecodeBatcher
            from .prefix_cache import PrefixCache

            # build-time resource verification (ISSUE 15): prove the
            # compile-cache bound from the decode spec — dead ctx rungs
            # and an over-budget ladder product are construction-time
            # warnings, not a production surprise at warmup
            self._verify_decode_build(decode_spec, decode_prefill)
            # one prefix cache for the whole fleet: any replica's
            # harvest serves any replica's admission
            self._prefix_cache = None
            # NOT a truthiness test: an empty PrefixCache is len()==0
            if prefix_cache is not None and prefix_cache is not False:
                if isinstance(prefix_cache, PrefixCache):
                    self._prefix_cache = prefix_cache
                else:
                    kw = (dict(prefix_cache)
                          if isinstance(prefix_cache, dict) else {})
                    kw.setdefault("metrics", self.metrics_)
                    self._prefix_cache = PrefixCache(**kw)
                pc = self._prefix_cache
                self.metrics_.bind_prefix_bytes(lambda: pc.nbytes)
            self._decoders = []
            for i in range(num_replicas):
                parent = parents[i % len(parents)]
                pred = parent if i < len(parents) else parent.clone()
                prefill = None
                if decode_prefill is not None:
                    prefill = dict(decode_prefill)
                    if i > 0:
                        prefill["predictor"] = \
                            decode_prefill["predictor"].clone()
                self._decoders.append(DecodeBatcher(
                    pred, decode_spec, ladder=self.ladder,
                    ctx_ladder=self.seq_ladder,
                    max_queue_depth=max_queue_depth,
                    default_timeout_s=default_timeout_s,
                    default_max_new_tokens=default_max_new_tokens,
                    eos_id=eos_id, clock=clock, metrics=self.metrics_,
                    prefix_cache=self._prefix_cache, prefill=prefill,
                    speculative=decode_speculative))
            # aggregate gauges over every replica's queue (each batcher
            # got the shared metrics and deliberately did NOT bind its
            # own — a per-replica bind would report only the last one)
            decoders = self._decoders
            self.metrics_.bind_gauges(
                lambda: sum(len(d._pending) for d in decoders),
                lambda: sum(d._admission.in_flight for d in decoders))
            self._workers = []
            self._closed = False
            self._shutdown_done = False
            self._stop_event = threading.Event()
            self._supervisor = None
            return

        def breaker():
            return CircuitBreaker(
                failure_threshold=max(1, self.max_replica_failures or 1),
                reset_timeout_s=0.0, clock=self._batcher.now)

        self._workers = []
        for i in range(num_replicas):
            parent = parents[i % len(parents)]
            pred = parent if i < len(parents) else parent.clone()
            self._workers.append(_Worker(pred, i, breaker()))
        self._closed = False
        self._shutdown_done = False
        self._stop_event = threading.Event()
        for w in self._workers:
            self._spawn_worker_thread(w)
        self._supervisor = None
        if supervisor_interval_s:
            self._supervisor = threading.Thread(
                target=self._supervisor_loop, args=(supervisor_interval_s,),
                name="paddle-tpu-serve-supervisor", daemon=True)
            self._supervisor.start()

    def _verify_decode_build(self, decode_spec, decode_prefill=None):
        """Static compile-cache verdict for the decode tier
        (``analysis.resources.decode_cache_verdict``): the scheduler's
        executable count is bounded by len(ladder) x len(valid ctx
        rungs) — times (1 + len(prefill rungs)) when a chunk program
        rides along — proved from the spec's cache capacity, checked
        against the budget at CONSTRUCTION. Findings surface as warnings
        and the result is kept on ``self.build_verification``; the
        proved bound on ``self.compile_cache_bound``."""
        from ..analysis.resources import decode_cache_verdict
        from .decode_batcher import (default_ctx_ladder,
                                     default_prefill_ladder)

        ctx_ladder = self.seq_ladder
        if ctx_ladder is None:
            ctx_ladder = default_ctx_ladder(decode_spec)
        prefill_ladder = None
        if decode_prefill is not None:
            prefill_ladder = decode_prefill.get("ladder")
            if prefill_ladder is None:
                prefill_ladder = default_prefill_ladder(decode_spec)
        bound, result = decode_cache_verdict(decode_spec, self.ladder,
                                             ctx_ladder,
                                             prefill_ladder=prefill_ladder)
        self.compile_cache_bound = bound
        self.build_verification = result
        for d in result.diagnostics:
            warnings.warn("serving build verification: %s" % d,
                          RuntimeWarning, stacklevel=3)

    # -- placement ----------------------------------------------------------
    @staticmethod
    def _resolve_decode_spec(decode, model_dir):
        if decode is None or decode is False:
            return None
        if isinstance(decode, dict):
            return decode
        if decode is True:
            if model_dir is None:
                raise ValueError("decode=True needs a model DIRECTORY "
                                 "carrying decode_spec.json; pass the "
                                 "spec dict for in-process predictors")
            from .decode_batcher import load_decode_spec

            return load_decode_spec(model_dir)
        raise TypeError("decode must be None, True, or a decode-spec "
                        "dict; got %r" % (decode,))

    def _build_parents(self, model, placement, mp, devices, num_replicas):
        """The replica-parent predictors placement produces: one
        weight-holder per device (per_device), per device GROUP (mp>1 +
        per_device), one mesh-sharded parent (mp>1), or just ``model``.
        Never more parents than replicas — an unused parent is an unused
        HBM-resident weight copy. mp parents are sharding-asserted
        before any replica clones."""
        if placement == "single" and mp <= 1:
            return [model]
        import jax

        devices = list(devices) if devices is not None else jax.devices()
        if mp > 1:
            import numpy as np
            from jax.sharding import Mesh

            if len(devices) < mp:
                raise ValueError(
                    "mp=%d needs %d devices, found %d"
                    % (mp, mp, len(devices)))
            if not callable(getattr(model, "shard", None)):
                raise TypeError(
                    "mp>1 needs a program-path predictor with .shard() "
                    "(Predictor/ProgramPredictor); got %r" % (model,))
            n_groups = (len(devices) // mp if placement == "per_device"
                        else 1)
            n_groups = max(1, min(n_groups, num_replicas))
            parents = []
            for g in range(n_groups):
                mesh = Mesh(np.array(devices[g * mp:(g + 1) * mp]),
                            ("mp",))
                parent = model.shard(mesh)
                self._assert_mp_sharded(parent, mesh)
                parents.append(parent)
            return parents
        # per_device, mp=1: one pinned weight copy per device in use
        return [model.clone(device=d)
                for d in devices[:max(1, min(len(devices), num_replicas))]]

    def _assert_mp_sharded(self, parent, mesh):
        """Build-time HLO assertion (``parallel/sharding_check``): every
        mp-annotated >=2-D parameter must enter the compiled step
        actually sharded, and no all-gather may reassemble one — the
        failure mode where GSPMD silently replicates a 'sharded' model
        and mp=k buys k chips of nothing."""
        from ..parallel import sharding_check

        prog = getattr(parent, "_program", None)
        if prog is None:
            return
        mesh_axes = set(mesh.axis_names)
        annotated = []
        for v in prog.list_vars():
            spec = getattr(v, "sharding", None)
            if not v.persistable or spec is None:
                continue
            if not any(a in mesh_axes for a in spec if a is not None):
                continue
            if v.shape is None or len(v.shape) < 2 or \
                    any(d is None or d < 0 for d in v.shape):
                continue
            annotated.append(v)
        if not annotated:
            warnings.warn(
                "mp=%d serving: the program carries no mp-annotated "
                "parameters — every weight will be fully replicated "
                "and tensor parallelism buys nothing"
                % mesh.devices.size, RuntimeWarning, stacklevel=3)
            return
        feed = self._synthesize_example_for(parent)
        padded, _ = pad_to_bucket(feed, (min(self.ladder),),
                                  seq_ladder=(min(self.seq_ladder),)
                                  if self.seq_ladder else None)
        parent.run(padded)
        hlo = parent._exe.lowered_hlo_text()
        for v in annotated:
            sharding_check.assert_param_sharded(hlo, v.name,
                                                logical_shape=v.shape)
        sharding_check.assert_no_param_allgather(
            hlo, [tuple(v.shape) for v in annotated])

    # -- client surface -----------------------------------------------------
    def submit(self, feed, timeout_s=None, max_new_tokens=None,
               eos_id=None):
        """Enqueue one request; returns a ``concurrent.futures.Future``.

        One-shot mode: ``feed`` is the usual dict/list of arrays and the
        future resolves to the fetch list (sliced to this request's
        rows). Decode mode (``decode=`` at construction): ``feed`` is the
        prompt — a 1-D int array/list, or a dict with a single
        ``prompt_ids`` entry — and the future resolves to the generated
        token ids; the request is continuously batched per STEP, so it
        shares every decode step with whatever else is in flight and
        retires the moment it finishes.

        Raises :class:`ServerOverloadedError` immediately when the bounded
        queue is full, ``BucketError`` when the request's batch exceeds the
        top rung, ``RuntimeError`` after shutdown."""
        if self._closed:
            raise RuntimeError("ServingEngine is shut down")
        if self._decoders is not None:
            if isinstance(feed, dict):
                if set(feed) != {"prompt_ids"}:
                    raise ValueError(
                        "decode-mode submit takes a prompt (1-D ids) or "
                        "{'prompt_ids': ids}; got keys %s" % sorted(feed))
                feed = feed["prompt_ids"]
            # least-loaded replica: pending + occupied slots
            dec = min(self._decoders,
                      key=lambda d: d._admission.in_flight)
            return dec.submit(feed, max_new_tokens=max_new_tokens,
                              eos_id=eos_id, timeout_s=timeout_s)
        if isinstance(feed, (list, tuple)):
            if len(feed) != len(self.feed_names):
                raise ValueError("expected %d inputs (%s), got %d"
                                 % (len(self.feed_names), self.feed_names,
                                    len(feed)))
            feed = dict(zip(self.feed_names, feed))
        feed = {k: np.asarray(v) for k, v in feed.items()}
        if self.feed_names:
            missing = set(self.feed_names) - set(feed)
            if missing:
                raise ValueError("missing feeds: %s" % sorted(missing))
        sizes = {k: a.shape[0] for k, a in feed.items() if a.ndim}
        if not sizes:
            raise ValueError("feeds need a leading batch dim to serve")
        if len(set(sizes.values())) > 1:
            raise ValueError("feeds disagree on batch size: %s" % sizes)
        n = next(iter(sizes.values()))
        bucket_for(n, self.ladder)  # validates n fits the ladder
        if self.seq_ladder:
            for a in feed.values():
                if a.ndim >= 2:
                    # reject an over-long sequence at the door, not inside
                    # a batch where it would fail innocent co-riders
                    bucket_for(a.shape[1], self.seq_ladder)
        timeout_s = (timeout_s if timeout_s is not None
                     else self.default_timeout_s)
        now = self._batcher.now()
        deadline = now + timeout_s if timeout_s is not None else None
        while True:
            try:
                self._admission.acquire(n)
                break
            except ServerOverloadedError:
                # EDF degradation: a full queue sheds its least-urgent
                # (latest-deadline) entry for a strictly more urgent
                # arrival; deadline-less work is the first to go and can
                # displace nothing itself. The batcher checks feasibility
                # atomically — enough strictly-later-deadline examples
                # must be queued to cover the whole shortfall (shedding
                # cannot reach capacity held by in-flight batches or
                # more-urgent work), so no victim dies for an arrival
                # that gets rejected anyway
                short = self._admission.shortfall(n)
                if short == 0:
                    continue  # racing release freed the capacity: retry
                victim = (self._batcher.shed_for(deadline, short)
                          if self.shed_on_overload else None)
                if victim is None:
                    self.metrics_.observe_rejected()
                    raise
                self._fail(victim, ServerOverloadedError(
                    "shed under overload: the slot went to a request "
                    "with an earlier deadline"))
                self.metrics_.observe_shed()
                flight.record("edf.shed", where="engine", n=victim.n)
        req = Request(feed, n, Future(), now, deadline=deadline)
        req.trace_ctx = trace.current()
        try:
            self._batcher.put(req)
        except RuntimeError:
            self._admission.release(n)
            raise RuntimeError("ServingEngine is shut down")
        return req.future

    def predict(self, feed, timeout_s=None, **decode_kw):
        """Synchronous convenience: submit + wait."""
        return self.submit(feed, timeout_s=timeout_s,
                           **decode_kw).result(timeout_s)

    def warmup(self, example_feed=None):
        """Pre-compile every (batch rung x seq rung) bucket on every
        replica, so the first real request at any bucket hits a warm
        executable. ``example_feed`` is one representative example
        (leading dim 1) — with a ``seq_ladder``, give it the SHORTEST
        sequence you expect, since padding can only lengthen it: seq
        rungs below the example's length can't be warmed from it and are
        skipped. Returns the number of (replica, bucket) compilations
        actually warmed."""
        from .buckets import BucketError

        if self._decoders is not None:
            return sum(d.warmup() for d in self._decoders)
        feed = example_feed
        if feed is None:
            feed = self._synthesize_example()
        feed = {k: np.asarray(v) for k, v in feed.items()}
        warmed = 0
        seq_rungs = self.seq_ladder or (None,)
        for w in self._workers:
            for rung in self.ladder:
                for s in seq_rungs:
                    try:
                        padded, _ = pad_to_bucket(
                            feed, (rung,),
                            seq_ladder=None if s is None else (s,))
                    except BucketError:
                        continue  # example longer than this seq rung
                    w.predictor.run(padded)
                    w.seen_signatures.add(self._signature(padded))
                    warmed += 1
        return warmed

    def metrics(self):
        return self.metrics_.snapshot()

    def metrics_report(self):
        return self.metrics_.report()

    def compiled_shape_counts(self):
        """Distinct dispatched feed signatures per replica — the bound the
        ladder guarantees (<= len(ladder), or len(ladder)*len(seq_ladder)
        with sequence bucketing). For program-path replicas this mirrors
        the Executor's real compile-cache size."""
        if self._decoders is not None:
            return [c for d in self._decoders
                    for c in d.compiled_shape_counts()]
        return [len(w.seen_signatures) for w in self._workers]

    def shutdown(self, drain=True, timeout_s=None):
        """Stop intake; with ``drain`` serve everything queued, otherwise
        cancel it. Joins the worker threads (warning on any that outlive
        ``timeout_s``); requests still queued after the join — a dead or
        stuck replica raced a full batcher — fail with
        :class:`EngineShutdownError` and return their admission slots
        rather than leaking callers' futures. Idempotent."""
        # _closed flips under _lifecycle_lock: once we hold it, no
        # in-progress _maybe_respawn can still spawn a thread, and none
        # started after this point will — every worker thread the join
        # sweep below must reap already exists
        with self._lifecycle_lock:
            self._closed = True
            if self._shutdown_done:
                return
            self._shutdown_done = True
        self._stop_event.set()
        if self._decoders is not None:
            for d in self._decoders:
                d.shutdown(drain=drain, timeout_s=timeout_s)
            return
        if self._supervisor is not None:
            self._supervisor.join(timeout_s if timeout_s is not None
                                  else 5.0)
        if not drain:
            for r in self._batcher.drain():
                if r.future.cancel():
                    self.metrics_.observe_expired()
                else:
                    self.metrics_.observe_failed()
                self._admission.release(r.n)
        self._batcher.close()
        for w in self._workers:
            if w.thread is not None:
                w.thread.join(timeout_s)
                if w.thread.is_alive():
                    warnings.warn(
                        "ServingEngine.shutdown: replica %d (%s) still "
                        "busy after %.1fs join timeout; its in-flight "
                        "batch is abandoned to the daemon thread"
                        % (w.index, w.thread.name, timeout_s or 0.0),
                        RuntimeWarning, stacklevel=2)
        # drain=True normally empties the queue through the workers; if
        # one died/stuck with requests still queued, fail them loudly and
        # give their admission capacity back
        for r in self._batcher.drain():
            self._fail(r, EngineShutdownError(
                "ServingEngine shut down before this request was served"))
            self.metrics_.observe_failed()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    # -- worker side --------------------------------------------------------
    @staticmethod
    def _signature(feed):
        return tuple(sorted((k, a.shape, str(a.dtype))
                            for k, a in feed.items()))

    def _synthesize_example(self):
        return self._synthesize_example_for(self._workers[0].predictor)

    def _synthesize_example_for(self, predictor):
        """Build a 1-example feed from the program's var metadata (program
        path only — the StableHLO manifest doesn't carry shapes)."""
        prog = getattr(predictor, "_program", None)
        if prog is None or not self.feed_names:
            raise ValueError("warmup() needs example_feed for this "
                             "predictor type")
        feed = {}
        for name in self.feed_names:
            var = prog.global_block().var(name)
            shape = [1 if (d is None or d < 0) else int(d)
                     for d in (var.shape or (1,))]
            shape[0] = 1
            dtype = np.dtype(var.dtype or "float32")
            if dtype.kind in "iu":
                feed[name] = np.zeros(shape, dtype=dtype)
            else:
                feed[name] = np.full(shape, 0.5, dtype=dtype)
        return feed

    def _spawn_worker_thread(self, worker):
        worker.thread = threading.Thread(
            target=self._worker_loop, args=(worker,),
            name="paddle-tpu-serve-%d" % worker.index, daemon=True)
        worker.thread.start()

    def _maybe_respawn(self, w):
        """Respawn ``w``'s dead thread — unless shutdown has begun. The
        ``_closed`` check and the spawn are one atomic step under
        ``_lifecycle_lock``: without it the supervisor could pass the
        check, lose the CPU to ``shutdown()``'s join sweep, then spawn a
        thread nobody will ever join — parked forever on a closed
        batcher. Returns True iff a thread was actually spawned."""
        with self._lifecycle_lock:
            if self._closed:
                return False
            if w.thread is not None and not w.thread.is_alive():
                self._spawn_worker_thread(w)
                self.metrics_.observe_respawned()
                flight.record("thread.respawn", where="engine", replica=w.index)
                return True
        return False

    def _supervisor_loop(self, interval_s):
        """Self-healing sweep: a worker thread that died outright (an
        escape below the batch-level containment) is respawned; its
        replica state (predictor, breaker) carries over — the breaker
        still evicts if the predictor itself is the problem."""
        while not self._stop_event.wait(interval_s):
            if self._closed:
                return
            for w in self._workers:
                if self._closed:
                    return
                self._maybe_respawn(w)

    def _worker_loop(self, worker):
        while True:
            # deterministic thread-death drills land here, BEFORE a batch
            # is claimed: a killed worker never strands futures
            try:
                faults.trip("serving.worker")
            except faults.InjectedFault:
                return  # die quietly; the supervisor's sweep respawns us
            batch = self._batcher.get_batch()
            if batch is None:
                return
            try:
                self._serve_batch(worker, batch)
            except BaseException:
                # _serve_batch already failed the batch's futures; a throw
                # reaching here (e.g. from metrics accounting) must not
                # take the replica down with it
                pass

    def _rebuild_replica(self, worker):
        """Evict a repeatedly-failing replica and rebuild it from the
        parent predictor (weights shared; per-replica executor state —
        the likely contaminant — is fresh)."""
        try:
            fresh = self._parent.clone()
        except Exception as e:
            # keep the old predictor; the breaker re-arms so another
            # failure_threshold failures trigger the next rebuild attempt
            # (not counted as an eviction — nothing was rebuilt)
            warnings.warn(
                "replica %d eviction: rebuild clone() failed (%r); "
                "keeping the old predictor and re-arming the breaker"
                % (worker.index, e), RuntimeWarning)
        else:
            worker.predictor = fresh
            worker.seen_signatures = set()
            self.metrics_.observe_evicted()
            flight.record("replica.evict", where="engine", replica=worker.index)
        worker.breaker.reset()

    # -- live hot-swap (the streaming publish plane) -------------------------
    def reload(self, source, version=None):
        """Hot-swap model parameters into every live replica WITHOUT
        stopping serving — the streaming publish plane's engine verb.

        ``source`` is a checkpoint directory (``checkpoint.load_staged``
        stages the newest intact — or the given ``version`` — with CRC
        verification and fallback past corrupt versions) or an
        already-staged ``[(name, array), ...]`` update list.

        Swap mechanics: each distinct predictor scope is COPIED into a
        fresh scope with the updated parameters overlaid (placement
        preserved — pinned/sharded arrays are ``device_put`` with the old
        array's sharding), then a single reference assignment flips each
        replica to it. A replica's in-flight micro-batch already read the
        old scope reference and finishes on the old weights; its next
        batch reads the new one — no request is dropped, no lock is held
        across a predictor call. Compiled step caches stay warm (shapes
        and dtypes are unchanged).

        Returns the version served. Raises on a model/checkpoint mismatch
        (no staged name present in any replica scope); decode-mode
        engines do not support reload."""
        if self._decoders is not None:
            raise NotImplementedError(
                "reload() is not supported in decode mode: KV caches are "
                "conversation state entangled with the weights")
        if self._closed:
            raise RuntimeError("engine is shut down")
        if isinstance(source, str):
            from .. import checkpoint

            prog = getattr(self._parent, "_program", None)
            if prog is None:
                raise TypeError(
                    "reload from a checkpoint dir needs a program-backed "
                    "predictor; got %r" % (type(self._parent).__name__,))
            version, updates, _extra = checkpoint.load_staged(
                source, prog, version=version)
        else:
            updates = list(source)
        sp = trace.span("model.swap")
        with sp:
            if sp:
                sp.set(version=version, replicas=len(self._workers))
            applied = self._swap_scopes(updates)
        self.serve_version = version
        self.swap_count += 1
        flight.record("model.swap", version=version, applied=applied,
                      replicas=len(self._workers), swap=self.swap_count)
        return version

    def prepare(self, source, version=None):
        """Phase 1 of the two-phase fleet swap: CRC-stage a version
        WITHOUT touching the served weights. The staged update list is
        held until :meth:`commit` applies it or :meth:`abort_swap` drops
        it; re-preparing replaces the staged version. Serving continues
        on the old weights throughout — an aborted prepare leaves no
        trace. Fault site ``swap.prepare``: ``error``/``corrupt`` fail
        the stage (the fleet publisher must then abort everywhere).
        Returns the staged version."""
        mode = faults.trip("swap.prepare")
        if self._decoders is not None:
            raise NotImplementedError(
                "prepare() is not supported in decode mode: KV caches "
                "are conversation state entangled with the weights")
        if self._closed:
            raise RuntimeError("engine is shut down")
        if isinstance(source, str):
            from .. import checkpoint

            prog = getattr(self._parent, "_program", None)
            if prog is None:
                raise TypeError(
                    "prepare from a checkpoint dir needs a program-backed "
                    "predictor; got %r" % (type(self._parent).__name__,))
            version, updates, _extra = checkpoint.load_staged(
                source, prog, version=version)
        else:
            updates = list(source)
        if mode == "corrupt":
            raise IOError("swap.prepare: staged bytes corrupt (injected)")
        self._staged_swap = (version, updates)
        flight.record("swap.prepare", version=version,
                      staged=len(updates))
        return version

    def commit(self, version=None):
        """Phase 2: atomically swap the prepared version in. Idempotent
        under retry — committing a ``version`` that already serves
        returns success, so a fleet publisher's RetryPolicy can re-drive
        a commit whose ACK was lost. Raises when nothing (or a different
        version) is staged. Fault site ``swap.commit`` drills the
        partial-commit / quarantine path."""
        faults.trip("swap.commit")
        staged = self._staged_swap
        if staged is None:
            if version is not None and self.serve_version == version:
                return version  # lost-ACK retry of a landed commit
            raise RuntimeError(
                "commit(%r): no staged version (prepare first)"
                % (version,))
        sv, updates = staged
        if version is not None and sv != version:
            raise RuntimeError(
                "commit(%r): staged version is %r" % (version, sv))
        sp = trace.span("model.swap")
        with sp:
            if sp:
                sp.set(version=sv, replicas=len(self._workers))
            applied = self._swap_scopes(updates)
        self._staged_swap = None
        self.serve_version = sv
        self.swap_count += 1
        flight.record("swap.commit", version=sv, applied=applied,
                      replicas=len(self._workers), swap=self.swap_count)
        return sv

    def abort_swap(self):
        """Drop a staged-but-uncommitted version (any target failing
        prepare aborts the whole fleet — nothing swaps). Returns True
        when something was staged."""
        staged, self._staged_swap = self._staged_swap, None
        if staged is not None:
            flight.record("swap.abort", version=staged[0])
        return staged is not None

    def _swap_scopes(self, updates):
        """Copy-and-overlay every distinct predictor scope, then flip the
        references. Returns the number of parameter names applied."""
        import jax

        from ..core.executor import Scope

        def overlay(old_get, names):
            hits = {}
            for name, val in updates:
                if name.startswith("@") or name not in names:
                    continue  # RNG stream / optimizer-only state
                ref = old_get(name)
                if isinstance(ref, jax.Array):
                    val = jax.device_put(val, ref.sharding)
                hits[name] = val
            return hits

        with self._lifecycle_lock:  # no respawn/rebuild mid-swap
            preds = {id(self._parent): self._parent}
            for w in self._workers:
                preds.setdefault(id(w.predictor), w.predictor)
            staged = {}  # id(old scope/state) -> (new scope/state, hits)
            applied = 0
            for pred in preds.values():
                if hasattr(pred, "_scope"):
                    old = pred._scope
                    if id(old) not in staged:
                        names = set(old.var_names())
                        hits = overlay(old.get, names)
                        fresh = Scope()
                        for n in names:
                            fresh.set(n, hits.get(n, old.get(n)))
                        staged[id(old)] = (fresh, len(hits))
                elif hasattr(pred, "_state"):  # StableHLOPredictor
                    old = pred._state
                    if id(old) not in staged:
                        hits = overlay(old.__getitem__, set(old))
                        fresh = dict(old)
                        fresh.update(hits)
                        staged[id(old)] = (fresh, len(hits))
                else:
                    continue
            if not any(n for _, n in staged.values()):
                raise ValueError(
                    "reload: no staged parameter matches any replica "
                    "scope — wrong checkpoint for this model?")
            for pred in preds.values():
                if hasattr(pred, "_scope"):
                    fresh, n = staged[id(pred._scope)]
                    pred._scope = fresh  # atomic: in-flight runs hold old
                    applied = max(applied, n)
                elif hasattr(pred, "_state"):
                    fresh, n = staged[id(pred._state)]
                    pred._state = fresh
                    applied = max(applied, n)
        return applied

    def _serve_batch(self, worker, batch):
        now = self._batcher.now()
        live = []
        for r in batch:
            if r.future.cancelled():
                self._admission.release(r.n)
                continue
            if r.deadline is not None and now > r.deadline:
                self._fail(r, DeadlineExceededError(
                    "request waited %.1f ms, deadline was %.1f ms"
                    % ((now - r.enqueue_t) * 1e3,
                       (r.deadline - r.enqueue_t) * 1e3)))
                self.metrics_.observe_expired()
                continue
            live.append(r)
        if not live:
            return
        # phase 1 — batch assembly. Failures here (disagreeing scalar
        # feeds, bucket violations) are REQUEST-CONTENT errors: the
        # replica is healthy and a retry can only repeat them, so they
        # fail the batch without touching the breaker or the retry budget
        try:
            if len(live) == 1:
                merged = live[0].feed
            else:
                merged = {}
                for k in live[0].feed:
                    vals = [r.feed[k] for r in live]
                    if vals[0].ndim == 0:
                        # scalar feeds (temperature etc.) have no batch dim
                        # to concatenate on; they can share one XLA call
                        # only when every request agrees on the value
                        if any(not np.array_equal(v, vals[0])
                               for v in vals[1:]):
                            raise ValueError(
                                "scalar feed %r differs across batched "
                                "requests; scalars must be equal to "
                                "coalesce" % k)
                        merged[k] = vals[0]
                    else:
                        if self.seq_ladder and vals[0].ndim >= 2:
                            # different seq lengths in one micro-batch:
                            # pad each rider to the rung covering the
                            # longest before the rows can concatenate
                            tgt = bucket_for(
                                max(v.shape[1] for v in vals),
                                self.seq_ladder)
                            vals = [edge_pad(v, tgt, 1) for v in vals]
                        merged[k] = np.concatenate(vals, axis=0)
            padded, n = pad_to_bucket(merged, self.ladder,
                                      seq_ladder=self.seq_ladder)
            rung = bucket_for(n, self.ladder)
        except Exception as e:
            for r in live:
                self._fail(r, e)
            self.metrics_.observe_failed(len(live))
            return
        # phase 2 — dispatch. Failures here are REPLICA faults: they
        # count on the breaker (evict+rebuild on trip) and the batch's
        # requests get their one cross-replica retry
        # parent the batch span onto the first live request's trace so a
        # propagated router trace stitches through the queue hand-off; the
        # predictor.run below reaches Executor.run on this same thread, so
        # the executor span nests here by ambient context
        sp = trace.span("engine.batch",
                        parent=next((r.trace_ctx for r in live
                                     if r.trace_ctx is not None), None))
        try:
            sig = self._signature(padded)
            hit = sig in worker.seen_signatures
            worker.seen_signatures.add(sig)
            faults.trip("predictor.run")
            with sp:
                if sp:  # tags must land before the span closes
                    sp.set(n=n, rung=rung, requests=len(live),
                           replica=worker.index)
                outs = worker.predictor.run(padded)
            outs = unpad_fetch(outs, n, padded_to=rung)
        except Exception as e:
            # fail only this batch; the replica (and its clone-shared
            # weights) keeps serving — unless its breaker says the
            # replica itself is the pattern, in which case evict+rebuild
            if (self.max_replica_failures
                    and worker.breaker.record_failure()):
                self._rebuild_replica(worker)
            self._dispose_failed(live, e)
            return
        worker.breaker.record_success()
        self.metrics_.observe_batch(actual=n, bucket=rung, cache_hit=hit)
        done_t = self._batcher.now()
        off = 0
        for r in live:
            rows = [o[off:off + r.n]
                    if (getattr(o, "ndim", 0) >= 1 and o.shape[0] == n)
                    else o for o in outs]
            off += r.n
            try:
                r.future.set_result(rows)
            except Exception:
                pass  # racing cancel; capacity still returns below
            self.metrics_.observe_completed(done_t - r.enqueue_t)
            self._admission.release(r.n)

    def _dispose_failed(self, live, exc):
        """A batch's ``predictor.run`` threw: requests that still have a
        retry budget re-enqueue for another replica to pick up (inference
        is idempotent — ``donate_state=False`` keeps clones read-only, so
        a replay cannot double-apply anything); the rest fail with the
        batch's exception. Retried requests KEEP their admission slot —
        they never left the system."""
        retry = []
        for r in live:
            if (self.cross_replica_retry and r.retries < 1
                    and not self._closed):
                r.retries += 1
                retry.append(r)
            else:
                self._fail(r, exc)
                self.metrics_.observe_failed()
        for r in retry:
            try:
                self._batcher.put(r)
            except RuntimeError:  # racing shutdown: no second chance left
                self._fail(r, exc)
                self.metrics_.observe_failed()
                continue
            self.metrics_.observe_retried()

    def _fail(self, req, exc):
        try:
            req.future.set_exception(exc)
        except Exception:
            pass
        self._admission.release(req.n)

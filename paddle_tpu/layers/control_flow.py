"""Control-flow layers (ref ``python/paddle/fluid/layers/control_flow.py``:
While:504, StaticRNN:278, ConditionalBlock:1055, Switch:1138).

TPU-native lowering: sub-block bodies are recorded symbolically and executed
through ``lax.scan`` / ``lax.while_loop`` / ``lax.cond`` — compiler-friendly
control flow with static shapes, replacing the reference's interpreter
recursion into sub-BlockDescs.
"""

from ..core import framework
from ..core.layer_helper import LayerHelper

__all__ = ["StaticRNN", "DynamicRNN", "While", "Switch", "cond", "increment",
           "less_than", "equal", "array_write", "array_read",
           "create_array", "array_length", "IfElse"]


def less_than(x, y, force_cpu=None, cond=None):
    """``cond`` (if given) receives the result in place — required inside a
    While body so the loop condition var is actually updated (ref
    ``layers/control_flow.py`` less_than cond semantics)."""
    from .math_op_patch import binary
    return binary(x, y, "less_than", out=cond)


def equal(x, y, cond=None):
    from .math_op_patch import binary
    return binary(x, y, "equal", out=cond)


def increment(x, value=1.0, in_place=True):
    from . import tensor
    return tensor.increment(x, value, in_place)


class BlockGuard:
    def __init__(self, program):
        self.program = program

    def __enter__(self):
        self.block = self.program._create_block()
        return self.block

    def __exit__(self, *a):
        self.program._rollback()
        return False


class StaticRNN:
    """Static-length RNN (ref ``control_flow.py:278``): the step block is
    recorded into a sub-block and lowered to one ``lax.scan`` — each step is
    the fused step computation on the MXU.

    Usage parity with the reference:
        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)          # x: [B, T, D] (batch-major)
            h = rnn.memory(shape=[H], batch_ref=x)
            nh = some_layers(x_t, h)
            rnn.update_memory(h, nh)
            rnn.step_output(nh)
        out = rnn()                           # [B, T, H]
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._mems = []          # (pre_var, init_var)
        self._mem_updates = {}   # pre_var.name -> post var
        self._step_inputs = []   # (step_var, full_var)
        self._step_outputs = []
        self._block = None
        self._entered = False

    def step(self):
        outer = self

        class _Guard(BlockGuard):
            def __init__(self):
                super().__init__(framework.default_main_program())

            def __enter__(self):
                outer._block = super().__enter__()
                outer._entered = True
                return outer._block

            def __exit__(self, *a):
                outer._entered = False
                return super().__exit__(*a)

        return _Guard()

    def step_input(self, x):
        assert self._entered
        step_var = self._block.create_var(
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=str(x.dtype))
        self._step_inputs.append((step_var, x))
        return step_var

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=0):
        assert self._entered
        if init is None:
            from . import tensor
            assert batch_ref is not None
            # build init OUTSIDE the step block
            cur = framework.default_main_program().current_block()
            saved_idx = framework.default_main_program().current_block_idx
            framework.default_main_program().current_block_idx = 0
            init = tensor.fill_constant_batch_size_like(
                batch_ref, [1] + list(shape), str(batch_ref.dtype), init_value)
            framework.default_main_program().current_block_idx = saved_idx
        pre = self._block.create_var(shape=init.shape, dtype=str(init.dtype))
        self._mems.append((pre, init))
        return pre

    def update_memory(self, mem, var):
        self._mem_updates[mem.name] = var

    def step_output(self, o):
        self._step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self, *args, **kwargs):
        prog = framework.default_main_program()
        gb = prog.global_block()
        step_ops = list(self._block.ops)
        x_vars = [full for _, full in self._step_inputs]
        # scan is time-major; wrap with transposes
        from . import tensor as T
        xs_tm = [T.transpose(x, [1, 0] + list(range(2, len(x.shape))))
                 for x in x_vars]
        init_vars = [init for _, init in self._mems]
        carry_names = [pre.name for pre, _ in self._mems]
        carry_out_names = [self._mem_updates[n].name for n in carry_names]
        x_names = [sv.name for sv, _ in self._step_inputs]
        y_names = [o.name for o in self._step_outputs]

        lasts = [gb.create_var(shape=i.shape, dtype=str(i.dtype))
                 for i in init_vars]
        ys = [gb.create_var(shape=(x_vars[0].shape[1],) + tuple(o.shape),
                            dtype=str(o.dtype)) for o in self._step_outputs]
        gb.append_op(
            "scan_block",
            {"X": xs_tm, "Init": init_vars},
            {"Last": lasts, "Ys": ys},
            {"step_ops": step_ops, "x_step_names": x_names,
             "carry_names": carry_names, "carry_out_names": carry_out_names,
             "y_names": y_names})
        outs = [T.transpose(y, [1, 0] + list(range(2, len(y.shape))))
                for y in ys]
        return outs[0] if len(outs) == 1 else outs


class While:
    """While loop (ref ``control_flow.py:504``) lowered to lax.while_loop.
    Loop-carried vars must be listed via ``loop_vars``."""

    def __init__(self, cond, loop_vars=None, name=None):
        self.cond_var = cond
        self.loop_vars = loop_vars or []
        self.helper = LayerHelper("while", name=name)
        self._guard = None

    def block(self):
        outer = self
        prog = framework.default_main_program()

        class _Guard(BlockGuard):
            def __init__(self):
                super().__init__(prog)

            def __enter__(self):
                outer._block = super().__enter__()
                return outer._block

            def __exit__(self, *exc):
                r = super().__exit__(*exc)
                if exc and exc[0] is not None:
                    return r
                gb = prog.global_block()
                body_ops = list(outer._block.ops)
                outs = [gb.create_var(shape=v.shape, dtype=str(v.dtype))
                        for v in outer.loop_vars]
                gb.append_op(
                    "while_block",
                    {"Carry": list(outer.loop_vars)},
                    {"Out": outs},
                    {"body_ops": body_ops,
                     "cond_name": outer.cond_var.name})
                for v, o in zip(outer.loop_vars, outs):
                    # rebind names so later layers see updated values
                    o.name = v.name
                    gb.vars[v.name] = o
                return r

        return _Guard()


class Switch:
    """Piecewise-case construct (ref ``control_flow.py:1138``), commonly used
    for LR schedules. First-match semantics: each case is guarded by
    ``its_cond AND NOT(any prior cond)``; the default by ``NOT(any cond)``.
    Lowered to jnp.where blending in run_op (see op_registry)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._prior_conds = []

    def case(self, condition):
        return _SwitchCase(self, condition)

    def default(self):
        return _SwitchCase(self, None)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class _SwitchCase:
    def __init__(self, switch, condition):
        self.switch = switch
        self.condition = condition
        self.prog = framework.default_main_program()

    def __enter__(self):
        self.block = self.prog._create_block()
        return self.block

    def __exit__(self, *a):
        self.prog._rollback()
        ops = list(self.block.ops)
        gb = self.prog.global_block()

        # effective condition = this cond AND NOT(prior conds); default =
        # NOT(any prior cond). Built with ops so it traces into the jit.
        def _not(v):
            o = gb.create_var(shape=v.shape or (1,), dtype="bool")
            gb.append_op("logical_not", {"X": v}, {"Out": o}, {})
            return o

        def _and(a, b):
            o = gb.create_var(shape=a.shape or (1,), dtype="bool")
            gb.append_op("logical_and", {"X": a, "Y": b}, {"Out": o}, {})
            return o

        eff = self.condition
        for prior in self.switch._prior_conds:
            np_ = _not(prior)
            eff = np_ if eff is None else _and(eff, np_)
        if self.condition is not None:
            self.switch._prior_conds.append(self.condition)
        for op in ops:
            if eff is not None:
                op.attrs["_switch_cond"] = eff.name
            gb.ops.append(op)
            self.prog._version += 1
        return False


def _append_cond_block(pred, true_ops, t_outs, false_ops, f_outs):
    """Shared cond_block lowering used by ``cond`` and ``IfElse``."""
    gb = framework.default_main_program().global_block()
    outs = [gb.create_var(shape=v.shape, dtype=str(v.dtype)) for v in t_outs]
    gb.append_op(
        "cond_block", {"Cond": pred}, {"Out": outs},
        {"true_ops": list(true_ops), "false_ops": list(false_ops),
         "true_out_names": [v.name for v in t_outs],
         "false_out_names": [v.name for v in f_outs]})
    return outs


def cond(pred, true_fn, false_fn, name=None):
    """Functional conditional (modern jax-style; the reference's
    ``ConditionalBlock`` pattern is subsumed): both branches are traced
    symbolically and lowered to lax.cond."""
    prog = framework.default_main_program()
    tb = prog._create_block()
    true_out = true_fn()
    prog._rollback()
    fb = prog._create_block()
    false_out = false_fn()
    prog._rollback()
    t_outs = true_out if isinstance(true_out, (list, tuple)) else [true_out]
    f_outs = false_out if isinstance(false_out, (list, tuple)) else [false_out]
    outs = _append_cond_block(pred, tb.ops, t_outs, fb.ops, f_outs)
    return outs[0] if len(outs) == 1 else outs


def create_array(dtype, capacity=None):
    """TensorArray (ref ``layers/control_flow.py`` create_array /
    ``lod_tensor_array.h``). TPU-native arrays are fixed-capacity stacked
    buffers [capacity, ...] — static shapes for XLA; the buffer materializes
    (zero-filled) on the first ``array_write``."""
    gb = framework.default_main_program().current_block()
    arr = gb.create_var(shape=None, dtype=dtype)
    arr._tensor_array_capacity = capacity
    return arr


def array_write(x, i, array=None, capacity=None):
    """Write ``x`` at position ``i`` (ref tensor_array_write). Returns the
    array; inside a While body the write updates the loop carry in place,
    so list the array in ``loop_vars``."""
    if array is None:
        array = create_array(str(x.dtype), capacity)
    cap = capacity or getattr(array, "_tensor_array_capacity", None)
    if cap is None:
        raise ValueError(
            "array_write needs a static capacity: pass capacity= here or "
            "on create_array (TPU arrays are fixed-capacity buffers)")
    array._tensor_array_capacity = cap
    cb = framework.default_main_program().current_block()
    cb.append_op("array_write", {"X": x, "I": i}, {"Out": array},
                 {"capacity": int(cap)})
    return array


def array_read(array, i):
    cb = framework.default_main_program().current_block()
    out = cb.create_var(shape=None, dtype=str(array.dtype))
    cb.append_op("array_read", {"Array": array, "I": i}, {"Out": out}, {})
    return out


def array_length(array):
    cb = framework.default_main_program().current_block()
    out = cb.create_var(shape=(), dtype="int64")
    cb.append_op("array_length", {"Array": array}, {"Out": out}, {})
    return out


class IfElse:
    """Ref ``layers/control_flow.py`` IfElse: two-branch construct over a
    boolean condition. Thin sugar over ``cond`` — both branches trace to
    lax.cond; ``input(x)`` returns x unchanged (no LoD split on TPU; the
    predicate is a scalar)."""

    def __init__(self, cond_var, name=None):
        self._cond = cond_var
        self._branches = {True: None, False: None}
        self._outputs = {True: None, False: None}
        self._in_true = None

    class _Branch:
        def __init__(self, owner, is_true):
            self.owner = owner
            self.is_true = is_true

        def __enter__(self):
            self.owner._in_true = self.is_true
            prog = framework.default_main_program()
            self.block = prog._create_block()
            self.owner._branches[self.is_true] = self.block
            return self.block

        def __exit__(self, *a):
            framework.default_main_program()._rollback()
            self.owner._in_true = None
            return False

    def true_block(self):
        return IfElse._Branch(self, True)

    def false_block(self):
        return IfElse._Branch(self, False)

    def input(self, x):
        return x

    def output(self, *outs):
        self._outputs[self._in_true] = list(outs)

    def __call__(self):
        t_outs = self._outputs[True]
        f_outs = self._outputs[False]
        assert t_outs and f_outs and len(t_outs) == len(f_outs), \
            "both branches must call output() with the same arity"
        return _append_cond_block(self._cond, self._branches[True].ops,
                                  t_outs, self._branches[False].ops, f_outs)


class DynamicRNN(StaticRNN):
    """Variable-length RNN (ref ``control_flow.py`` DynamicRNN, which walks
    LoD sequences shrinking the live batch each step).

    Padded-batch redesign: same step-block recording as StaticRNN, but the
    caller passes per-row ``lengths`` at call time; memory updates FREEZE
    once a row's length is exhausted (so final memories equal the state at
    each row's last valid step, matching the reference's semantics of
    shorter sequences retiring early) and step outputs beyond a row's
    length are zeroed.

        drnn = DynamicRNN()
        with drnn.step():                     # block() also accepted
            x_t = drnn.step_input(x)          # x: [B, T, D]
            h = drnn.memory(shape=[H], batch_ref=x)
            nh = some_layers(x_t, h)
            drnn.update_memory(h, nh)
            drnn.step_output(nh)
        out = drnn(lengths=seq_len)           # [B, T, H], zero-padded
    """

    def block(self):
        return self.step()

    def __call__(self, lengths=None, **kwargs):
        if lengths is None:
            return super().__call__(**kwargs)
        from . import nn, tensor

        prog = framework.default_main_program()
        x_full = self._step_inputs[0][1]  # [B, T, ...]
        seq_len = x_full.shape[1]

        # [B, T] time indices as an extra scanned input (the step mask
        # needs its own t), built in the OUTER block
        t_row = tensor.unsqueeze(
            tensor.range(0, seq_len, 1, "float32"), [0])   # [1, T]
        zero_b = tensor.fill_constant_batch_size_like(
            x_full, [1, 1], "float32", 0.0)                # [B, 1]
        t_full = nn.elementwise_add(zero_b, t_row)         # [B, T]
        len_f = tensor.cast(lengths, "float32")            # [B]

        # inject masking ops INTO the recorded step block
        saved_idx = prog.current_block_idx
        prog.current_block_idx = self._block.idx
        self._entered = True
        try:
            t_step = self.step_input(t_full)               # [B] per step
            alive = tensor.cast(
                less_than(t_step, len_f), "float32")       # [B]
            for pre, _ in list(self._mems):
                post = self._mem_updates[pre.name]
                m = alive
                for _ in range(len(post.shape) - 1):
                    m = tensor.unsqueeze(m, [-1])
                frozen = nn.elementwise_add(
                    nn.elementwise_mul(post, m),
                    nn.elementwise_mul(
                        pre, nn.scale(m, scale=-1.0, bias=1.0)))
                self._mem_updates[pre.name] = frozen
            masked_outs = []
            for o in self._step_outputs:
                m = alive
                for _ in range(len(o.shape) - 1):
                    m = tensor.unsqueeze(m, [-1])
                masked_outs.append(nn.elementwise_mul(o, m))
            self._step_outputs = masked_outs
        finally:
            self._entered = False
            prog.current_block_idx = saved_idx
        return super().__call__(**kwargs)

"""Debug-surface ops: Print and py_func.

Reference: ``paddle/fluid/operators/print_op.cc`` (+ the Python wrapper
``layers/control_flow.py:146``) and ``operators/py_func_op.cc``
(``layers/nn.py:10346``). TPU-native: Print rides an ordered host
callback inside the jitted step (jax.debug/io_callback — the XLA analog
of the reference's CPU-side TensorPrint), py_func rides
``jax.pure_callback`` with an optional ``backward_func`` realized as a
``custom_vjp`` whose cotangent is computed by a second host callback —
the same (x, out, dout) -> dx contract as the reference grad kernel.
"""

import numpy as np

import jax
from jax.experimental import io_callback

from ..core.layer_helper import LayerHelper
from ..core.op_registry import register, get, put, REPLAY_KEY

__all__ = ["Print", "py_func"]


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Print the tensor (and/or its gradient) whenever it flows. Returns
    the wrapped tensor (identity in the compute graph)."""
    helper = LayerHelper("print", name=None)
    out = helper.create_variable_for_type_inference(
        dtype=str(input.dtype), shape=input.shape)
    helper.append_op(
        "print", {"In": input}, {"Out": out},
        {"first_n": first_n, "message": message or "",
         "summarize": summarize, "print_tensor_name": print_tensor_name,
         "print_tensor_type": print_tensor_type,
         "print_tensor_shape": print_tensor_shape,
         "print_phase": print_phase, "var_name": input.name})
    return out


def _tensor_report(tag, name, arr, attrs):
    parts = [attrs.get("message") or ""]
    if attrs.get("print_tensor_name", True):
        parts.append("%s %s" % (tag, name))
    if attrs.get("print_tensor_type", True):
        parts.append("dtype: %s" % arr.dtype)
    if attrs.get("print_tensor_shape", True):
        parts.append("shape: %s" % (tuple(arr.shape),))
    n = attrs.get("summarize", -1)
    flat = np.asarray(arr).reshape(-1)
    if n is not None and n >= 0:
        flat = flat[:n]
    parts.append("data: %s" % np.array2string(flat, threshold=20))
    print("  ".join(p for p in parts if p))


@register("print")
def _print_op(env, op):
    x = get(env, op.input("In"))
    attrs = op.attrs
    name = attrs.get("var_name", "?")
    first_n = attrs.get("first_n", -1)
    phase = attrs.get("print_phase", "both")
    counter = attrs.setdefault("_host_counter", [0])
    # the autodiff replays forward ops (control_ops loss_fn): forward
    # prints are suppressed there — the outer pass prints fwd, the replay
    # (where gradients actually flow) prints bwd
    in_replay = bool(env.get(REPLAY_KEY))

    def host_print(tag, arr):
        counter[0] += 1
        if first_n is None or first_n < 0 or counter[0] <= first_n:
            _tensor_report(tag, name, arr, attrs)

    def fwd_print(arr):
        io_callback(lambda a: host_print("fwd", a), None, arr,
                    ordered=True)
        return arr

    want_fwd = phase in ("forward", "both") and not in_replay
    want_bwd = phase in ("backward", "both") and in_replay

    if want_bwd:
        @jax.custom_vjp
        def ident(a):
            return a

        def ident_fwd(a):
            return a, None

        def ident_bwd(_, g):
            io_callback(lambda a: host_print("bwd-grad", a), None, g,
                        ordered=True)
            return (g,)

        ident.defvjp(ident_fwd, ident_bwd)
        put(env, op.output("Out"), ident(x))
    elif want_fwd:
        put(env, op.output("Out"), fwd_print(x))
    else:
        put(env, op.output("Out"), x)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """Run a user Python function as an op (ref ``py_func_op.cc``).

    ``x``: input Variable or list; ``out``: pre-created output Variable
    or list (shapes/dtypes declare the callback's result signature);
    ``backward_func(*xs, *outs, *douts) -> dxs`` supplies gradients.
    """
    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    helper = LayerHelper("py_func", name=None)
    helper.append_op(
        "py_func", {"X": xs}, {"Out": outs},
        {"func": func, "backward_func": backward_func})
    return out


@register("py_func")
def _py_func_op(env, op):
    xs = [get(env, v) for v in op.input_list("X")]
    out_vars = op.output_list("Out")
    func = op.attr("func")
    backward_func = op.attr("backward_func")

    def out_specs(batch):
        specs = []
        for v in out_vars:
            shape = tuple(batch if s == -1 else s for s in v.shape)
            specs.append(jax.ShapeDtypeStruct(shape, v.dtype))
        return tuple(specs)

    batch = xs[0].shape[0] if xs and xs[0].ndim else 1
    specs = out_specs(batch)

    def call_fwd(*args):
        res = func(*[np.asarray(a) for a in args])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r, dtype=s.dtype).reshape(s.shape)
                     for r, s in zip(res, specs))

    if backward_func is None:
        outs = jax.pure_callback(call_fwd, specs, *xs)
    else:
        @jax.custom_vjp
        def pf(*xs_):
            return jax.pure_callback(call_fwd, specs, *xs_)

        def pf_fwd(*xs_):
            outs_ = jax.pure_callback(call_fwd, specs, *xs_)
            return outs_, (xs_, outs_)

        def pf_bwd(res, gs):
            xs_, outs_ = res
            x_specs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                            for a in xs_)

            def call_bwd(*args):
                r = backward_func(*[np.asarray(a) for a in args])
                r = r if isinstance(r, (list, tuple)) else [r]
                return tuple(np.asarray(v, dtype=s.dtype).reshape(s.shape)
                             for v, s in zip(r, x_specs))

            return jax.pure_callback(call_bwd, x_specs,
                                     *(xs_ + outs_ + tuple(gs)))

        pf.defvjp(pf_fwd, pf_bwd)
        outs = pf(*xs)

    for v, o in zip(out_vars, outs):
        put(env, v, o)

"""Neural layers (ref ``python/paddle/fluid/layers/nn.py`` — 153 layers).

Every layer appends symbolic ops; shapes use -1 for the batch dim. The op
impls (``core/opimpl``) lower to jnp/lax, so a stack of these layers traces
into one fused XLA computation. Docstring citations point at the reference
layer definitions for parity checking.
"""

import numpy as np

from ..core.layer_helper import LayerHelper
from ..core.initializer import (ConstantInitializer, NormalInitializer,
                                UniformInitializer, XavierInitializer)
from ..core.param_attr import ParamAttr

__all__ = [
    "fc", "embedding", "conv2d", "conv3d", "conv2d_transpose",
    "depthwise_conv2d", "pool2d", "adaptive_pool2d", "batch_norm",
    "layer_norm", "group_norm", "dropout", "softmax", "log_softmax",
    "cross_entropy", "softmax_with_cross_entropy",
    "smooth_softmax_with_cross_entropy", "fused_linear_smooth_ce",
    "sigmoid_cross_entropy_with_logits", "square_error_cost", "smooth_l1",
    "huber_loss", "label_smooth", "kldiv_loss", "bpr_loss", "hinge_loss",
    "log_loss", "margin_rank_loss", "mse_loss",
    "mean", "mul", "matmul", "scale", "clip", "clip_by_norm",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod",
    "topk", "argmax", "argmin", "argsort", "l2_normalize",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "elementwise_mod",
    "relu", "prelu", "maxout", "swish", "gelu", "brelu", "leaky_relu",
    "elu", "relu6", "pow", "stanh", "hard_sigmoid", "lrn",
    "one_hot", "lod_reset", "pad", "pad2d", "image_resize", "resize_bilinear",
    "resize_nearest", "grid_sampler", "pixel_shuffle", "im2sequence",
    "multi_head_attention", "scaled_dot_product_attention",
    "cached_multi_head_attention", "kv_cache_write",
    "cached_multi_head_attention_chunk", "kv_cache_write_chunk",
    "row_conv", "autoincreased_step_counter", "cos_sim",
    "split", "warpctc", "nce", "hsigmoid", "cumsum",
    "linear_chain_crf", "crf_decoding",
    "dynamic_lstm", "dynamic_gru", "lstm", "gru_unit",
    "moe_ffn",
    "beam_search", "beam_search_gather", "beam_search_decode",
]


def _dtype(x):
    return str(x.dtype)


def _conv_out(size, k, s, p, d=1):
    if size is None or size < 0:
        return -1
    return (size + 2 * p - (d * (k - 1) + 1)) // s + 1


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


# ---------------------------------------------------------------------------
# dense / embedding
# ---------------------------------------------------------------------------

def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """Fully-connected layer (ref ``nn.py`` fc). Multiple inputs are summed
    after projection, matching the reference."""
    helper = LayerHelper("fc", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    import copy as _copy
    attrs = ParamAttr._to_attr(param_attr)
    if not isinstance(attrs, list):
        # one attr per input (ref fc w_0/w_1 suffixes): copies so unnamed
        # attrs each generate a fresh name; explicitly named attrs get a
        # _<i> suffix so the weights don't collide
        copies = [attrs]
        for i in range(1, len(inputs)):
            c = _copy.copy(attrs)
            if c.name is not None:
                c.name = "%s_%d" % (c.name, i)
            copies.append(c)
        attrs = copies
    mul_results = []
    for inp, attr in zip(inputs, attrs):
        in_shape = inp.shape
        flat_dim = int(np.prod(in_shape[num_flatten_dims:]))
        w = helper.create_parameter(attr, shape=[flat_dim, size],
                                    dtype=_dtype(inp))
        out_shape = tuple(in_shape[:num_flatten_dims]) + (size,)
        tmp = helper.create_variable_for_type_inference(
            dtype=_dtype(inp), shape=out_shape)
        helper.append_op("mul", {"X": inp, "Y": w}, {"Out": tmp},
                         {"x_num_col_dims": num_flatten_dims,
                          "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(
            dtype=_dtype(inputs[0]), shape=mul_results[0].shape)
        helper.append_op("sum", {"X": mul_results}, {"Out": pre_bias}, {})
    pre_act = helper.append_bias_op(pre_bias)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    """Embedding lookup (ref ``nn.py`` embedding / ``lookup_table_op``).
    ``is_sparse`` marks the gradient for scatter-style updates;
    ``is_distributed`` marks the table for mesh sharding (the pserver
    distributed-lookup-table analog, see parallel/sharded_embedding)."""
    helper = LayerHelper("embedding", param_attr=param_attr, name=name)
    w = helper.create_parameter(helper.param_attr, shape=list(size), dtype=dtype)
    w.is_distributed = is_distributed
    if is_sparse:
        # SelectedRows parity (ref ``framework/selected_rows.h:32``): the
        # gradient materializes as (rows, values) and optimizers take their
        # scatter-update branch instead of a full-table dense update.
        w.is_sparse_grad = True
    in_shape = input.shape
    base = in_shape[:-1] if (in_shape and in_shape[-1] == 1) else in_shape
    out = helper.create_variable_for_type_inference(
        dtype=dtype, shape=tuple(base) + (size[1],))
    helper.append_op(
        "lookup_table", {"W": w, "Ids": input}, {"Out": out},
        {"is_sparse": is_sparse, "padding_idx": padding_idx if padding_idx is not None else -1})
    return out


# ---------------------------------------------------------------------------
# convolution / pooling
# ---------------------------------------------------------------------------

def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    """2-D convolution, NCHW (ref ``nn.py`` conv2d / ``conv_op.cc``).
    ``use_cudnn`` accepted for parity (XLA picks the conv algorithm)."""
    helper = LayerHelper("conv2d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    k = _pair(filter_size)
    s = _pair(stride)
    p = _pair(padding)
    d = _pair(dilation)
    n, c, h, w_ = input.shape
    std = (2.0 / (k[0] * k[1] * c)) ** 0.5
    filt = helper.create_parameter(
        helper.param_attr, shape=[num_filters, c // groups, k[0], k[1]],
        dtype=_dtype(input),
        default_initializer=NormalInitializer(0.0, std))
    out_shape = (n, num_filters, _conv_out(h, k[0], s[0], p[0], d[0]),
                 _conv_out(w_, k[1], s[1], p[1], d[1]))
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=out_shape)
    helper.append_op(
        "conv2d", {"Input": input, "Filter": filt}, {"Output": out},
        {"strides": list(s), "paddings": list(p), "dilations": list(d),
         "groups": groups})
    if helper.bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[num_filters],
                                    dtype=_dtype(input), is_bias=True)
        tmp = helper.create_variable_for_type_inference(
            dtype=_dtype(input), shape=out_shape)
        helper.append_op("elementwise_add", {"X": out, "Y": b}, {"Out": tmp},
                         {"axis": 1})
        out = tmp
    return helper.append_activation(out)


def depthwise_conv2d(input, num_filters, filter_size, **kwargs):
    kwargs["groups"] = input.shape[1]
    return conv2d(input, num_filters, filter_size, **kwargs)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv3d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    k = tuple(filter_size) if isinstance(filter_size, (list, tuple)) else (filter_size,) * 3
    s = tuple(stride) if isinstance(stride, (list, tuple)) else (stride,) * 3
    p = tuple(padding) if isinstance(padding, (list, tuple)) else (padding,) * 3
    d = tuple(dilation) if isinstance(dilation, (list, tuple)) else (dilation,) * 3
    n, c = input.shape[0], input.shape[1]
    spatial = input.shape[2:]
    filt = helper.create_parameter(
        helper.param_attr, shape=[num_filters, c // groups] + list(k),
        dtype=_dtype(input))
    out_shape = (n, num_filters) + tuple(
        _conv_out(sz, k[i], s[i], p[i], d[i]) for i, sz in enumerate(spatial))
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=out_shape)
    helper.append_op(
        "conv3d", {"Input": input, "Filter": filt}, {"Output": out},
        {"strides": list(s), "paddings": list(p), "dilations": list(d),
         "groups": groups})
    if helper.bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[num_filters],
                                    dtype=_dtype(input), is_bias=True)
        tmp = helper.create_variable_for_type_inference(
            dtype=_dtype(input), shape=out_shape)
        helper.append_op("elementwise_add", {"X": out, "Y": b}, {"Out": tmp},
                         {"axis": 1})
        out = tmp
    return helper.append_activation(out)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    s = _pair(stride)
    p = _pair(padding)
    d = _pair(dilation)
    n, c, h, w_ = input.shape
    if filter_size is None:
        assert output_size is not None
        osz = _pair(output_size)
        k = tuple(osz[i] - (input.shape[2 + i] - 1) * s[i] + 2 * p[i]
                  for i in range(2))
    else:
        k = _pair(filter_size)
    filt = helper.create_parameter(
        helper.param_attr, shape=[c, num_filters // groups, k[0], k[1]],
        dtype=_dtype(input))
    oh = (h - 1) * s[0] - 2 * p[0] + d[0] * (k[0] - 1) + 1 if h > 0 else -1
    ow = (w_ - 1) * s[1] - 2 * p[1] + d[1] * (k[1] - 1) + 1 if w_ > 0 else -1
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=(n, num_filters, oh, ow))
    helper.append_op(
        "conv2d_transpose", {"Input": input, "Filter": filt},
        {"Output": out},
        {"strides": list(s), "paddings": list(p), "dilations": list(d),
         "groups": groups})
    if helper.bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[num_filters],
                                    dtype=_dtype(input), is_bias=True)
        tmp = helper.create_variable_for_type_inference(
            dtype=_dtype(input), shape=out.shape)
        helper.append_op("elementwise_add", {"X": out, "Y": b}, {"Out": tmp},
                         {"axis": 1})
        out = tmp
    return helper.append_activation(out)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    helper = LayerHelper("pool2d", name=name)
    k = _pair(pool_size)
    s = _pair(pool_stride)
    p = _pair(pool_padding)
    n, c, h, w_ = input.shape
    if global_pooling:
        out_shape = (n, c, 1, 1)
    else:
        rnd = (lambda a, b: -(-a // b)) if ceil_mode else (lambda a, b: a // b)
        oh = rnd(h + 2 * p[0] - k[0], s[0]) + 1 if h > 0 else -1
        ow = rnd(w_ + 2 * p[1] - k[1], s[1]) + 1 if w_ > 0 else -1
        out_shape = (n, c, oh, ow)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=out_shape)
    helper.append_op(
        "pool2d", {"X": input}, {"Out": out},
        {"pooling_type": pool_type, "ksize": list(k), "strides": list(s),
         "paddings": list(p), "global_pooling": global_pooling,
         "ceil_mode": ceil_mode, "exclusive": exclusive})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    k = _pair(pool_size)
    n, c = input.shape[0], input.shape[1]
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=(n, c, k[0], k[1]))
    helper.append_op(
        "pool2d", {"X": input}, {"Out": out},
        {"pooling_type": pool_type, "ksize": list(k), "adaptive": True})
    return out


# ---------------------------------------------------------------------------
# normalization / dropout
# ---------------------------------------------------------------------------

def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               use_global_stats=False):
    """BatchNorm (ref ``nn.py`` batch_norm / ``batch_norm_op.cc``). Moving
    stats are persistable state vars updated functionally each step."""
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    dtype = _dtype(input)
    scale = helper.create_parameter(
        helper.param_attr, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(
        helper.bias_attr, shape=[c], dtype=dtype, is_bias=True)
    mean = helper.create_parameter(
        ParamAttr(name=moving_mean_name, trainable=False),
        shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(0.0))
    variance = helper.create_parameter(
        ParamAttr(name=moving_variance_name, trainable=False),
        shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    mean.stop_gradient = True
    variance.stop_gradient = True
    out = helper.create_variable_for_type_inference(dtype=dtype,
                                                    shape=input.shape)
    saved_mean = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(c,), stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(c,), stop_gradient=True)
    helper.append_op(
        "batch_norm",
        {"X": input, "Scale": scale, "Bias": bias, "Mean": mean,
         "Variance": variance},
        {"Y": out, "MeanOut": mean, "VarianceOut": variance,
         "SavedMean": saved_mean, "SavedVariance": saved_var},
        {"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
         "data_layout": data_layout, "use_global_stats": use_global_stats})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = _dtype(input)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": input}
    if scale:
        s = helper.create_parameter(
            helper.param_attr, shape=norm_shape, dtype=dtype,
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = s
    if shift:
        b = helper.create_parameter(helper.bias_attr, shape=norm_shape,
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = b
    out = helper.create_variable_for_type_inference(dtype=dtype,
                                                    shape=input.shape)
    mean = helper.create_variable_for_type_inference(
        dtype=dtype, shape=input.shape[:begin_norm_axis], stop_gradient=True)
    var = helper.create_variable_for_type_inference(
        dtype=dtype, shape=input.shape[:begin_norm_axis], stop_gradient=True)
    helper.append_op("layer_norm", inputs,
                     {"Y": out, "Mean": mean, "Variance": var},
                     {"epsilon": epsilon, "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, name=None):
    helper = LayerHelper("group_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1]
    dtype = _dtype(input)
    scale = helper.create_parameter(
        helper.param_attr, shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(helper.bias_attr, shape=[c], dtype=dtype,
                                   is_bias=True)
    out = helper.create_variable_for_type_inference(dtype=dtype,
                                                    shape=input.shape)
    helper.append_op("group_norm",
                     {"X": input, "Scale": scale, "Bias": bias},
                     {"Y": out}, {"groups": groups, "epsilon": epsilon})
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                    shape=x.shape)
    mask = helper.create_variable_for_type_inference(
        dtype=_dtype(x), shape=x.shape, stop_gradient=True)
    helper.append_op("dropout", {"X": x}, {"Out": out, "Mask": mask},
                     {"dropout_prob": dropout_prob, "is_test": is_test,
                      "dropout_implementation": dropout_implementation})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                    shape=input.shape)
    helper.append_op("lrn", {"X": input}, {"Out": out},
                     {"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


# ---------------------------------------------------------------------------
# generic op-emitters used by many layers
# ---------------------------------------------------------------------------

def _unary_layer(op_type, x, attrs=None, name=None, out_shape=None,
                 out_dtype=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(
        dtype=out_dtype or _dtype(x),
        shape=x.shape if out_shape is None else out_shape)
    helper.append_op(op_type, {"X": x}, {"Out": out}, attrs or {})
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    return _unary_layer("softmax", input, {"axis": axis}, name)


def log_softmax(input, axis=-1, name=None):
    return _unary_layer("log_softmax", input, {"axis": axis}, name)


def relu(x, name=None):
    return _unary_layer("relu", x, name=name)


def relu6(x, threshold=6.0, name=None):
    return _unary_layer("relu6", x, {"threshold": threshold}, name)


def leaky_relu(x, alpha=0.02, name=None):
    return _unary_layer("leaky_relu", x, {"alpha": alpha}, name)


def elu(x, alpha=1.0, name=None):
    return _unary_layer("elu", x, {"alpha": alpha}, name)


def gelu(x, approximate=None, name=None):
    """``approximate=None`` (default) lets the op pick: exact erf in f32,
    tanh-approx under AMP (see ``opimpl/math_ops.py:_gelu``). Pass an
    explicit bool to pin the form."""
    attrs = {} if approximate is None else {"approximate": approximate}
    return _unary_layer("gelu", x, attrs, name)


def swish(x, beta=1.0, name=None):
    return _unary_layer("swish", x, {"beta": beta}, name)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _unary_layer("brelu", x, {"t_min": t_min, "t_max": t_max}, name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _unary_layer("stanh", x, {"scale_a": scale_a, "scale_b": scale_b},
                        name)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _unary_layer("hard_sigmoid", x, {"slope": slope, "offset": offset},
                        name)


def pow(x, factor=1.0, name=None):
    return _unary_layer("pow", x, {"factor": factor}, name)


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = [int(np.prod(x.shape[1:]))]
    alpha = helper.create_parameter(
        helper.param_attr, shape=alpha_shape, dtype=_dtype(x),
        default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                    shape=x.shape)
    helper.append_op("prelu", {"X": x, "Alpha": alpha}, {"Out": out},
                     {"mode": mode})
    return out


def maxout(x, groups, name=None):
    n, c, h, w = x.shape
    return _unary_layer("maxout", x, {"groups": groups}, name,
                        out_shape=(n, c // groups, h, w))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                    shape=x.shape)
    helper.append_op("scale", {"X": x}, {"Out": out},
                     {"scale": scale, "bias": bias,
                      "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def clip(x, min, max, name=None):
    return _unary_layer("clip", x, {"min": min, "max": max}, name)


def clip_by_norm(x, max_norm, name=None):
    return _unary_layer("clip_by_norm", x, {"max_norm": max_norm}, name)


def mean(x, name=None):
    return _unary_layer("mean", x, name=name, out_shape=())


def _elementwise(op_type, x, y, axis=-1, act=None, name=None):
    from ..core.op_registry import static_bcast_shape

    helper = LayerHelper(op_type, act=act, name=name)
    try:
        out_shape = static_bcast_shape(x.shape, y.shape, axis)
    except ValueError:
        # statically infeasible: declare x's shape and let the analysis
        # shape pass report the mismatch with provenance
        out_shape = x.shape
    out = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                    shape=out_shape)
    helper.append_op(op_type, {"X": x, "Y": y}, {"Out": out}, {"axis": axis})
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_pow", x, y, axis, act, name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mod", x, y, axis, act, name)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out_shape = tuple(x.shape[:x_num_col_dims]) + tuple(y.shape[y_num_col_dims:])
    out = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                    shape=out_shape)
    helper.append_op("mul", {"X": x, "Y": y}, {"Out": out},
                     {"x_num_col_dims": x_num_col_dims,
                      "y_num_col_dims": y_num_col_dims})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    xs = list(x.shape)
    ys = list(y.shape)
    if transpose_x and len(xs) >= 2:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if transpose_y and len(ys) >= 2:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    batch = xs[:-2] if len(xs) >= len(ys) else ys[:-2]
    out_shape = tuple(batch) + (xs[-2], ys[-1]) if len(xs) >= 2 and len(ys) >= 2 else None
    out = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                    shape=out_shape)
    helper.append_op("matmul", {"X": x, "Y": y}, {"Out": out},
                     {"transpose_X": transpose_x, "transpose_Y": transpose_y,
                      "alpha": alpha})
    return out


def _reduce_layer(op_type, input, dim, keep_dim, name):
    helper = LayerHelper(op_type, name=name)
    if dim is None:
        out_shape = ()
        reduce_all = True
        dims = [0]
    else:
        dims = dim if isinstance(dim, (list, tuple)) else [dim]
        reduce_all = False
        nd = len(input.shape)
        axes = {d % nd for d in dims}
        if keep_dim:
            out_shape = tuple(1 if i in axes else s
                              for i, s in enumerate(input.shape))
        else:
            out_shape = tuple(s for i, s in enumerate(input.shape)
                              if i not in axes)
    out = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                    shape=out_shape)
    helper.append_op(op_type, {"X": input}, {"Out": out},
                     {"dim": list(dims), "keep_dim": keep_dim,
                      "reduce_all": reduce_all})
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer("reduce_prod", input, dim, keep_dim, name)


def cumsum(x, axis=-1, exclusive=False, reverse=False, name=None):
    return _unary_layer("cumsum", x,
                        {"axis": axis, "exclusive": exclusive,
                         "reverse": reverse}, name)


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    nd = len(input.shape)
    axis = dim % nd
    in_sz = input.shape[axis]
    if isinstance(num_or_sections, int):
        sections = [in_sz // num_or_sections] * num_or_sections
        attrs = {"num": num_or_sections, "axis": axis}
    else:
        sections = list(num_or_sections)
        attrs = {"sections": sections, "axis": axis}
    outs = []
    for sec in sections:
        shape = tuple(sec if i == axis else s for i, s in enumerate(input.shape))
        outs.append(helper.create_variable_for_type_inference(
            dtype=_dtype(input), shape=shape))
    helper.append_op("split", {"X": input}, {"Out": outs}, attrs)
    return outs


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    out_shape = tuple(input.shape[:-1]) + (k,)
    values = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=out_shape)
    indices = helper.create_variable_for_type_inference(
        dtype="int32", shape=out_shape, stop_gradient=True)
    helper.append_op("top_k", {"X": input},
                     {"Out": values, "Indices": indices}, {"k": k})
    return values, indices


def argmax(x, axis=0, name=None):
    shape = tuple(s for i, s in enumerate(x.shape) if i != axis % len(x.shape))
    return _unary_layer("argmax", x, {"axis": axis}, name, out_shape=shape,
                        out_dtype="int32")


def argmin(x, axis=0, name=None):
    shape = tuple(s for i, s in enumerate(x.shape) if i != axis % len(x.shape))
    return _unary_layer("argmin", x, {"axis": axis}, name, out_shape=shape,
                        out_dtype="int32")


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                    shape=input.shape)
    ids = helper.create_variable_for_type_inference(
        dtype="int32", shape=input.shape, stop_gradient=True)
    helper.append_op("argsort", {"X": input},
                     {"Out": out, "Indices": ids}, {"axis": axis})
    return out, ids


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                    shape=x.shape)
    norm = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                     shape=x.shape)
    helper.append_op("norm", {"X": x}, {"Out": out, "Norm": norm},
                     {"axis": axis, "epsilon": epsilon})
    return out


def cos_sim(X, Y, name=None):
    xn = l2_normalize(X, axis=-1)
    yn = l2_normalize(Y, axis=-1)
    prod = elementwise_mul(xn, yn)
    return reduce_sum(prod, dim=-1, keep_dim=True)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def cross_entropy(input, label, soft_label=False, ignore_index=-100,
                  name=None):
    helper = LayerHelper("cross_entropy", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=tuple(input.shape[:-1]) + (1,))
    helper.append_op("cross_entropy", {"X": input, "Label": label},
                     {"Y": out},
                     {"soft_label": soft_label, "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(
        dtype=_dtype(logits), shape=tuple(logits.shape[:-1]) + (1,))
    softmax_out = helper.create_variable_for_type_inference(
        dtype=_dtype(logits), shape=logits.shape)
    helper.append_op("softmax_with_cross_entropy",
                     {"Logits": logits, "Label": label},
                     {"Loss": loss, "Softmax": softmax_out},
                     {"soft_label": soft_label, "ignore_index": ignore_index})
    if return_softmax:
        return loss, softmax_out
    return loss


def smooth_softmax_with_cross_entropy(logits, label, epsilon=0.0):
    """Fused label-smoothed softmax CE (closed form, single logits pass).

    TPU-first replacement for the reference's ``label_smooth`` +
    ``softmax_with_cross_entropy`` pair (``operators/label_smooth_op.cc``,
    ``softmax_with_cross_entropy_op.cc``), which materializes a full
    [..., V] soft-label tensor. Returns per-position loss with the class
    axis reduced away (shape ``logits.shape[:-1]``)."""
    helper = LayerHelper("smooth_softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(
        dtype="float32", shape=tuple(logits.shape[:-1]))
    helper.append_op("smooth_softmax_ce",
                     {"Logits": logits, "Label": label},
                     {"Loss": loss}, {"epsilon": float(epsilon)})
    return loss


def fused_linear_smooth_ce(input, label, size, epsilon=0.0,
                           param_attr=None, bias_attr=None, name=None):
    """Vocab projection + label-smoothed softmax CE, fused (the TPU
    replacement for ``fc(size=V)`` + ``smooth_softmax_with_cross_entropy``:
    on TPU the [.., V] logits stay in VMEM — see ``ops/fused_ce.py``).
    ``input``: [..., D]; ``label``: int ids shaped like ``input[:-1]``.
    Returns per-position f32 loss of shape ``input.shape[:-1]``."""
    helper = LayerHelper("fused_linear_smooth_ce", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d_in = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr, shape=[d_in, size],
                                dtype=_dtype(input))
    inputs = {"X": input, "W": w, "Label": label}
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[size],
                                    dtype=_dtype(input), is_bias=True)
        inputs["Bias"] = b
    loss = helper.create_variable_for_type_inference(
        dtype="float32", shape=tuple(input.shape[:-1]))
    helper.append_op("fused_linear_smooth_ce", inputs, {"Loss": loss},
                     {"epsilon": float(epsilon)})
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                    shape=x.shape)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     {"X": x, "Label": label}, {"Out": out},
                     {"ignore_index": ignore_index, "normalize": normalize})
    return out


def square_error_cost(input, label, name=None):
    helper = LayerHelper("square_error_cost", name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                    shape=input.shape)
    helper.append_op("square_error_cost", {"X": input, "Y": label},
                     {"Out": out}, {})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0,
              name=None):
    helper = LayerHelper("smooth_l1_loss", name=name)
    diff = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                     shape=x.shape)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(x), shape=(x.shape[0], 1))
    inputs = {"X": x, "Y": y}
    if inside_weight is not None:
        inputs["InsideWeight"] = inside_weight
    if outside_weight is not None:
        inputs["OutsideWeight"] = outside_weight
    helper.append_op("smooth_l1_loss", inputs,
                     {"Diff": diff, "Out": out}, {"sigma": sigma})
    return out


def huber_loss(input, label, delta, name=None):
    helper = LayerHelper("huber_loss", name=name)
    residual = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                         shape=input.shape)
    out = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                    shape=input.shape)
    helper.append_op("huber_loss", {"X": input, "Y": label},
                     {"Residual": residual, "Out": out}, {"delta": delta})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype=dtype,
                                                    shape=label.shape)
    inputs = {"X": label}
    if prior_dist is not None:
        inputs["PriorDist"] = prior_dist
    helper.append_op("label_smooth", inputs, {"Out": out},
                     {"epsilon": float(epsilon)})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    shape = () if reduction in ("mean", "sum", "batchmean") else x.shape
    out = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                    shape=shape)
    helper.append_op("kldiv_loss", {"X": x, "Target": target},
                     {"Loss": out}, {"reduction": reduction})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=(input.shape[0], 1))
    helper.append_op("bpr_loss", {"X": input, "Label": label}, {"Y": out}, {})
    return out


def hinge_loss(input, label, name=None):
    helper = LayerHelper("hinge_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                    shape=input.shape)
    helper.append_op("hinge_loss", {"Logits": input, "Labels": label},
                     {"Loss": out}, {})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                    shape=input.shape)
    helper.append_op("log_loss", {"Predicted": input, "Labels": label},
                     {"Loss": out}, {"epsilon": epsilon})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(left),
                                                    shape=left.shape)
    act = helper.create_variable_for_type_inference(dtype=_dtype(left),
                                                    shape=left.shape)
    helper.append_op("margin_rank_loss",
                     {"X1": left, "X2": right, "Label": label},
                     {"Out": out, "Activated": act}, {"margin": margin})
    return out


def mse_loss(input, label, name=None):
    helper = LayerHelper("mse_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                    shape=())
    helper.append_op("mse_loss", {"X": input, "Y": label}, {"Out": out}, {})
    return out


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None, name=None):
    """CTC loss (ref ``warpctc_op.cc``): padded ``[B, T, C]`` logits
    (softmax applied internally, warp-ctc convention), ``label`` [B, L],
    per-example ``input_length``/``label_length`` [B] (defaulting to the
    padded sizes). Returns [B, 1] negative log likelihood. The alpha
    recursion runs as a lax.scan in log space — no external warp-ctc lib,
    gradient via autodiff through the scan."""
    helper = LayerHelper("warpctc", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=(input.shape[0], 1))
    inputs = {"Logits": input, "Label": label}
    if input_length is not None:
        inputs["LogitsLength"] = input_length
    if label_length is not None:
        inputs["LabelLength"] = label_length
    helper.append_op("warpctc", inputs, {"Loss": out},
                     {"blank": blank, "norm_by_times": norm_by_times})
    return out


def linear_chain_crf(input, label, param_attr=None, length=None, name=None):
    """Linear-chain CRF negative log likelihood (ref
    ``linear_chain_crf_op.cc``): ``input`` [B, T, D] emissions, ``label``
    [B, T]; creates the [D+2, D] transition parameter (row 0 start, row 1
    end, rows 2.. pairwise). Returns [B, 1] cost to minimize."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr,
                         name=name)
    size = input.shape[-1]
    transition = helper.create_parameter(
        helper.param_attr, shape=[size + 2, size], dtype=_dtype(input))
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=(input.shape[0], 1))
    inputs = {"Emission": input, "Transition": transition, "Label": label}
    if length is not None:
        inputs["Length"] = length
    helper.append_op("linear_chain_crf", inputs,
                     {"LogLikelihood": out}, {})
    return out


def crf_decoding(input, param_attr, label=None, length=None, name=None):
    """Viterbi decode with the CRF's transition parameter (ref
    ``crf_decoding_op.cc``); pass the same ``param_attr`` name used by
    ``linear_chain_crf``. With ``label`` given, returns the per-position
    correctness mask instead of the path (reference semantics)."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr, name=name)
    size = input.shape[-1]
    from ..core import framework as _fw
    attr = ParamAttr._to_attr(param_attr)
    gb = _fw.default_main_program().global_block()
    if attr.name and gb.has_var(attr.name):
        # reuse the trained transition var in-program (no duplicate init)
        transition = gb.var(attr.name)
    else:
        # separate infer program: create under the shared name; values come
        # from the scope at run time
        transition = helper.create_parameter(
            helper.param_attr, shape=[size + 2, size], dtype=_dtype(input))
    out = helper.create_variable_for_type_inference(
        dtype="int32", shape=tuple(input.shape[:2]))
    inputs = {"Emission": input, "Transition": transition}
    if label is not None:
        inputs["Label"] = label
    if length is not None:
        inputs["Length"] = length
    helper.append_op("crf_decoding", inputs, {"ViterbiPath": out}, {})
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (ref ``nce_op.cc``): ``input``
    [B, D], ``label`` [B, 1]; samples ``num_neg_samples`` noise classes per
    example (uniform or log_uniform). ``seed`` != 0 fixes the sample draw
    (reference parity); 0 threads the executor PRNG."""
    if custom_dist is not None or sample_weight is not None:
        raise NotImplementedError(
            "nce custom_dist/sample_weight are not supported; use "
            "sampler='uniform' or 'log_uniform'")
    if sampler not in ("uniform", "log_uniform"):
        raise ValueError("unsupported nce sampler %r" % (sampler,))
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=_dtype(input))
    b = helper.create_parameter(helper.bias_attr,
                                shape=[num_total_classes],
                                dtype=_dtype(input), is_bias=True)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=(input.shape[0], 1))
    helper.append_op(
        "nce", {"Input": input, "Label": label, "Weight": w, "Bias": b},
        {"Cost": out},
        {"num_neg_samples": num_neg_samples or 10, "sampler": sampler,
         "seed": seed})
    return out


def _hsigmoid_simple_code_tables(num_classes):
    """Default complete-binary-tree paths (ref ``math/matrix_bit_code.h``
    SimpleCode): class c maps to code c + num_classes; node index at bit i
    is (code >> (i+1)) - 1, bit value (code >> i) & 1."""
    rows = []
    for c in range(num_classes):
        code = c + num_classes
        length = code.bit_length() - 1
        rows.append(([(code >> (i + 1)) - 1 for i in range(length)],
                     [(code >> i) & 1 for i in range(length)]))
    max_len = max(len(r[0]) for r in rows)
    table = [r[0] + [-1] * (max_len - len(r[0])) for r in rows]
    codes = [[float(v) for v in r[1]] + [0.0] * (max_len - len(r[1]))
             for r in rows]
    return table, codes


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid (ref ``hierarchical_sigmoid_op.cc``): log-time
    softmax over a class tree. Default: complete binary tree with
    ``num_classes - 1`` internal nodes; custom: ``path_table``/``path_code``
    vars [B, L] (pad with -1)."""
    helper = LayerHelper("hsigmoid", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dim = input.shape[-1]
    n_nodes = num_classes if is_custom else num_classes - 1
    w = helper.create_parameter(helper.param_attr, shape=[n_nodes, dim],
                                dtype=_dtype(input))
    b = helper.create_parameter(helper.bias_attr, shape=[n_nodes],
                                dtype=_dtype(input), is_bias=True)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=(input.shape[0], 1))
    inputs = {"Input": input, "Label": label, "W": w, "Bias": b}
    attrs = {"num_classes": num_classes}
    if is_custom:
        inputs["PathTable"] = path_table
        inputs["PathCode"] = path_code
    else:
        table, codes = _hsigmoid_simple_code_tables(num_classes)
        attrs["path_table"] = table
        attrs["path_code"] = codes
    helper.append_op("hsigmoid", inputs, {"Cost": out}, attrs)
    return out


# ---------------------------------------------------------------------------
# beam-search decode (ref ``nn.py`` beam_search / beam_search_decode over
# ``operators/beam_search_op.cc``; TPU-native dense [B, K] re-design — see
# ``core/opimpl/decode_ops.py``)
# ---------------------------------------------------------------------------

def beam_search(pre_ids, pre_scores, scores, beam_size, end_id,
                return_parent_idx=True, name=None):
    """One pruning step: ``pre_ids``/``pre_scores`` [B, K], ``scores``
    [B, K, V] next-token log-probs. Returns (selected_ids, selected_scores,
    parent_idx), each [B, K]. Step 0 convention: initialize pre_scores to
    [0, -1e9, ...] so the duplicated start beams collapse to one."""
    helper = LayerHelper("beam_search", name=name)
    b, k = tuple(pre_ids.shape)[:2]
    sel_ids = helper.create_variable_for_type_inference(
        dtype=str(pre_ids.dtype), shape=(b, k))
    sel_scores = helper.create_variable_for_type_inference(
        dtype=str(pre_scores.dtype), shape=(b, k))
    parent = helper.create_variable_for_type_inference(
        dtype="int32", shape=(b, k))
    helper.append_op(
        "beam_search_step",
        {"PreIds": pre_ids, "PreScores": pre_scores, "Scores": scores},
        {"SelectedIds": sel_ids, "SelectedScores": sel_scores,
         "ParentIdx": parent},
        {"beam_size": beam_size, "end_id": end_id})
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_gather(x, parent_idx, name=None):
    """Reorder per-beam state ``x`` [B, K, ...] by ``parent_idx`` [B, K]
    (the reference reorders hidden state via LoD; here an explicit gather)."""
    helper = LayerHelper("beam_search_gather", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(x), shape=x.shape)
    helper.append_op("beam_search_gather", {"X": x, "Ids": parent_idx},
                     {"Out": out}, {})
    return out


def beam_search_decode(ids_array, parents_array, length, final_scores,
                       beam_size, end_id, name=None):
    """Backtrack per-step (ids, parents) arrays — written by ``array_write``
    inside the decode loop — into sentences [B, K, T] + scores [B, K]
    (ref ``beam_search_decode_op.cc``)."""
    helper = LayerHelper("beam_search_decode", name=name)
    sent = helper.create_variable_for_type_inference(dtype="int64",
                                                     shape=None)
    sscores = helper.create_variable_for_type_inference(
        dtype=str(final_scores.dtype), shape=final_scores.shape)
    helper.append_op(
        "beam_search_decode",
        {"IdsArray": ids_array, "ParentsArray": parents_array,
         "Length": length, "FinalScores": final_scores},
        {"SentenceIds": sent, "SentenceScores": sscores},
        {"beam_size": beam_size, "end_id": end_id})
    return sent, sscores


# ---------------------------------------------------------------------------
# misc tensor-ish layers that live in nn.py in the reference
# ---------------------------------------------------------------------------

def one_hot(input, depth, name=None):
    base = input.shape[:-1] if input.shape and input.shape[-1] == 1 else input.shape
    return _unary_layer("one_hot", input, {"depth": depth}, name,
                        out_shape=tuple(base) + (depth,), out_dtype="float32")


def lod_reset(x, y=None, target_lod=None):
    """LoD is not a TPU concept; identity for API parity (sequence info
    travels as explicit length tensors)."""
    return x


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    shape = tuple(
        (s + paddings[2 * i] + paddings[2 * i + 1]) if s >= 0 else -1
        for i, s in enumerate(x.shape))
    out = helper.create_variable_for_type_inference(dtype=_dtype(x),
                                                    shape=shape)
    helper.append_op("pad", {"X": x}, {"Out": out},
                     {"paddings": list(paddings), "pad_value": pad_value})
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    n, c, h, w = input.shape
    shape = (n, c, h + paddings[0] + paddings[1] if h >= 0 else -1,
             w + paddings[2] + paddings[3] if w >= 0 else -1)
    out = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                    shape=shape)
    helper.append_op("pad2d", {"X": input}, {"Out": out},
                     {"paddings": list(paddings), "mode": mode,
                      "pad_value": pad_value})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1):
    helper = LayerHelper("image_resize", name=name)
    n, c, h, w = input.shape
    if out_shape is not None:
        oh, ow = out_shape
    else:
        oh, ow = int(h * scale), int(w * scale)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=(n, c, oh, ow))
    op_type = "bilinear_interp" if resample.upper() == "BILINEAR" else "nearest_interp"
    helper.append_op(op_type, {"X": input}, {"Out": out},
                     {"out_h": oh, "out_w": ow,
                      "align_corners": align_corners})
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners)


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", name=name)
    n, c = x.shape[0], x.shape[1]
    oh, ow = grid.shape[1], grid.shape[2]
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(x), shape=(n, c, oh, ow))
    helper.append_op("grid_sampler", {"X": x, "Grid": grid},
                     {"Output": out}, {})
    return out


def pixel_shuffle(x, upscale_factor, name=None):
    helper = LayerHelper("pixel_shuffle", name=name)
    n, c, h, w = x.shape
    r = upscale_factor
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(x), shape=(n, c // (r * r), h * r, w * r))
    helper.append_op("pixel_shuffle", {"X": x}, {"Out": out},
                     {"upscale_factor": r})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    helper = LayerHelper("im2sequence", name=name)
    k = _pair(filter_size)
    s = _pair(stride)
    p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
    n, c, h, w = input.shape
    oh = (h + p[0] + p[2] - k[0]) // s[0] + 1 if h > 0 else -1
    ow = (w + p[1] + p[3] - k[1]) // s[1] + 1 if w > 0 else -1
    rows = n * oh * ow if n > 0 and oh > 0 and ow > 0 else -1
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=(rows, c * k[0] * k[1]))
    helper.append_op("im2sequence", {"X": input}, {"Out": out},
                     {"kernels": list(k), "strides": list(s),
                      "paddings": list(p)})
    return out


# ---------------------------------------------------------------------------
# recurrent layers (ref ``nn.py`` dynamic_lstm/dynamic_gru over
# ``operators/lstm_op.cc``/``gru_op.cc``; TPU-native: lax.scan over padded
# [B, T, *] batches + explicit lengths instead of LoD)
# ---------------------------------------------------------------------------

def dynamic_lstm(input, size, lengths=None, h_0=None, c_0=None,
                 param_attr=None, bias_attr=None,
                 use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype=None, name=None):
    """LSTM over a pre-projected sequence (ref ``nn.py`` dynamic_lstm).

    ``input`` is ``[B, T, 4H]`` — the x@W projection done by a preceding
    ``fc`` (matching the reference contract where ``size = 4*hidden`` and the
    input projection is the user's fc). ``lengths`` `[B]` masks padding (the
    LoD replacement); ``h_0``/``c_0`` `[B, H]` seed the recurrent state
    (zeros when omitted). Returns ``(hidden [B,T,H], cell [B,T,H])``.
    ``use_peepholes`` accepted for API parity (ignored: peephole connections
    are off the MXU critical path and rarely used)."""
    helper = LayerHelper("dynamic_lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden_size = size // 4
    dtype = dtype or _dtype(input)
    w = helper.create_parameter(helper.param_attr,
                                shape=[hidden_size, 4 * hidden_size],
                                dtype=dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[4 * hidden_size],
                                dtype=dtype, is_bias=True)
    b_sz, t_sz = input.shape[0], input.shape[1]
    hidden = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(b_sz, t_sz, hidden_size))
    cell = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(b_sz, t_sz, hidden_size))
    inputs = {"Input": input, "Weight": w, "Bias": b}
    if lengths is not None:
        inputs["Lengths"] = lengths
    if h_0 is not None:
        inputs["H0"] = h_0
    if c_0 is not None:
        inputs["C0"] = c_0
    helper.append_op("lstm_seq", inputs, {"Hidden": hidden, "Cell": cell},
                     {"is_reverse": is_reverse})
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, lengths=None, h_0=None, c_0=None,
                  param_attr=None, bias_attr=None, use_peepholes=False,
                  is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", cell_clip=0.0, proj_clip=0.0,
                  dtype=None, name=None):
    """Projection LSTM (ref ``nn.py`` dynamic_lstmp / ``lstmp_op.cc``):
    the recurrent state is the P-dim projection of the hidden state.
    ``input`` is ``[B, T, 4H]`` pre-projected; returns
    ``(projection [B,T,P], cell [B,T,H])``."""
    helper = LayerHelper("dynamic_lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden_size = size // 4
    dtype = dtype or _dtype(input)
    w = helper.create_parameter(helper.param_attr,
                                shape=[proj_size, 4 * hidden_size],
                                dtype=dtype)
    import copy as _copy
    pattr = ParamAttr._to_attr(param_attr)
    pattr = _copy.copy(pattr)
    if pattr.name is not None:
        pattr.name = pattr.name + "_proj"
    wp = helper.create_parameter(pattr, shape=[hidden_size, proj_size],
                                 dtype=dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[4 * hidden_size],
                                dtype=dtype, is_bias=True)
    b_sz, t_sz = input.shape[0], input.shape[1]
    proj = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(b_sz, t_sz, proj_size))
    cell = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(b_sz, t_sz, hidden_size))
    inputs = {"Input": input, "Weight": w, "ProjWeight": wp, "Bias": b}
    if lengths is not None:
        inputs["Lengths"] = lengths
    if h_0 is not None:
        inputs["H0"] = h_0
    if c_0 is not None:
        inputs["C0"] = c_0
    helper.append_op("lstmp_seq", inputs,
                     {"Projection": proj, "Cell": cell},
                     {"is_reverse": is_reverse, "cell_clip": cell_clip,
                      "proj_clip": proj_clip,
                      "proj_activation": proj_activation})
    return proj, cell


def attention_lstm(input, size, lengths=None, h_0=None, c_0=None,
                   param_attr=None, bias_attr=None, name=None):
    """Attention LSTM (ref ``attention_lstm_op.cc``): each step attends
    over the whole sequence with c_{t-1} and feeds the pooled vector to
    an LSTM cell. ``input`` [B, T, M]; returns (hidden [B,T,D], cell)."""
    helper = LayerHelper("attention_lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = size
    m = int(input.shape[-1])
    dtype = _dtype(input)
    aw = helper.create_parameter(helper.param_attr, shape=[m + d, 1],
                                 dtype=dtype)
    ab = helper.create_parameter(helper.bias_attr, shape=[1], dtype=dtype,
                                 is_bias=True)
    asc = helper.create_parameter(None, shape=[1], dtype=dtype)
    asb = helper.create_parameter(None, shape=[1], dtype=dtype,
                                  is_bias=True)
    import copy as _copy
    pattr = _copy.copy(ParamAttr._to_attr(param_attr))
    if pattr.name is not None:
        pattr.name = pattr.name + "_lstm"
    lw = helper.create_parameter(pattr, shape=[m + d, 4 * d], dtype=dtype)
    lb = helper.create_parameter(None, shape=[4 * d], dtype=dtype,
                                 is_bias=True)
    b_sz, t_sz = input.shape[0], input.shape[1]
    hidden = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(b_sz, t_sz, d))
    cell = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(b_sz, t_sz, d))
    inputs = {"X": input, "AttentionWeight": aw, "AttentionBias": ab,
              "AttentionScalar": asc, "AttentionScalarBias": asb,
              "LSTMWeight": lw, "LSTMBias": lb}
    if lengths is not None:
        inputs["Lengths"] = lengths
    if h_0 is not None:
        inputs["H0"] = h_0
    if c_0 is not None:
        inputs["C0"] = c_0
    helper.append_op("attention_lstm", inputs,
                     {"Hidden": hidden, "Cell": cell}, {})
    return hidden, cell


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Tree-based convolution (ref ``nn.py`` tree_conv /
    ``tree_conv_op.cc``, TBCNN): continuous-binary-tree filters over
    subtree patches. Returns [*, N, output_size, num_filters] (batched)
    like the reference's [N, output_size, num_filters]."""
    helper = LayerHelper("tree_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = _dtype(nodes_vector)
    fdim = int(nodes_vector.shape[-1])
    w = helper.create_parameter(
        helper.param_attr, shape=[fdim, 3, output_size, num_filters],
        dtype=dtype)
    lead = tuple(nodes_vector.shape[:-1])
    out = helper.create_variable_for_type_inference(
        dtype=dtype, shape=lead + (output_size, num_filters))
    helper.append_op("tree_conv",
                     {"NodesVector": nodes_vector, "EdgeSet": edge_set,
                      "Filter": w},
                     {"Out": out}, {"max_depth": max_depth})
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr,
                                    shape=[num_filters], dtype=dtype,
                                    is_bias=True)
        biased = helper.create_variable_for_type_inference(
            dtype=dtype, shape=out.shape)
        helper.append_op("elementwise_add", {"X": out, "Y": b},
                         {"Out": biased}, {"axis": -1})
        out = biased
    return helper.append_activation(out)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    """3-D pooling over NCDHW input (ref ``nn.py`` pool3d)."""
    def _t3(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    helper = LayerHelper("pool3d", name=name)
    k, s, p = _t3(pool_size), _t3(pool_stride), _t3(pool_padding)
    n, c, d, h, w_ = input.shape
    if global_pooling:
        out_shape = (n, c, 1, 1, 1)
    else:
        rnd = (lambda a, b: -(-a // b)) if ceil_mode \
            else (lambda a, b: a // b)
        dims = [rnd(sp + 2 * pp - kk, st) + 1 if sp > 0 else -1
                for sp, kk, st, pp in zip((d, h, w_), k, s, p)]
        out_shape = (n, c) + tuple(dims)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=out_shape)
    helper.append_op(
        "pool3d", {"X": input}, {"Out": out},
        {"pooling_type": pool_type, "ksize": k, "strides": s,
         "paddings": p, "global_pooling": global_pooling,
         "ceil_mode": ceil_mode, "exclusive": exclusive})
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", name=None):
    """Adaptive 3-D pooling to a fixed output size (equal bins)."""
    helper = LayerHelper("adaptive_pool3d", name=name)
    k = list(pool_size) if isinstance(pool_size, (list, tuple)) \
        else [pool_size] * 3
    n, c = input.shape[0], input.shape[1]
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(input), shape=(n, c) + tuple(k))
    helper.append_op("pool3d", {"X": input}, {"Out": out},
                     {"pooling_type": pool_type, "ksize": k,
                      "strides": k, "paddings": [0, 0, 0],
                      "adaptive": True})
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """3-D transposed convolution over NCDHW (ref ``nn.py``
    conv3d_transpose / ``conv_transpose_op.cc``)."""
    def _t3(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    s, p, dl = _t3(stride), _t3(padding), _t3(dilation)
    fs = _t3(filter_size)
    n, cin, d, h, w_ = input.shape
    dtype = _dtype(input)
    w = helper.create_parameter(
        helper.param_attr,
        shape=[cin, num_filters // groups] + fs, dtype=dtype)
    dims = [(sp - 1) * st - 2 * pp + dd * (kk - 1) + 1 if sp > 0 else -1
            for sp, st, pp, dd, kk in zip((d, h, w_), s, p, dl, fs)]
    out = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(n, num_filters) + tuple(dims))
    helper.append_op("conv3d_transpose",
                     {"Input": input, "Filter": w}, {"Output": out},
                     {"strides": s, "paddings": p, "dilations": dl,
                      "groups": groups})
    pre_act = out
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[num_filters],
                                    dtype=dtype, is_bias=True)
        pre_act = helper.create_variable_for_type_inference(
            dtype=dtype, shape=out.shape)
        helper.append_op("elementwise_add", {"X": out, "Y": b},
                         {"Out": pre_act}, {"axis": 1})
    return helper.append_activation(pre_act)


def lstm(input, init_h=None, init_c=None, max_len=None, hidden_size=None,
         num_layers=1, dropout_prob=0.0, is_bidirec=False, lengths=None,
         is_test=False, name=None, default_initializer=None, seed=-1):
    """Multi-layer (optionally bidirectional) LSTM on ``[B, T, D]`` input
    (ref ``nn.py`` lstm / ``cudnn_lstm_op``). The per-layer input projection
    is an fc (MXU matmul batched over [B*T]); recurrence is lax.scan.
    ``init_h``/``init_c`` `[B, H]` seed layer 0's forward direction (zeros
    when omitted; deeper layers / the reverse direction always start at
    zero). ``max_len`` is unused (static shapes carry the length).
    Returns ``(out [B,T,H*dirs], last_h, last_c)`` where last_* are
    ``[B, H*dirs]`` of the final layer."""
    from . import tensor as tensor_layers
    from .sequence_lod import sequence_first_step, sequence_last_step

    x = input
    hidden = None
    cell = None
    h_r = c_r = None
    for layer in range(num_layers):
        lname = None if name is None else "%s_l%d" % (name, layer)
        proj = fc(x, size=4 * hidden_size, num_flatten_dims=2,
                  name=None if lname is None else lname + "_proj")
        hidden, cell = dynamic_lstm(proj, 4 * hidden_size, lengths=lengths,
                                    h_0=init_h if layer == 0 else None,
                                    c_0=init_c if layer == 0 else None,
                                    name=lname)
        if is_bidirec:
            proj_r = fc(x, size=4 * hidden_size, num_flatten_dims=2,
                        name=None if lname is None else lname + "_proj_r")
            h_r, c_r = dynamic_lstm(proj_r, 4 * hidden_size, lengths=lengths,
                                    is_reverse=True, name=lname)
            hidden = tensor_layers.concat([hidden, h_r], axis=-1)
        if dropout_prob and layer < num_layers - 1:
            hidden = dropout(hidden, dropout_prob, is_test=is_test)
        x = hidden
    # final states per direction: forward direction ends at t=len-1; the
    # reverse scan's final state sits at original position 0
    fwd_h = sequence_last_step(
        hidden if not is_bidirec else
        tensor_layers.slice(hidden, axes=[2], starts=[0],
                            ends=[hidden_size]), lengths=lengths)
    fwd_c = sequence_last_step(cell, lengths=lengths)
    if is_bidirec:
        last_h = tensor_layers.concat(
            [fwd_h, sequence_first_step(h_r)], axis=-1)
        last_c = tensor_layers.concat(
            [fwd_c, sequence_first_step(c_r)], axis=-1)
    else:
        last_h, last_c = fwd_h, fwd_c
    return hidden, last_h, last_c


def dynamic_gru(input, size, lengths=None, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", origin_mode=False, h_0=None,
                name=None):
    """GRU over a pre-projected ``[B, T, 3H]`` sequence (ref ``nn.py``
    dynamic_gru / ``gru_op.cc``); ``size`` is the hidden width H."""
    helper = LayerHelper("dynamic_gru", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    dtype = _dtype(input)
    w = helper.create_parameter(helper.param_attr, shape=[size, 3 * size],
                                dtype=dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[3 * size],
                                dtype=dtype, is_bias=True)
    b_sz, t_sz = input.shape[0], input.shape[1]
    hidden = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(b_sz, t_sz, size))
    inputs = {"Input": input, "Weight": w, "Bias": b}
    if lengths is not None:
        inputs["Lengths"] = lengths
    helper.append_op("gru_seq", inputs, {"Hidden": hidden},
                     {"is_reverse": is_reverse, "origin_mode": origin_mode})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", origin_mode=False,
             name=None):
    """Single GRU step (ref ``gru_unit_op``): ``input`` [B, 3H] pre-projected,
    ``hidden`` [B, H] previous state. Returns the new hidden [B, H] (the
    reference also returns gates/reset_hidden_prev; composed models only use
    the hidden)."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden_size = size // 3
    dtype = _dtype(input)
    w = helper.create_parameter(helper.param_attr,
                                shape=[hidden_size, 3 * hidden_size],
                                dtype=dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[3 * hidden_size],
                                dtype=dtype, is_bias=True)
    new_hidden = helper.create_variable_for_type_inference(
        dtype=dtype, shape=hidden.shape)
    helper.append_op("gru_unit",
                     {"Input": input, "HiddenPrev": hidden, "Weight": w,
                      "Bias": b},
                     {"Hidden": new_hidden}, {"origin_mode": origin_mode})
    return new_hidden


def moe_ffn(input, num_experts, d_ff, k=2, capacity_factor=1.25, act="relu",
            param_attr=None, name=None):
    """Mixture-of-experts feed-forward block (new capability — the reference
    has no MoE, SURVEY.md §2.5D). Expert weights are sharded over the ``ep``
    mesh axis; GSPMD lowers dispatch to ICI all-to-alls (see
    ``parallel/moe.py``). Returns ``(out, aux_loss)`` — add
    ``scale(aux_loss, small_coeff)`` into the training loss for load
    balancing."""
    if act not in ("relu", "gelu"):
        raise ValueError("moe_ffn act must be 'relu' or 'gelu', got %r"
                         % (act,))
    helper = LayerHelper("moe_ffn", param_attr=param_attr, name=name)
    d = input.shape[-1]
    dtype = _dtype(input)

    def p(tag, shape, sharding=None, init=None):
        return helper.create_parameter(
            ParamAttr(name=None if name is None else name + "." + tag,
                      initializer=init or XavierInitializer(),
                      sharding=sharding),
            shape=shape, dtype=dtype)

    # per-expert Xavier fans ([D,F], not the stacked 3-D shape — the default
    # initializer would read shape[2:] as a conv receptive field and start
    # experts ~sqrt(D)x too small)
    lim = (6.0 / (d + d_ff)) ** 0.5
    xavier2d = UniformInitializer(-lim, lim)
    gate_w = p("gate", [d, num_experts])
    w1 = p("w1", [num_experts, d, d_ff], sharding=("ep", None, None),
           init=xavier2d)
    b1 = p("b1", [num_experts, d_ff], sharding=("ep", None))
    w2 = p("w2", [num_experts, d_ff, d], sharding=("ep", None, None),
           init=xavier2d)
    b2 = p("b2", [num_experts, d], sharding=("ep", None))
    out = helper.create_variable_for_type_inference(
        dtype=dtype, shape=input.shape)
    aux = helper.create_variable_for_type_inference(dtype="float32",
                                                    shape=())
    helper.append_op(
        "moe_ffn",
        {"X": input, "GateW": gate_w, "W1": w1, "B1": b1, "W2": w2,
         "B2": b2},
        {"Out": out, "AuxLoss": aux},
        {"k": k, "capacity_factor": capacity_factor, "act": act})
    return out, aux


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Row convolution / lookahead conv (ref ``row_conv_op``): realized as a
    sequence_conv with context [0, future_context_size]."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act, name=name)
    d = input.shape[-1]
    ctx = future_context_size + 1
    w = helper.create_parameter(helper.param_attr, shape=[ctx * d, d],
                                dtype=_dtype(input))
    out = helper.create_variable_for_type_inference(dtype=_dtype(input),
                                                    shape=input.shape)
    helper.append_op("sequence_conv", {"X": input, "Filter": w},
                     {"Out": out},
                     {"contextLength": ctx, "contextStart": 0})
    return helper.append_activation(out)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Global step counter var, incremented once per executor run (ref
    ``layers/nn.py`` autoincreased_step_counter). Backing state is a
    persistable scalar initialized by the startup program."""
    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    gb = helper.main_program.global_block()
    if name in gb.vars:
        # idempotent: one increment per program per counter
        for op in gb.ops:
            if op.type == "increment" and op.output("Out").name == name:
                return gb.vars[name]
    counter = gb.create_var(
        name=name, shape=(1,), dtype="int64", persistable=True)
    startup_block = helper.startup_program.global_block()
    if not any(op.output("Out") is not None and op.output("Out").name == name
               for op in startup_block.ops):
        sp_var = startup_block.create_var(name=name, shape=(1,),
                                          dtype="int64", persistable=True)
        startup_block.append_op(
            "fill_constant", outputs={"Out": sp_var},
            attrs={"shape": (1,), "dtype": "int64", "value": begin - step})
    helper.append_op("increment", {"X": counter}, {"Out": counter},
                     {"step": float(step)})
    return counter


# ---------------------------------------------------------------------------
# attention (ref nn.py scaled_dot_product_attention; multi-head used by the
# transformer model). Uses the Pallas flash-attention kernel on TPU.
# ---------------------------------------------------------------------------

def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    helper = LayerHelper("scaled_dot_product_attention")
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(queries), shape=queries.shape)
    helper.append_op(
        "flash_attention", {"Q": queries, "K": keys, "V": values},
        {"Out": out},
        {"num_heads": num_heads, "dropout_rate": dropout_rate,
         "causal": False})
    return out


def multi_head_attention(queries, keys, values, attn_bias=None, d_key=None,
                         d_value=None, d_model=None, n_head=1,
                         dropout_rate=0.0, causal=False, param_attr=None,
                         name=None):
    """Fused multi-head attention: QKV projections (MXU matmuls) + flash
    attention (Pallas kernel on TPU) + output projection. The reference
    composes this from primitive layers in its transformer test
    (``tests/unittests/dist_transformer.py``); here it is a first-class layer
    so the hot path is one fused kernel."""
    helper = LayerHelper("multi_head_attention", param_attr=param_attr,
                         name=name)
    d_model = d_model or queries.shape[-1]
    d_key = d_key or d_model // n_head
    d_value = d_value or d_model // n_head
    dtype = _dtype(queries)

    def proj(x, dout, tag):
        w = helper.create_parameter(
            ParamAttr(name=None if name is None else name + "." + tag,
                      initializer=XavierInitializer(),
                      sharding=(None, "mp")),
            shape=[x.shape[-1], dout], dtype=dtype)
        out = helper.create_variable_for_type_inference(
            dtype=dtype, shape=tuple(x.shape[:-1]) + (dout,))
        helper.append_op("matmul", {"X": x, "Y": w}, {"Out": out}, {})
        return out

    q = proj(queries, d_key * n_head, "q")
    k = proj(keys, d_key * n_head, "k")
    v = proj(values, d_value * n_head, "v")
    ctx = helper.create_variable_for_type_inference(
        dtype=dtype, shape=tuple(queries.shape[:-1]) + (d_value * n_head,))
    inputs = {"Q": q, "K": k, "V": v}
    if attn_bias is not None:
        inputs["Bias"] = attn_bias
    helper.append_op("flash_attention", inputs, {"Out": ctx},
                     {"num_heads": n_head, "dropout_rate": dropout_rate,
                      "causal": causal})
    # output projection sharded mp on input dim (row-parallel): its matmul
    # reduces over the sharded axis -> GSPMD inserts the psum
    wo = helper.create_parameter(
        ParamAttr(name=None if name is None else name + ".out",
                  initializer=XavierInitializer(), sharding=("mp", None)),
        shape=[d_value * n_head, d_model], dtype=dtype)
    out = helper.create_variable_for_type_inference(
        dtype=dtype, shape=tuple(queries.shape[:-1]) + (d_model,))
    helper.append_op("matmul", {"X": ctx, "Y": wo}, {"Out": out}, {})
    return out


def kv_cache_write(cache, x, pos, name=None):
    """Per-row KV-cache update: ``cache[b, pos[b]] = x[b]`` (see
    ``core/opimpl/attention_ops.py``). ``cache``: [B, C, ...], ``x``:
    [B, ...], ``pos``: [B] int. Returns the updated cache tensor."""
    helper = LayerHelper("kv_cache_write", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(cache), shape=cache.shape)
    helper.append_op("kv_cache_write",
                     {"Cache": cache, "X": x, "Pos": pos}, {"Out": out}, {})
    return out


def cached_multi_head_attention(x, cache_k, cache_v, pos, d_model=None,
                                n_head=1, name=None):
    """One-token incremental attention sharing
    :func:`multi_head_attention`'s weights (same ``name`` -> same
    ``name.q/.k/.v/.out`` parameters), for KV-cached decode step programs:
    project the current token ``x`` [B, d_model], write its K/V rows into
    the fixed-capacity caches at each row's own ``pos``, attend over the
    filled prefix, and apply the output projection. Returns
    ``(out [B, d_model], new_cache_k, new_cache_v)`` — the updated caches
    are carried by the decode scheduler between steps."""
    helper = LayerHelper("cached_multi_head_attention", name=name)
    d_model = d_model or x.shape[-1]
    dtype = _dtype(x)

    def proj(inp, tag):
        w = helper.create_parameter(
            ParamAttr(name=None if name is None else name + "." + tag,
                      initializer=XavierInitializer(),
                      sharding=(None, "mp")),
            shape=[inp.shape[-1], d_model], dtype=dtype)
        out = helper.create_variable_for_type_inference(
            dtype=dtype, shape=tuple(inp.shape[:-1]) + (d_model,))
        helper.append_op("matmul", {"X": inp, "Y": w}, {"Out": out}, {})
        return out

    q = proj(x, "q")
    k = proj(x, "k")
    v = proj(x, "v")
    new_k = kv_cache_write(cache_k, k, pos, name=helper.name + "_kw")
    new_v = kv_cache_write(cache_v, v, pos, name=helper.name + "_vw")
    ctx = helper.create_variable_for_type_inference(
        dtype=dtype, shape=tuple(x.shape[:-1]) + (d_model,))
    helper.append_op("cached_attention",
                     {"Q": q, "CacheK": new_k, "CacheV": new_v, "Pos": pos},
                     {"Out": ctx}, {"num_heads": n_head})
    wo = helper.create_parameter(
        ParamAttr(name=None if name is None else name + ".out",
                  initializer=XavierInitializer(), sharding=("mp", None)),
        shape=[d_model, d_model], dtype=dtype)
    out = helper.create_variable_for_type_inference(
        dtype=dtype, shape=tuple(x.shape[:-1]) + (d_model,))
    helper.append_op("matmul", {"X": ctx, "Y": wo}, {"Out": out}, {})
    return out, new_k, new_v


def kv_cache_write_chunk(cache, x, pos, name=None):
    """K-row KV-cache update: ``cache[b, pos[b, j]] = x[b, j]`` (see
    ``core/opimpl/attention_ops.py``). ``cache``: [B, C, ...], ``x``:
    [B, K, ...], ``pos``: [B, K] int. Out-of-range positions drop, so a
    padded chunk lane writes nothing. Returns the updated cache."""
    helper = LayerHelper("kv_cache_write_chunk", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=_dtype(cache), shape=cache.shape)
    helper.append_op("kv_cache_write_chunk",
                     {"Cache": cache, "X": x, "Pos": pos}, {"Out": out}, {})
    return out


def cached_multi_head_attention_chunk(x, cache_k, cache_v, pos,
                                      d_model=None, n_head=1, name=None):
    """K-token incremental attention sharing
    :func:`multi_head_attention`'s weights (same ``name`` -> same
    ``name.q/.k/.v/.out`` parameters) — the chunked-prefill /
    speculative-verify sibling of :func:`cached_multi_head_attention`:
    project a K-token chunk ``x`` [B, K, d_model], write its K/V rows
    into the fixed-capacity caches at each row's own ``pos`` [B, K],
    attend each query over the filled prefix plus the chunk's earlier
    tokens (per-query causal mask ``c <= pos[b, j]``), and apply the
    output projection. Returns ``(out [B, K, d_model], new_cache_k,
    new_cache_v)``."""
    helper = LayerHelper("cached_multi_head_attention_chunk", name=name)
    d_model = d_model or x.shape[-1]
    dtype = _dtype(x)

    def proj(inp, tag):
        w = helper.create_parameter(
            ParamAttr(name=None if name is None else name + "." + tag,
                      initializer=XavierInitializer(),
                      sharding=(None, "mp")),
            shape=[inp.shape[-1], d_model], dtype=dtype)
        out = helper.create_variable_for_type_inference(
            dtype=dtype, shape=tuple(inp.shape[:-1]) + (d_model,))
        helper.append_op("matmul", {"X": inp, "Y": w}, {"Out": out}, {})
        return out

    q = proj(x, "q")
    k = proj(x, "k")
    v = proj(x, "v")
    new_k = kv_cache_write_chunk(cache_k, k, pos, name=helper.name + "_kw")
    new_v = kv_cache_write_chunk(cache_v, v, pos, name=helper.name + "_vw")
    ctx = helper.create_variable_for_type_inference(
        dtype=dtype, shape=tuple(x.shape[:-1]) + (d_model,))
    helper.append_op("cached_attention_chunk",
                     {"Q": q, "CacheK": new_k, "CacheV": new_v, "Pos": pos},
                     {"Out": ctx}, {"num_heads": n_head})
    wo = helper.create_parameter(
        ParamAttr(name=None if name is None else name + ".out",
                  initializer=XavierInitializer(), sharding=("mp", None)),
        shape=[d_model, d_model], dtype=dtype)
    out = helper.create_variable_for_type_inference(
        dtype=dtype, shape=tuple(x.shape[:-1]) + (d_model,))
    helper.append_op("matmul", {"X": ctx, "Y": wo}, {"Out": out}, {})
    return out, new_k, new_v

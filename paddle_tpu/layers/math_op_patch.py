"""Operator-overload sugar for Variables (ref
``python/paddle/fluid/layers/math_op_patch.py`` monkey_patch_variable)."""


from ..core.framework import Variable

_CMP = {"less_than", "less_equal", "greater_than", "greater_equal",
        "equal", "not_equal"}


def binary(x, other, op_type, reverse=False, out=None):
    block = x.block.program.current_block()
    if not isinstance(other, Variable):
        from . import tensor

        val = float(other)
        other = tensor.fill_constant(
            shape=[1], dtype=str(x.dtype), value=val)
    from ..core.op_registry import static_bcast_shape

    a, b = (other, x) if reverse else (x, other)
    a_shape = a.shape or ()
    b_shape = b.shape or ()
    try:
        out_shape = static_bcast_shape(a_shape, b_shape, -1)
    except ValueError:
        # infeasible operands: keep a's shape; the analysis shape pass
        # reports the contradiction with the op's creation site
        out_shape = a_shape
    dtype = "bool" if op_type in _CMP else str(a.dtype)
    if out is None:
        out = block.create_var(shape=out_shape, dtype=dtype)
    block.append_op(op_type, {"X": a, "Y": b}, {"Out": out}, {"axis": -1})
    return out

"""Metric layers (ref ``python/paddle/fluid/layers/metric_op.py``)."""

from ..core.layer_helper import LayerHelper
from . import nn

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    _, indices = nn.topk(input, k=k)
    acc = helper.create_variable_for_type_inference(dtype="float32", shape=())
    correct = correct or helper.create_variable_for_type_inference(
        dtype="int32", shape=(1,))
    total = total or helper.create_variable_for_type_inference(
        dtype="int32", shape=(1,))
    helper.append_op("accuracy",
                     {"Indices": indices, "Label": label},
                     {"Accuracy": acc, "Correct": correct, "Total": total},
                     {})
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming AUC with persistable histogram state (ref auc_op)."""
    helper = LayerHelper("auc")
    from . import tensor

    stat_pos = tensor.create_global_var(
        shape=(num_thresholds + 1,), value=0.0, dtype="float32",
        persistable=True, name=helper.name + ".stat_pos")
    stat_neg = tensor.create_global_var(
        shape=(num_thresholds + 1,), value=0.0, dtype="float32",
        persistable=True, name=helper.name + ".stat_neg")
    auc_out = helper.create_variable_for_type_inference(dtype="float32",
                                                        shape=())
    helper.append_op(
        "auc",
        {"Predict": input, "Label": label, "StatPos": stat_pos,
         "StatNeg": stat_neg},
        {"AUC": auc_out, "StatPosOut": stat_pos, "StatNegOut": stat_neg},
        {"num_thresholds": num_thresholds, "curve": curve})
    return auc_out, auc_out, [stat_pos, stat_neg]

"""Data-entry layers (ref ``python/paddle/fluid/layers/io.py``): ``data``
declares a feed slot; ``py_reader`` is provided by the data pipeline
(``paddle_tpu.data.py_reader``) as a host-side prefetching iterator that
feeds the executor (double-buffered device puts replace the reference's
``create_double_buffer_reader_op``)."""

from ..core.layer_helper import LayerHelper

__all__ = ["data"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=None, stop_gradient=True):
    """Declare an input variable (ref ``layers/io.py:39``).

    ``append_batch_size=True`` prepends -1, matching the reference. The
    executor specializes the compiled program on the fed batch shape."""
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.main_program.global_block().create_var(
        name=name, shape=tuple(shape), dtype=dtype, lod_level=lod_level,
        is_data=True, stop_gradient=stop_gradient)

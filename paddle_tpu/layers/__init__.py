"""User-facing layers namespace (ref ``python/paddle/fluid/layers/``)."""

from . import nn
from . import ops
from . import tensor
from . import detection
from . import extras
from . import io
from . import control_flow
from . import metric_op
from . import sequence_lod
from . import learning_rate_scheduler
from . import math_op_patch  # noqa: F401
from . import debug_ops
from .debug_ops import Print, py_func  # noqa: F401

from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403
from .metric_op import *  # noqa: F401,F403
from .sequence_lod import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .control_flow import (StaticRNN, DynamicRNN, While, Switch, cond,  # noqa: F401
                           array_write, array_read, create_array,
                           array_length, IfElse, less_than, equal,
                           increment)
from .learning_rate_scheduler import (  # noqa: F401
    exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, noam_decay,
    linear_lr_warmup)

"""Structured kernel-gate decisions (ISSUE 15 satellite).

Every Pallas kernel family guards itself with a gate (VMEM budget,
supported geometry, dtype, platform). Those gates used to answer with a
bare bool — a refused kernel silently fell back and nothing recorded
WHY. A :class:`GateDecision` carries the chosen kernel plus one
:class:`GateReason` per failed (or decisive) check, so:

  * op impls record the decision in their op's attrs
    (``op.attrs["_kernel_choice"]``) at trace time — inspectable after
    a build, cloned with the program;
  * the static resource pass (``analysis/resources.py``) evaluates the
    SAME gates shape-only (``static_only=True`` skips the platform
    checks) and surfaces refusals as findings with op provenance;
  * bench records keep reporting the honest kernel name.
"""

__all__ = ["GateReason", "GateDecision"]


class GateReason:
    """One gate check's outcome: the check name ('vmem' / 'geometry' /
    'dtype' / 'platform' / 'env' / ...), a human detail string, and
    whether this check blocked admission."""

    __slots__ = ("check", "detail", "blocking")

    def __init__(self, check, detail, blocking=True):
        self.check = check
        self.detail = detail
        self.blocking = bool(blocking)

    def to_dict(self):
        return {"check": self.check, "detail": self.detail,
                "blocking": self.blocking}

    def __repr__(self):
        return "GateReason(%s%s: %s)" % (
            self.check, "" if self.blocking else " [info]", self.detail)


class GateDecision:
    """The gate's verdict: ``kernel`` is what will actually run (the
    Pallas kernel name when admitted, the fallback name when refused);
    ``reasons`` records every failed check (refusals) or decisive note.
    Truthiness == admitted, so ``if gate(...):`` keeps working."""

    __slots__ = ("admitted", "kernel", "fallback", "reasons")

    def __init__(self, admitted, kernel, fallback=None, reasons=()):
        self.admitted = bool(admitted)
        self.kernel = kernel
        self.fallback = fallback
        self.reasons = list(reasons)

    def __bool__(self):
        return self.admitted

    @property
    def blocking_reasons(self):
        return [r for r in self.reasons if r.blocking]

    def blocked_only_by(self, *checks):
        """True when every blocking reason is one of ``checks`` — e.g.
        'the ONLY thing keeping this shape off the kernel is the VMEM
        budget' (the actionable finding class)."""
        blocking = self.blocking_reasons
        return bool(blocking) and all(r.check in checks for r in blocking)

    def to_dict(self):
        return {"admitted": self.admitted, "kernel": self.kernel,
                "fallback": self.fallback,
                "reasons": [r.to_dict() for r in self.reasons]}

    def describe(self):
        why = "; ".join("%s: %s" % (r.check, r.detail)
                        for r in self.blocking_reasons)
        if self.admitted and not self.blocking_reasons:
            return "kernel %s" % self.kernel
        if self.admitted:
            # admitted-with-demotion (e.g. head-split instead of the
            # packed streaming path): the reason IS the actionable part
            return "runs kernel %s instead of %s: %s" % (
                self.kernel, self.fallback or "the preferred kernel", why)
        return "fell back to %s (wanted %s): %s" % (
            self.kernel, self.fallback or "pallas", why or "no reason")

    def __repr__(self):
        return "GateDecision(%s)" % self.describe()

"""TPU-native row scatter-add for narrow embedding tables.

The backward of every embedding-bound model is a row scatter-add
(``grad_table.at[ids].add(grad_rows)``), and round 5 measured XLA's
lowering at ~15 ns/row regardless of row width (``tools/bench_gather.py``
``s_k16``/``s_k128``) — a per-row HBM read-modify-write DMA each, declared
a "chip property" in ``models/deepfm.py``. This module is the purpose-built
challenge to that claim (ROADMAP item 3): for tables whose PACKED layout
fits VMEM, the scatter runs as a Pallas kernel that

  1. streams the table HBM->VMEM once (as the kernel's aliased output
     block) in the packed ``P = 128 // K`` rows-per-128-lane layout of
     ``ops/rowops.py`` — so a [100000, 16] f32 table is a 6.4 MB VMEM
     resident, not a 51 MB lane-padded one;
  2. accumulates every (row, value) pair into the VMEM-resident table with
     a lane-positioned masked add (row -> (row // P, lanes (row % P)*K..)),
     so duplicate ids cost a VMEM add, never an HBM round trip; and
  3. streams the table back VMEM->HBM once.

Total HBM traffic: ``2*V*K + N*K`` bytes instead of N serialized row RMW
DMAs — at the DeepFM bench shape (V=100k, K=16, N=212992) that is ~26 MB
of streaming vs 212992 latency-bound DMAs, a ~50x headroom if the VMEM
accumulate loop keeps up. The sorted-segment formulation the ISSUE names
(sort ids, segment-reduce duplicates, one dense store per unique row) is
kept as an A/B variant (``sort=True`` / ``PADDLE_TPU_SCATTER_SORT=1``):
sorting buys store locality but costs an argsort (~7 ms/step at the bench
shape — see ``control_ops`` merge note), so the default path is unsorted
and duplicate-safe by serial accumulation. ``tools/bench_gather.py``
measures both against ``.at[ids].add`` and ``--write`` commits the winner
to ``ROW_OP_FLOORS.json`` (the ``CHIP_CEILING.json`` pattern); until a
bench-chip run lands, the 15 ns/row floor stands and the pallas entries
are null (committed-negative-result form, NOTES_r7.md).

Reference capability: ``operators/math/selected_rows_functor.cc`` MergeAdd
+ the SelectedRows optimizer kernels — the reference's answer to sparse
rows is pserver-side partial tables; ours is keeping the whole narrow
table VMEM-resident for the duration of one scatter pass.
"""

import functools

import jax
import jax.numpy as jnp

__all__ = ["scatter_add_rows", "gate", "use_pallas", "packed_vmem_bytes"]

_LANES = 128
_INTERPRET = False  # tests flip this to run the kernel on CPU

# The packed table + one double-buffered vals block must fit comfortably;
# leave headroom for the vals stream and compiler temporaries. 10 MB
# admits the [100k, 16] f32 microbench table (6.4 MB packed) but NOT the
# DeepFM bench's [100k, 32] f32 fused table (12.8 MB packed) —
# PADDLE_TPU_SCATTER_VMEM_MB raises the budget toward the 16 MB/core
# ceiling for the on-chip A/B (tools/bench_gather.py s_pallas_w32 runs
# at 14; whether Mosaic fits it is part of the pending measurement).
_DEFAULT_VMEM_MB = 10
_CHUNK = 1024  # (rows, vals) slots processed per grid step


def _vmem_budget():
    import os

    try:
        mb = float(os.environ.get("PADDLE_TPU_SCATTER_VMEM_MB",
                                  _DEFAULT_VMEM_MB))
    except ValueError:
        mb = _DEFAULT_VMEM_MB
    return int(mb * 1024 * 1024)


def packed_vmem_bytes(v, k, esize):
    """VMEM bytes of the [Vp, P*K] packed table block (the kernel's
    resident accumulator)."""
    from .rowops import pack_factor

    p = pack_factor(k)
    vp = -(-v // p)
    width = p * k
    # lane dim pads to 128, sublane to the dtype tile height
    width_pad = -(-width // _LANES) * _LANES
    sub = {2: 16, 4: 8}.get(esize, 8)
    vp_pad = -(-vp // sub) * sub
    return vp_pad * width_pad * esize


def gate(v, k, n, dtype, static_only=False):
    """Structured gate (``ops.gates.GateDecision``): the packed table
    fits the VMEM budget, the row width packs (or is already
    lane-aligned), and we are on a single TPU (a mesh would make the
    custom call fight GSPMD) or under the test interpreter.
    ``static_only=True`` evaluates ONLY the shape/dtype/VMEM checks —
    the platform-independent view the static resource pass wants."""
    from .gates import GateDecision, GateReason
    from .rowops import pack_factor

    reasons = []
    if k <= 0 or v <= 0 or n <= 0:
        reasons.append(GateReason(
            "geometry", "degenerate table/update shape [%d, %d] x %d rows"
            % (v, k, n)))
    elif pack_factor(k) == 1 and k % _LANES:
        reasons.append(GateReason(
            "geometry", "row width %d neither packs into 128 lanes nor "
            "aligns to them: lane padding would explode VMEM" % k))
    dt = jnp.dtype(dtype)
    if not jnp.issubdtype(dt, jnp.floating):
        reasons.append(GateReason(
            "dtype", "%s table: grad surfaces are float; int tables keep "
            "the XLA scatter" % dt))
        esize = 4
    else:
        esize = dt.itemsize
        if esize not in (2, 4):
            reasons.append(GateReason(
                "dtype", "%d-byte float rows unsupported" % esize))
    if not reasons:
        need = packed_vmem_bytes(v, k, esize) \
            + 2 * _CHUNK * max(k, _LANES) * esize
        budget = _vmem_budget()
        if need > budget:
            reasons.append(GateReason(
                "vmem", "packed [%d, %d] table + vals stream needs %.1f "
                "MB VMEM, budget is %.1f MB "
                "(PADDLE_TPU_SCATTER_VMEM_MB raises it)"
                % (v, k, need / 2**20, budget / 2**20)))
    if not static_only and not reasons and not _INTERPRET:
        from ..core.op_registry import env_flag, single_tpu

        if env_flag("PADDLE_TPU_NO_PALLAS_SCATTER"):  # A/B escape hatch
            reasons.append(GateReason(
                "env", "PADDLE_TPU_NO_PALLAS_SCATTER=1"))
        elif not single_tpu():
            reasons.append(GateReason(
                "platform", "not a single TPU (a mesh would make the "
                "custom call fight GSPMD)"))
    if reasons:
        return GateDecision(False, "xla_at_add", fallback="pallas_rowbin",
                            reasons=reasons)
    from ..core.op_registry import env_flag

    kernel = ("pallas_sorted_segment"
              if env_flag("PADDLE_TPU_SCATTER_SORT") else "pallas_rowbin")
    return GateDecision(True, kernel)


def use_pallas(v, k, n, dtype):
    """Boolean view of :func:`gate` (the pre-ISSUE-15 surface)."""
    return gate(v, k, n, dtype).admitted


def record_choice(op, v, k, n, dtype):
    """Evaluate the gate and record the structured decision in the
    consuming op's attrs (``_kernel_choice``) — trace-time, so a built
    program carries which kernel its sparse updates actually take and
    why (the ISSUE 15 no-silent-fallback contract)."""
    decision = gate(v, k, n, dtype)
    if op is not None:
        op.attrs["_kernel_choice"] = decision.to_dict()
    return decision


def _scatter_kernel(rows_ref, vals_ref, tab_in_ref, out_ref, *, chunk, p, k,
                    vp):
    """One grid step: fold ``chunk`` (row, value) pairs into the
    VMEM-resident packed table. ``rows_ref`` is scalar-prefetched so the
    serial accumulate loop reads indices from SMEM; sentinel rows
    (>= vp*p: out-of-range ids, padding slots, merged-duplicate parking)
    skip the store. out_ref aliases the packed table input — pallas
    streams it HBM->VMEM once, every add below is a VMEM op, and the
    final writeback is the only other HBM pass."""
    from jax.experimental import pallas as pl

    del tab_in_ref  # aliased with out_ref; present only for the alias slot
    base = pl.program_id(0) * chunk
    lane_grp = jax.lax.broadcasted_iota(jnp.int32, (1, p * k), 1) // k

    def body(i, carry):
        r = rows_ref[base + i]

        @pl.when(r < vp * p)
        def _():
            v = vals_ref[i, :].reshape(1, k)
            v_tiled = jnp.concatenate([v] * p, axis=1) if p > 1 else v
            pos = jnp.where(lane_grp == r % p, v_tiled,
                            jnp.zeros_like(v_tiled))
            out_ref[pl.ds(r // p, 1), :] += pos

        return carry

    jax.lax.fori_loop(0, chunk, body, 0)


def _scatter_packed_call(bp, rows, vals, p, k, vp):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = rows.shape[0]
    chunk = min(_CHUNK, n) if n % _CHUNK else _CHUNK
    n_pad = -(-n // chunk) * chunk
    if n_pad != n:
        rows = jnp.concatenate(
            [rows, jnp.full((n_pad - n,), vp * p, jnp.int32)])
        vals = jnp.concatenate(
            [vals, jnp.zeros((n_pad - n, k), vals.dtype)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_pad // chunk,),
        in_specs=[pl.BlockSpec((chunk, k), lambda i, rr: (i, 0)),
                  pl.BlockSpec((vp, p * k), lambda i, rr: (0, 0))],
        out_specs=pl.BlockSpec((vp, p * k), lambda i, rr: (0, 0)),
    )
    return pl.pallas_call(
        functools.partial(_scatter_kernel, chunk=chunk, p=p, k=k, vp=vp),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((vp, p * k), bp.dtype),
        input_output_aliases={2: 0},
        interpret=_INTERPRET,
    )(rows, vals, bp)


def _sorted_merge(rows, vals, sentinel):
    """The ISSUE's sorted-segment formulation: sort ids, segment-reduce
    duplicates, leaving one dense store per unique row (duplicate slots
    parked on the dropped sentinel). A/B variant — the argsort costs more
    than the serial-accumulate default saves at the bench shapes."""
    from ..core.op_registry import merge_sparse_rows

    return merge_sparse_rows(rows, vals, sentinel)


def scatter_add_rows(base, rows, vals, sort=None):
    """``base.at[rows].add(vals, mode="drop")`` for a 2-D ``[V, K]``
    table — via the VMEM-resident Pallas kernel when :func:`use_pallas`
    admits the shape, else the XLA scatter. Exact: out-of-range rows
    drop, duplicate rows accumulate.

    rows: [N] integer; vals: [N, K] (or broadcastable leading shape that
    flattens to it). ``sort=True`` (or PADDLE_TPU_SCATTER_SORT=1) routes
    through the sorted-segment merge first.
    """
    v, k = base.shape
    rows = rows.reshape(-1).astype(jnp.int32)
    vals = vals.reshape(-1, k)
    if vals.dtype != base.dtype:
        vals = vals.astype(base.dtype)
    if not use_pallas(v, k, rows.shape[0], base.dtype):
        return base.at[rows].add(vals, mode="drop")
    from .rowops import pack_factor

    p = pack_factor(k)
    vp = -(-v // p)
    if sort is None:
        from ..core.op_registry import env_flag

        sort = env_flag("PADDLE_TPU_SCATTER_SORT")
    # exact ``.at[].add(mode="drop")`` index semantics: negative rows in
    # [-V, 0) wrap python-style, anything else out of range parks on the
    # sentinel (dropped by the kernel) — so the packed row/sub
    # decomposition below always sees in-range or sentinel rows
    rows = jnp.where((rows >= -v) & (rows < 0), rows + v, rows)
    rows = jnp.where((rows >= 0) & (rows < v), rows, vp * p)
    if sort:
        rows, vals = _sorted_merge(rows, vals, vp * p)
    pad = vp * p - v
    bp = jnp.pad(base, ((0, pad), (0, 0))) if pad else base
    bp = bp.reshape(vp, p * k)
    out = _scatter_packed_call(bp, rows, vals, p, k, vp)
    return out.reshape(vp * p, k)[:v]

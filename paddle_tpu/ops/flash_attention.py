"""Flash attention: Pallas TPU kernel + pure-jax reference.

The reference framework has no fused attention (2019-era; attention is
composed from matmul/softmax layers, e.g. ``tests/unittests/dist_transformer.py``)
— this is where the TPU build beats it: one VMEM-resident kernel with online
softmax, no [T, T] HBM materialization.

Kernel design (see /opt/skills/guides/pallas_guide.md):
  grid over (batch*heads, q blocks); K/V streamed in blocks; running
  (max, sum, acc) online-softmax state in VMEM scratch; causal masking
  skips fully-masked K blocks via the grid order.
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


def _use_pallas(q):
    """Pallas path only on real TPU backends and head_dim friendly shapes."""
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    if dev.platform != "tpu":
        return False
    return True


# ---------------------------------------------------------------------------
# reference (and CPU-test) implementation
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, bias=None, causal=False, scale=None):
    """q,k,v: [B, H, T, D]."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        t_q, t_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale,
                  kv_len):
    """One q-block program. ``kv_len`` is the TRUE (unpadded) key length;
    keys at positions >= kv_len are always masked so padded inputs are
    handled exactly."""
    from jax.experimental import pallas as pl

    q = q_ref[...]  # [block_q, d]
    block_q, d = q.shape
    kv_pad = k_ref.shape[0]
    q_idx = pl.program_id(1)

    m_i = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l_i = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_kb = kv_pad // block_k

    def body(kb, carry):
        m_i, l_i, acc = carry
        k = pl.load(k_ref, (pl.dslice(kb * block_k, block_k), slice(None)))
        v = pl.load(v_ref, (pl.dslice(kb * block_k, block_k), slice(None)))
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < kv_len
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = mask & (q_pos >= k_pos)
        s = jnp.where(mask, s, -jnp.inf)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1))
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(mask, jnp.exp(s - m_safe[:, None]), 0.0)
        alpha = jnp.where(jnp.isfinite(m_i), jnp.exp(m_i - m_safe), 0.0)
        l_new = alpha * l_i + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m_i, l_i, acc = jax.lax.fori_loop(0, num_kb, body, (m_i, l_i, acc))
    o_ref[...] = (acc / jnp.maximum(l_i, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention(q, k, v, causal, scale):
    if _use_pallas(q):
        try:
            return _flash_fwd_pallas_3d(q, k, v, causal, scale)
        except Exception:
            return mha_reference(q, k, v, None, causal, scale)
    return mha_reference(q, k, v, None, causal, scale)


def _flash_fwd(q, k, v, causal, scale):
    out = _flash_attention(q, k, v, causal, scale)
    return out, (q, k, v)


def _flash_bwd(causal, scale, res, g):
    """Backward via recompute + jax autodiff of the reference formulation
    (memory-light: no stored probs; XLA fuses the recompute)."""
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: mha_reference(q, k, v, None, causal,
                                                   scale), q, k, v)
    return vjp(g)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def _flash_fwd_pallas_3d(q, k, v, causal, scale):
    """Pallas forward with per-(batch*head) vmap to keep kernel refs 2-D
    (the tiling-friendly layout: [T, D] blocks)."""
    b, h, t, d = q.shape

    def one(qi, ki, vi):
        return _one_head_pallas(qi, ki, vi, causal, scale)

    qq = q.reshape(b * h, t, d)
    kk = k.reshape(b * h, k.shape[2], d)
    vv = v.reshape(b * h, v.shape[2], d)
    out = jax.vmap(one)(qq, kk, vv)
    return out.reshape(b, h, t, d)


def _one_head_pallas(q, k, v, causal, scale, block_q=256, block_k=256):
    from jax.experimental import pallas as pl

    t, d = q.shape
    t_k = k.shape[0]
    block_q = min(block_q, t)
    block_k = min(block_k, t_k)

    # pad both sequence axes up to block multiples; padded keys are masked
    # inside the kernel (kv_len), padded q rows are sliced off after.
    def pad_to(x, m):
        r = (-x.shape[0]) % m
        return jnp.pad(x, ((0, r), (0, 0))) if r else x

    qp = pad_to(q, block_q)
    kp = pad_to(k, block_k)
    vp = pad_to(v, block_k)
    t_pad = qp.shape[0]
    tk_pad = kp.shape[0]

    kernel = functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                               scale=scale, kv_len=t_k)
    out = pl.pallas_call(
        kernel,
        grid=(1, t_pad // block_q),
        in_specs=[
            pl.BlockSpec((block_q, d), lambda _, qi: (qi, 0)),
            pl.BlockSpec((tk_pad, d), lambda _, qi: (0, 0)),
            pl.BlockSpec((tk_pad, d), lambda _, qi: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_q, d), lambda _, qi: (qi, 0)),
        out_shape=jax.ShapeDtypeStruct((t_pad, d), q.dtype),
    )(qp, kp, vp)
    return out[:t]


# ---------------------------------------------------------------------------
# public entry: packed [B, T, H*D] layout used by the layers API
# ---------------------------------------------------------------------------

def flash_attention(q, k, v, num_heads, bias=None, causal=False,
                    dropout_rate=0.0, rng=None):
    """q,k,v: [B, T, H*D] (packed heads). Returns [B, T, H*D]."""
    b, t, hd = q.shape
    d = hd // num_heads
    t_k = k.shape[1]

    def split(x, t_):
        return x.reshape(b, t_, num_heads, d).transpose(0, 2, 1, 3)

    qh, kh, vh = split(q, t), split(k, t_k), split(v, t_k)
    scale = 1.0 / math.sqrt(d)
    if bias is not None or dropout_rate > 0.0:
        out = mha_reference(qh, kh, vh, bias, causal, scale)
        if dropout_rate > 0.0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, out.shape)
            out = out * keep / (1.0 - dropout_rate)
    else:
        out = _flash_attention(qh, kh, vh, causal, scale)
    return out.transpose(0, 2, 1, 3).reshape(b, t, hd)

"""Flash attention: Pallas TPU kernels + pure-jax reference.

The reference framework has no fused attention (2019-era; attention is
composed from matmul/softmax layers, e.g. ``tests/unittests/dist_transformer.py``)
— this is where the TPU build beats it: VMEM-resident kernels with online
softmax, no [T, T] HBM materialization in forward OR backward.

Kernel set (see /opt/skills/guides/pallas_guide.md):
  * forward: grid (q blocks); K/V streamed in k blocks; running
    (max, sum, acc) online-softmax state; per-key additive bias (the
    padding-mask case), causal masking, and in-kernel dropout on the
    attention weights via the TPU PRNG (pltpu.prng_*), seeded per
    (batch*head, q block, k block) so the backward regenerates identical
    masks.
  * backward: ONE fused kernel (grid over k blocks) producing dK/dV/dB
    per block and accumulating dQ into a revisited full-T VMEM output —
    using the saved row logsumexp and D = rowsum(dO * O), the standard
    flash formulation; probabilities are recomputed per block, never
    stored, and never twice (a separate dQ kernel would redo st and dp
    for every block pair).

CPU/tests: ``mha_reference`` is the numerics oracle; the kernels also run
under ``interpret=True`` for hermetic CI (all paths except dropout, whose
PRNG primitives are TPU-only).
"""

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np

_INTERPRET = False  # tests flip this to run kernels on CPU


def _use_pallas(q):
    if _INTERPRET:
        return True
    from ..core.op_registry import env_flag

    if env_flag("PADDLE_TPU_NO_FLASH"):  # A/B escape hatch
        return False
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    return dev.platform == "tpu"


# ---------------------------------------------------------------------------
# reference (and CPU-fallback) implementation
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, bias=None, causal=False, scale=None,
                  dropout_rate=0.0, rng=None):
    """q,k,v: [B, H, T, D]; bias broadcastable to [B, H, Tq, Tk].
    Dropout (like the kernels) applies to the attention WEIGHTS."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        t_q, t_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
# Pallas TPU kernels
# ---------------------------------------------------------------------------

def _dropout_keep(shape, rate, seed, tags):
    """In-kernel dropout keep-mask from the TPU PRNG. ``tags`` are python/
    traced ints mixed into the seed so every (bh, q block, k block) gets an
    independent, regenerable stream. Tags fold into ONE scalar (multi-
    operand prng_seed hits a Mosaic lowering bug)."""
    from jax.experimental.pallas import tpu as pltpu

    mixed = seed.astype(jnp.int32)
    for mult, tag in zip((1000003, 7919, 104729), tags):
        mixed = mixed + jnp.int32(mult) * jnp.asarray(tag, jnp.int32)
    pltpu.prng_seed(mixed)
    bits = pltpu.prng_random_bits(shape)
    # uniform in [0, 2^23): keep iff below keep_prob * 2^23
    u = jax.lax.bitcast_convert_type(bits, jnp.uint32) & jnp.uint32(0x7FFFFF)
    thresh = jnp.uint32(int((1.0 - rate) * float(1 << 23)))
    return u < thresh


def _kv_mask_lo(num_kb, q_idx, block_q, block_k, kv_len, kv_pad, causal):
    """First k-block index needing a mask, for the forward's k-loop
    split: interior blocks run the lean body; only diagonal blocks
    (causal) and the padded kv tail are masked."""
    mask_lo = num_kb
    if causal:
        # clamp to num_kb: for t_q > t_k the diagonal can lie beyond the
        # last k block, and the lean prefix must never read past kv_pad
        mask_lo = jnp.minimum(num_kb, (q_idx * block_q) // block_k)
    if kv_len < kv_pad:
        mask_lo = jnp.minimum(mask_lo, kv_len // block_k)
    return mask_lo


def _kv_mask(kb, q_idx, block_q, block_k, kv_len, kv_pad, causal):
    """[block_k, block_q] keep-mask for a masked k-block iteration —
    the kv-tail bound and/or the causal triangle (None if neither
    applies)."""
    k_pos = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 0)
    mask = k_pos < kv_len if kv_len < kv_pad else None
    if causal:
        q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1)
        keep = q_pos >= k_pos
        mask = keep if mask is None else mask & keep
    return mask


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, o_ref, lse_ref, *,
                block_k, causal, scale, kv_len, dropout_rate):
    from jax.experimental import pallas as pl

    q = q_ref[...]
    block_q, d = q.shape
    kv_pad = k_ref.shape[0]
    bh_idx = pl.program_id(0)
    q_idx = pl.program_id(1)

    # TRANSPOSED scores [bk, bq] (cf. _dense_fwd_kernel): the online
    # max/sum run over SUBLANES (vreg adds, no cross-lane shuffles) and
    # the running per-query stats are [1, bq] LANE vectors that broadcast
    # for free; the accumulator is kept transposed [d, bq] so its
    # per-iteration rescale is also a lane-broadcast. One [d, bq]
    # transpose per PROGRAM at the end, instead of lane reductions per
    # k-block iteration.
    m_i = jnp.full((1, block_q), -jnp.inf, jnp.float32)
    l_i = jnp.zeros((1, block_q), jnp.float32)
    acc = jnp.zeros((d, block_q), jnp.float32)

    num_kb = kv_pad // block_k
    if causal:
        # blocks strictly above the diagonal are fully masked — skip them
        num_kb = jnp.minimum(
            num_kb, ((q_idx + 1) * q.shape[0] + block_k - 1) // block_k)

    # mask specialization: interior blocks need NO mask at all — only the
    # diagonal block (causal) and the padded kv tail do. The two [bk, bq]
    # iotas + compares + selects per iteration are pure VPU overhead, so
    # the loop is split into an unmasked prefix and a masked remainder.
    kv_partial = kv_len < kv_pad          # static
    mask_lo = _kv_mask_lo(num_kb, q_idx, block_q, block_k, kv_len,
                          kv_pad, causal)

    def make_body(masked):
        def body(kb, carry):
            m_i, l_i, acc = carry
            k = k_ref[pl.dslice(kb * block_k, block_k), :]
            v = v_ref[pl.dslice(kb * block_k, block_k), :]
            st = jax.lax.dot_general(
                k, q, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [bk, bq]
            if bias_ref is not None:
                b = bias_ref[0, pl.dslice(kb * block_k, block_k)]
                st = st + b.astype(jnp.float32)[:, None]
            if masked:
                mask = _kv_mask(kb, q_idx, block_q, block_k, kv_len,
                                kv_pad, causal)
                st = jnp.where(mask, st, -jnp.inf)
            m_new = jnp.maximum(m_i, jnp.max(st, axis=0, keepdims=True))
            m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            # exp(-inf - m_safe) is exactly 0 (m_safe finite), so masked
            # slots vanish without a second select
            p = jnp.exp(st - m_safe)
            alpha = jnp.where(jnp.isfinite(m_i), jnp.exp(m_i - m_safe), 0.0)
            l_new = alpha * l_i + jnp.sum(p, axis=0, keepdims=True)
            p_use = p
            if dropout_rate > 0.0:
                keep = _dropout_keep((block_k, block_q), dropout_rate,
                                     seed_ref[0, 0], (bh_idx, q_idx, kb))
                p_use = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
            acc_new = acc * alpha + jax.lax.dot_general(
                v, p_use.astype(v.dtype), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # [d, bq]
            return m_new, l_new, acc_new
        return body

    carry = (m_i, l_i, acc)
    if causal or kv_partial:
        carry = jax.lax.fori_loop(0, mask_lo, make_body(False), carry)
        carry = jax.lax.fori_loop(mask_lo, num_kb, make_body(True), carry)
    else:
        carry = jax.lax.fori_loop(0, num_kb, make_body(False), carry)
    m_i, l_i, acc = carry
    l_safe = jnp.maximum(l_i, 1e-30)
    o_ref[...] = (acc / l_safe).T.astype(o_ref.dtype)
    # row logsumexp for the backward's prob recomputation; the stats ref
    # holds the FULL row axis (Mosaic-friendly layout), sliced per program
    lse = jnp.where(jnp.isfinite(m_i), m_i + jnp.log(l_safe), -jnp.inf)
    lse_ref[0, pl.dslice(q_idx * block_q, block_q)] = \
        lse[0].astype(jnp.float32)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, db_ref, dq_ref, *,
                    block_q, causal, scale, kv_len, kv_pad, q_len,
                    dropout_rate):
    from jax.experimental import pallas as pl

    k = k_ref[...]
    v = v_ref[...]
    block_k, d = k.shape
    q_pad = q_ref.shape[0]
    bh_idx = pl.program_id(0)
    k_idx = pl.program_id(1)

    # TRANSPOSED scores [bk, bq] (cf. _fwd_kernel): per-query lse/delta
    # broadcast along lanes; the per-key bias-grad reduction rides the
    # MXU as a ones-column dot instead of a per-iteration lane reduce
    bias_blk = None
    if bias_ref is not None:
        bias_blk = bias_ref[0, pl.dslice(k_idx * block_k, block_k)]

    # dQ FUSION: dq accumulates here instead of in a separate kernel
    # that would recompute st and dp per (q, k) block pair (~35% of the
    # backward dots; measured bwd 5.86 -> 4.46 ms at T=2048). The dq
    # output block maps to the SAME full-T buffer for every k_idx
    # (Mosaic output revisiting keeps it VMEM-resident across the k grid
    # for a fixed bh); zero it on the first k step. VMEM note: the f32
    # full-T dq (+ bf16 q/do + stats) bounds the single-chip streaming
    # path at roughly T ~16k for d=64; longer contexts are the
    # sequence-parallel ring's job (parallel/ring_attention.py).
    @pl.when(k_idx == 0)
    def _init_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    # Mask specialization: padded k rows only produce dk/dv/db rows the
    # caller's unpad discards, so no per-iteration kv-tail mask — but a
    # once-per-program [bk, 1] row-validity select keeps them FINITE
    # (exp(st - lse) can overflow to inf for garbage rows, and debug-nans
    # style finiteness checks see the pre-slice kernel outputs). The
    # causal mask applies only to diagonal q blocks (the segment head)
    # and the q-pad mask only to the final q block (the tail) — interior
    # q blocks run the lean body.
    kvalid = None
    if kv_len < kv_pad:                    # static
        kvalid = (k_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < kv_len

    def make_body(masked):
        def body(qb, carry):
            dk, dv, db = carry
            q = q_ref[pl.dslice(qb * block_q, block_q), :]
            do = do_ref[pl.dslice(qb * block_q, block_q), :]
            lse = lse_ref[0, pl.dslice(qb * block_q, block_q)]
            delta = delta_ref[0, pl.dslice(qb * block_q, block_q)]
            st = jax.lax.dot_general(
                k, q, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [bk, bq]
            if bias_blk is not None:
                st = st + bias_blk.astype(jnp.float32)[:, None]
            lse_okf = jnp.isfinite(lse).astype(jnp.float32)
            lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
            p = jnp.exp(st - lse_safe[None, :]) * lse_okf[None, :]
            if kvalid is not None:
                p = jnp.where(kvalid, p, 0.0)   # sublane-broadcast select
            if masked:
                q_pos = qb * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_k, block_q), 1)
                mask = q_pos < q_len if q_len < q_pad else None
                if causal:
                    k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
                        jnp.int32, (block_k, block_q), 0)
                    keep = q_pos >= k_pos
                    mask = keep if mask is None else mask & keep
                if mask is not None:
                    p = jnp.where(mask, p, 0.0)
            dp = jax.lax.dot_general(
                v, do, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [bk, bq]
            p_drop = p
            if dropout_rate > 0.0:
                keep = _dropout_keep((block_k, block_q), dropout_rate,
                                     seed_ref[0, 0], (bh_idx, qb, k_idx))
                inv = 1.0 / (1.0 - dropout_rate)
                p_drop = jnp.where(keep, p * inv, 0.0)
                dp = jnp.where(keep, dp * inv, 0.0)
            ds = p * (dp - delta[None, :])  # [bk, bq]
            # bf16 operands on the transposed contractions: the MXU runs
            # f32 dots at a fraction of its bf16 rate
            dv = dv + jax.lax.dot_general(
                p_drop.astype(v.dtype), do.astype(v.dtype),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # [bk, d]
            dk = dk + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if db is not None:
                db = db + jax.lax.dot_general(
                    ds, jnp.ones((1, block_q), jnp.float32),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [bk, 1]
            # dq[qb] += ds^T k (contract bk; masked/padded k rows have
            # ds == 0, so no kv mask is needed here)
            sl = pl.dslice(qb * block_q, block_q)
            dq_ref[sl, :] += jax.lax.dot_general(
                ds.astype(k.dtype), k, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            return dk, dv, db
        return body

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    db0 = (jnp.zeros((block_k, 1), jnp.float32)
           if db_ref is not None else None)
    qb_end = q_pad // block_q
    qb_lo = (k_idx * block_k) // block_q if causal else 0
    carry = (dk0, dv0, db0)
    q_partial = q_len < q_pad             # static
    if causal or q_partial:
        # segment head: diagonal (causal) blocks, masked
        if causal:
            first_full = (k_idx * block_k + block_k - 1
                          + block_q - 1) // block_q
            a_hi = jnp.minimum(first_full, qb_end)
        else:
            a_hi = qb_lo
        # segment middle: lean; segment tail: q-padded block(s), masked
        pad_lo = (q_len // block_q) if q_partial else qb_end
        b_hi = jnp.maximum(a_hi, jnp.minimum(pad_lo, qb_end))
        carry = jax.lax.fori_loop(qb_lo, a_hi, make_body(True), carry)
        carry = jax.lax.fori_loop(a_hi, b_hi, make_body(False), carry)
        carry = jax.lax.fori_loop(b_hi, qb_end, make_body(True), carry)
    else:
        carry = jax.lax.fori_loop(qb_lo, qb_end, make_body(False), carry)
    dk, dv, db = carry
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)
    if db_ref is not None:
        db_ref[0, pl.dslice(k_idx * block_k, block_k)] = \
            db[:, 0].astype(db_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call drivers — [BH, T, D] layout, one program per (bh, block)
# ---------------------------------------------------------------------------

def _pad_t(x, m):
    r = (-x.shape[1]) % m
    return jnp.pad(x, ((0, 0), (0, r), (0, 0))) if r else x


def _pad_vec(x, m):
    r = (-x.shape[1]) % m
    return jnp.pad(x, ((0, 0), (0, r))) if r else x


def _block_sizes(t, t_k, bwd=False):
    """Mosaic wants the lane (last) dim of 1-D stats blocks divisible by
    128, so real-TPU blocks are 128-multiples; interpret mode uses
    8-multiples to exercise the padded-edge logic cheaply.
    PADDLE_TPU_FLASH_BLOCK (and _BWD for the backward kernels) override
    the default caps (A/B knobs). NOTE: the _BWD override only engages
    when dropout is OFF — dropout masks regenerate per (bh, q-block,
    k-block) tile, so fwd and bwd must share block geometry. 512 is the
    measured sweet spot at T=2048 for BOTH directions
    (tools/attn_device_time.py: fwd 4.46 -> 2.18 ms vs 256-blocks, bwd
    8.76 -> 5.86; 128 is 2.5x worse, 1024 regresses bwd) — bigger
    blocks amortize the per-iteration MXU/VPU serialization across 4x
    the elements."""
    m = 8 if _INTERPRET else 128
    default = 64 if _INTERPRET else 512   # small interpret cap keeps the
    try:                                  # multi-block paths exercised
        cap = int(os.environ.get("PADDLE_TPU_FLASH_BLOCK", default))
        if bwd:
            cap = int(os.environ.get("PADDLE_TPU_FLASH_BLOCK_BWD", cap))
    except ValueError:
        raise ValueError("PADDLE_TPU_FLASH_BLOCK(_BWD) must be integers")

    def r(x):
        return ((x + m - 1) // m) * m

    cap = max(m, r(cap) if cap % m else cap)  # Mosaic lane divisibility
    return min(cap, r(t)), min(cap, r(t_k))


def _flash_fwd_impl(q, k, v, bias, seed, causal, scale, dropout_rate):
    """q,k,v: [BH, T, D]; bias [BH, Tk] additive per-key or None.
    Returns (out [BH, T, D], lse [BH, T])."""
    from jax.experimental import pallas as pl

    bh, t, d = q.shape
    t_k = k.shape[1]
    block_q, block_k = _block_sizes(t, t_k)
    qp, kp, vp = _pad_t(q, block_q), _pad_t(k, block_k), _pad_t(v, block_k)
    t_pad, tk_pad = qp.shape[1], kp.shape[1]

    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, scale=scale,
        kv_len=t_k, dropout_rate=dropout_rate)
    in_specs = [
        pl.BlockSpec((None, block_q, d), lambda b, qi: (b, qi, 0)),
        pl.BlockSpec((None, tk_pad, d), lambda b, qi: (b, 0, 0)),
        pl.BlockSpec((None, tk_pad, d), lambda b, qi: (b, 0, 0)),
    ]
    args = [qp, kp, vp]
    if bias is not None:
        in_specs.append(pl.BlockSpec((None, 8, tk_pad),
                                     lambda b, qi: (b, 0, 0)))
        bp = _pad_vec(bias, block_k)
        args.append(jnp.broadcast_to(bp[:, None, :], (bh, 8, tk_pad)))
    in_specs.append(pl.BlockSpec((1, 1), lambda b, qi: (0, 0)))
    args.append(jnp.asarray([[seed]], jnp.uint32))

    def kernel_entry(*refs):
        if bias is not None:
            q_ref, k_ref, v_ref, b_ref, s_ref, o_ref, l_ref = refs
        else:
            q_ref, k_ref, v_ref, s_ref, o_ref, l_ref = refs
            b_ref = None
        kernel(q_ref, k_ref, v_ref, b_ref, s_ref, o_ref, l_ref)

    out, lse = pl.pallas_call(
        kernel_entry,
        grid=(bh, t_pad // block_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, qi: (b, qi, 0)),
            # stats ride an 8-row sublane-padded block (Mosaic disallows
            # 1-D effective blocks); row 0 is the data
            pl.BlockSpec((None, 8, t_pad), lambda b, qi: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, t_pad), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(*args)
    return out[:, :t], lse[:, 0, :t]


def _flash_bwd_impl(q, k, v, bias, seed, causal, scale, dropout_rate,
                    out, lse, do):
    from jax.experimental import pallas as pl

    bh, t, d = q.shape
    t_k = k.shape[1]
    # dropout masks regenerate per (bh, q-block, k-block) tile: the bwd
    # may only use different block sizes than fwd when dropout is off
    block_q, block_k = _block_sizes(t, t_k,
                                    bwd=(dropout_rate == 0.0))
    qp, kp, vp = _pad_t(q, block_q), _pad_t(k, block_k), _pad_t(v, block_k)
    dop = _pad_t(do, block_q)
    t_pad, tk_pad = qp.shape[1], kp.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # [BH, T]

    def pad8(x):  # [BH, T] -> [BH, 8, T_pad] sublane-padded stats block
        xp = _pad_vec(x, block_q)
        return jnp.broadcast_to(xp[:, None, :], (bh, 8, xp.shape[1]))

    lsep = pad8(lse)
    deltap = pad8(delta)
    if bias is not None:
        bp = _pad_vec(bias, block_k)
        biasp = jnp.broadcast_to(bp[:, None, :], (bh, 8, bp.shape[1]))
    else:
        biasp = None
    seed_arr = jnp.asarray([[seed]], jnp.uint32)

    # one fused kernel: grid over k blocks produces dK/dV/(dB) per block
    # AND accumulates dQ into a revisited full-T output (no separate dQ
    # kernel recomputing st/dp — see _bwd_dkv_kernel)
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, block_q=block_q, causal=causal, scale=scale,
        kv_len=t_k, kv_pad=tk_pad, q_len=t, dropout_rate=dropout_rate)

    def dkv_entry(*refs):
        if biasp is not None:
            (q_ref, k_ref, v_ref, b_ref, s_ref, do_ref, l_ref, de_ref,
             dk_ref, dv_ref, db_ref, dq_ref) = refs
        else:
            (q_ref, k_ref, v_ref, s_ref, do_ref, l_ref, de_ref,
             dk_ref, dv_ref, dq_ref) = refs
            b_ref = db_ref = None
        dkv_kernel(q_ref, k_ref, v_ref, b_ref, s_ref, do_ref, l_ref,
                   de_ref, dk_ref, dv_ref, db_ref, dq_ref)

    in_specs2 = [
        pl.BlockSpec((None, t_pad, d), lambda b, ki: (b, 0, 0)),
        pl.BlockSpec((None, block_k, d), lambda b, ki: (b, ki, 0)),
        pl.BlockSpec((None, block_k, d), lambda b, ki: (b, ki, 0)),
    ]
    args2 = [qp, kp, vp]
    if biasp is not None:
        in_specs2.append(pl.BlockSpec((None, 8, tk_pad),
                                      lambda b, ki: (b, 0, 0)))
        args2.append(biasp)
    in_specs2.append(pl.BlockSpec((1, 1), lambda b, ki: (0, 0)))
    args2.append(seed_arr)
    in_specs2 += [
        pl.BlockSpec((None, t_pad, d), lambda b, ki: (b, 0, 0)),
        pl.BlockSpec((None, 8, t_pad), lambda b, ki: (b, 0, 0)),
        pl.BlockSpec((None, 8, t_pad), lambda b, ki: (b, 0, 0)),
    ]
    args2 += [dop, lsep, deltap]
    out_specs2 = [
        pl.BlockSpec((None, block_k, d), lambda b, ki: (b, ki, 0)),
        pl.BlockSpec((None, block_k, d), lambda b, ki: (b, ki, 0)),
    ]
    out_shape2 = [
        jax.ShapeDtypeStruct((bh, tk_pad, d), k.dtype),
        jax.ShapeDtypeStruct((bh, tk_pad, d), v.dtype),
    ]
    if biasp is not None:
        out_specs2.append(pl.BlockSpec((None, 8, tk_pad),
                                       lambda b, ki: (b, 0, 0)))
        out_shape2.append(jax.ShapeDtypeStruct((bh, 8, tk_pad),
                                               jnp.float32))
    # dq: full-T f32 accumulator, SAME block for every k step (Mosaic
    # revisiting — written back once per bh)
    out_specs2.append(pl.BlockSpec((None, t_pad, d),
                                   lambda b, ki: (b, 0, 0)))
    out_shape2.append(jax.ShapeDtypeStruct((bh, t_pad, d), jnp.float32))
    res = pl.pallas_call(
        dkv_entry,
        grid=(bh, tk_pad // block_k),
        in_specs=in_specs2,
        out_specs=out_specs2,
        out_shape=out_shape2,
        interpret=_INTERPRET,
    )(*args2)
    if biasp is not None:
        dk, dv, db, dq = res
        db = db[:, 0, :t_k]
    else:
        dk, dv, dq = res
        db = None
    return dq[:, :t].astype(q.dtype), dk[:, :t_k], dv[:, :t_k], db


# ---------------------------------------------------------------------------
# packed STREAMING kernels — [B, T, H*D] layout, heads looped in-kernel
# ---------------------------------------------------------------------------
#
# The head-split streaming path below reshapes [B,T,H*D] -> [B*H,T,D] around
# the custom calls, and XLA materializes those relayouts as real HBM copies
# (~36 ms/step at the seq-2048 bench config — NOTES_r5.md; 7 copies per
# attention site). These kernels keep the packed layout the projection
# matmuls produce END TO END: the grid stays (batch, block), each program
# loops the heads over static lane slices (like the dense kernels), and the
# online-softmax k-loop streams K/V blocks exactly as the head-split
# kernels do. The price is VMEM: K/V (fwd) and q/do/dq-f32 (bwd) are
# full-T refs of width H*D rather than D, which caps the single-chip
# packed path near T~2-3k for transformer-base — precisely the bench
# regime; longer contexts keep the head-split path (gate:
# _packed_stream_fits; PADDLE_TPU_SPLIT_STREAM=1 forces the old path for
# A/B).

def _packed_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, o_ref,
                       lse_ref, *, num_heads, block_k, causal, scale,
                       kv_len, dropout_rate):
    from jax.experimental import pallas as pl

    block_q, hd = q_ref.shape
    d = hd // num_heads
    kv_pad = k_ref.shape[0]
    b_idx = pl.program_id(0)
    q_idx = pl.program_id(1)

    num_kb = kv_pad // block_k
    if causal:
        num_kb = jnp.minimum(
            num_kb, ((q_idx + 1) * block_q + block_k - 1) // block_k)
    kv_partial = kv_len < kv_pad
    mask_lo = _kv_mask_lo(num_kb, q_idx, block_q, block_k, kv_len,
                          kv_pad, causal)

    for h in range(num_heads):
        sl = pl.dslice(h * d, d)
        q = q_ref[:, sl]
        # same transposed-scores online softmax as _fwd_kernel, with K/V
        # loads lane-sliced to this head's columns (no HBM relayout)
        m_i = jnp.full((1, block_q), -jnp.inf, jnp.float32)
        l_i = jnp.zeros((1, block_q), jnp.float32)
        acc = jnp.zeros((d, block_q), jnp.float32)

        def make_body(masked):
            def body(kb, carry):
                m_i, l_i, acc = carry
                ksl = pl.dslice(kb * block_k, block_k)
                k = k_ref[ksl, sl]
                v = v_ref[ksl, sl]
                st = jax.lax.dot_general(
                    k, q, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                if bias_ref is not None:
                    bb = bias_ref[0, ksl]
                    st = st + bb.astype(jnp.float32)[:, None]
                if masked:
                    mask = _kv_mask(kb, q_idx, block_q, block_k, kv_len,
                                    kv_pad, causal)
                    st = jnp.where(mask, st, -jnp.inf)
                m_new = jnp.maximum(m_i, jnp.max(st, axis=0, keepdims=True))
                m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
                p = jnp.exp(st - m_safe)
                alpha = jnp.where(jnp.isfinite(m_i),
                                  jnp.exp(m_i - m_safe), 0.0)
                l_new = alpha * l_i + jnp.sum(p, axis=0, keepdims=True)
                p_use = p
                if dropout_rate > 0.0:
                    keep = _dropout_keep(
                        (block_k, block_q), dropout_rate, seed_ref[0, 0],
                        (b_idx * num_heads + h, q_idx, kb))
                    p_use = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
                acc_new = acc * alpha + jax.lax.dot_general(
                    v, p_use.astype(v.dtype), (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return m_new, l_new, acc_new
            return body

        carry = (m_i, l_i, acc)
        if causal or kv_partial:
            carry = jax.lax.fori_loop(0, mask_lo, make_body(False), carry)
            carry = jax.lax.fori_loop(mask_lo, num_kb, make_body(True),
                                      carry)
        else:
            carry = jax.lax.fori_loop(0, num_kb, make_body(False), carry)
        m_i, l_i, acc = carry
        l_safe = jnp.maximum(l_i, 1e-30)
        o_ref[:, sl] = (acc / l_safe).T.astype(o_ref.dtype)
        lse = jnp.where(jnp.isfinite(m_i), m_i + jnp.log(l_safe), -jnp.inf)
        lse_ref[h, pl.dslice(q_idx * block_q, block_q)] = \
            lse[0].astype(jnp.float32)


def _packed_bwd_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, do_ref,
                       lse_ref, delta_ref, dk_ref, dv_ref, db_ref, dq_ref,
                       *, num_heads, block_q, causal, scale, kv_len, kv_pad,
                       q_len, dropout_rate):
    from jax.experimental import pallas as pl

    block_k, hd = k_ref.shape
    d = hd // num_heads
    q_pad = q_ref.shape[0]
    b_idx = pl.program_id(0)
    k_idx = pl.program_id(1)

    bias_blk = None
    if bias_ref is not None:
        bias_blk = bias_ref[0, pl.dslice(k_idx * block_k, block_k)]

    # dq accumulates into the SAME revisited full-T packed buffer for
    # every k step (cf. _bwd_dkv_kernel); zero it on the first
    @pl.when(k_idx == 0)
    def _init_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    kvalid = None
    if kv_len < kv_pad:
        kvalid = (k_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < kv_len

    qb_end = q_pad // block_q
    qb_lo = (k_idx * block_k) // block_q if causal else 0
    q_partial = q_len < q_pad
    db_total = (jnp.zeros((block_k, 1), jnp.float32)
                if db_ref is not None else None)

    for h in range(num_heads):
        sl = pl.dslice(h * d, d)
        k = k_ref[:, sl]
        v = v_ref[:, sl]

        def make_body(masked):
            def body(qb, carry):
                dk, dv, db = carry
                qsl = pl.dslice(qb * block_q, block_q)
                q = q_ref[qsl, sl]
                do = do_ref[qsl, sl]
                lse = lse_ref[h, qsl]
                delta = delta_ref[h, qsl]
                st = jax.lax.dot_general(
                    k, q, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                if bias_blk is not None:
                    st = st + bias_blk.astype(jnp.float32)[:, None]
                lse_okf = jnp.isfinite(lse).astype(jnp.float32)
                lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
                p = jnp.exp(st - lse_safe[None, :]) * lse_okf[None, :]
                if kvalid is not None:
                    p = jnp.where(kvalid, p, 0.0)
                if masked:
                    q_pos = qb * block_q + jax.lax.broadcasted_iota(
                        jnp.int32, (block_k, block_q), 1)
                    mask = q_pos < q_len if q_len < q_pad else None
                    if causal:
                        k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
                            jnp.int32, (block_k, block_q), 0)
                        keep = q_pos >= k_pos
                        mask = keep if mask is None else mask & keep
                    if mask is not None:
                        p = jnp.where(mask, p, 0.0)
                dp = jax.lax.dot_general(
                    v, do, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                p_drop = p
                if dropout_rate > 0.0:
                    keep = _dropout_keep(
                        (block_k, block_q), dropout_rate, seed_ref[0, 0],
                        (b_idx * num_heads + h, qb, k_idx))
                    inv = 1.0 / (1.0 - dropout_rate)
                    p_drop = jnp.where(keep, p * inv, 0.0)
                    dp = jnp.where(keep, dp * inv, 0.0)
                ds = p * (dp - delta[None, :])
                dv = dv + jax.lax.dot_general(
                    p_drop.astype(v.dtype), do.astype(v.dtype),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                dk = dk + jax.lax.dot_general(
                    ds.astype(q.dtype), q, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                if db is not None:
                    db = db + jax.lax.dot_general(
                        ds, jnp.ones((1, block_q), jnp.float32),
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
                dq_ref[qsl, sl] += jax.lax.dot_general(
                    ds.astype(k.dtype), k, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32) * scale
                return dk, dv, db
            return body

        carry = (jnp.zeros((block_k, d), jnp.float32),
                 jnp.zeros((block_k, d), jnp.float32),
                 jnp.zeros((block_k, 1), jnp.float32)
                 if db_ref is not None else None)
        if causal or q_partial:
            if causal:
                first_full = (k_idx * block_k + block_k - 1
                              + block_q - 1) // block_q
                a_hi = jnp.minimum(first_full, qb_end)
            else:
                a_hi = qb_lo
            pad_lo = (q_len // block_q) if q_partial else qb_end
            b_hi = jnp.maximum(a_hi, jnp.minimum(pad_lo, qb_end))
            carry = jax.lax.fori_loop(qb_lo, a_hi, make_body(True), carry)
            carry = jax.lax.fori_loop(a_hi, b_hi, make_body(False), carry)
            carry = jax.lax.fori_loop(b_hi, qb_end, make_body(True), carry)
        else:
            carry = jax.lax.fori_loop(qb_lo, qb_end, make_body(False),
                                      carry)
        dk, dv, db = carry
        dk_ref[:, sl] = dk.astype(dk_ref.dtype)
        dv_ref[:, sl] = dv.astype(dv_ref.dtype)
        if db_total is not None:
            db_total = db_total + db  # bias is shared across heads
    if db_ref is not None:
        db_ref[0, pl.dslice(k_idx * block_k, block_k)] = \
            db_total[:, 0].astype(db_ref.dtype)


def _packed_stream_fwd_impl(q, k, v, bias, seed, num_heads, causal, scale,
                            dropout_rate):
    """q,k,v: packed [B, T, H*D]; bias [B, Tk] or None.
    Returns (out [B, T, H*D], lse [B, nh_pad, T])."""
    from jax.experimental import pallas as pl

    b, t, hd = q.shape
    t_k = k.shape[1]
    block_q, block_k = _block_sizes(t, t_k)
    qp, kp, vp = _pad_t(q, block_q), _pad_t(k, block_k), _pad_t(v, block_k)
    t_pad, tk_pad = qp.shape[1], kp.shape[1]
    nh_pad = max(num_heads, 8)

    kernel = functools.partial(
        _packed_fwd_kernel, num_heads=num_heads, block_k=block_k,
        causal=causal, scale=scale, kv_len=t_k, dropout_rate=dropout_rate)
    in_specs = [
        pl.BlockSpec((None, block_q, hd), lambda b, qi: (b, qi, 0)),
        pl.BlockSpec((None, tk_pad, hd), lambda b, qi: (b, 0, 0)),
        pl.BlockSpec((None, tk_pad, hd), lambda b, qi: (b, 0, 0)),
    ]
    args = [qp, kp, vp]
    if bias is not None:
        in_specs.append(pl.BlockSpec((None, 8, tk_pad),
                                     lambda b, qi: (b, 0, 0)))
        bp = _pad_vec(bias, block_k)
        args.append(jnp.broadcast_to(bp[:, None, :], (b, 8, tk_pad)))
    in_specs.append(pl.BlockSpec((1, 1), lambda b, qi: (0, 0)))
    args.append(jnp.asarray([[seed]], jnp.uint32))

    def entry(*refs):
        if bias is not None:
            q_ref, k_ref, v_ref, b_ref, s_ref, o_ref, l_ref = refs
        else:
            q_ref, k_ref, v_ref, s_ref, o_ref, l_ref = refs
            b_ref = None
        kernel(q_ref, k_ref, v_ref, b_ref, s_ref, o_ref, l_ref)

    out, lse = pl.pallas_call(
        entry,
        grid=(b, t_pad // block_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, hd), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((None, nh_pad, t_pad), lambda b, qi: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t_pad, hd), q.dtype),
            jax.ShapeDtypeStruct((b, nh_pad, t_pad), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(*args)
    return out[:, :t], lse[:, :, :t]


def _packed_stream_bwd_impl(q, k, v, bias, seed, num_heads, causal, scale,
                            dropout_rate, out, lse, do):
    from jax.experimental import pallas as pl

    b, t, hd = q.shape
    t_k = k.shape[1]
    d = hd // num_heads
    block_q, block_k = _block_sizes(t, t_k, bwd=(dropout_rate == 0.0))
    qp, kp, vp = _pad_t(q, block_q), _pad_t(k, block_k), _pad_t(v, block_k)
    dop = _pad_t(do, block_q)
    t_pad, tk_pad = qp.shape[1], kp.shape[1]
    nh_pad = lse.shape[1]
    # per-(b, h, t) delta = rowsum_d(do * o) over this head's lanes; the
    # [B,T,H] reduce + transpose is tiny next to the old full [B,T,H,D]
    # relayouts
    prod = jnp.sum(
        (do.astype(jnp.float32) * out.astype(jnp.float32)).reshape(
            b, t, num_heads, d), axis=-1)
    delta = prod.transpose(0, 2, 1)  # [B, H, T]

    def pad_stats(x):  # [B, nh?, T] -> [B, nh_pad, T_pad]
        if x.shape[1] < nh_pad:
            x = jnp.pad(x, ((0, 0), (0, nh_pad - x.shape[1]), (0, 0)))
        r = (-x.shape[2]) % block_q
        return jnp.pad(x, ((0, 0), (0, 0), (0, r))) if r else x

    lsep = pad_stats(lse)
    deltap = pad_stats(delta)
    if bias is not None:
        bp = _pad_vec(bias, block_k)
        biasp = jnp.broadcast_to(bp[:, None, :], (b, 8, bp.shape[1]))
    else:
        biasp = None

    kernel = functools.partial(
        _packed_bwd_kernel, num_heads=num_heads, block_q=block_q,
        causal=causal, scale=scale, kv_len=t_k, kv_pad=tk_pad, q_len=t,
        dropout_rate=dropout_rate)

    def entry(*refs):
        if biasp is not None:
            (q_ref, k_ref, v_ref, b_ref, s_ref, do_ref, l_ref, de_ref,
             dk_ref, dv_ref, db_ref, dq_ref) = refs
        else:
            (q_ref, k_ref, v_ref, s_ref, do_ref, l_ref, de_ref,
             dk_ref, dv_ref, dq_ref) = refs
            b_ref = db_ref = None
        kernel(q_ref, k_ref, v_ref, b_ref, s_ref, do_ref, l_ref, de_ref,
               dk_ref, dv_ref, db_ref, dq_ref)

    in_specs = [
        pl.BlockSpec((None, t_pad, hd), lambda b, ki: (b, 0, 0)),
        pl.BlockSpec((None, block_k, hd), lambda b, ki: (b, ki, 0)),
        pl.BlockSpec((None, block_k, hd), lambda b, ki: (b, ki, 0)),
    ]
    args = [qp, kp, vp]
    if biasp is not None:
        in_specs.append(pl.BlockSpec((None, 8, tk_pad),
                                     lambda b, ki: (b, 0, 0)))
        args.append(biasp)
    in_specs.append(pl.BlockSpec((1, 1), lambda b, ki: (0, 0)))
    args.append(jnp.asarray([[seed]], jnp.uint32))
    in_specs += [
        pl.BlockSpec((None, t_pad, hd), lambda b, ki: (b, 0, 0)),
        pl.BlockSpec((None, nh_pad, t_pad), lambda b, ki: (b, 0, 0)),
        pl.BlockSpec((None, nh_pad, t_pad), lambda b, ki: (b, 0, 0)),
    ]
    args += [dop, lsep, deltap]
    out_specs = [
        pl.BlockSpec((None, block_k, hd), lambda b, ki: (b, ki, 0)),
        pl.BlockSpec((None, block_k, hd), lambda b, ki: (b, ki, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b, tk_pad, hd), k.dtype),
        jax.ShapeDtypeStruct((b, tk_pad, hd), v.dtype),
    ]
    if biasp is not None:
        out_specs.append(pl.BlockSpec((None, 8, tk_pad),
                                      lambda b, ki: (b, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b, 8, tk_pad), jnp.float32))
    out_specs.append(pl.BlockSpec((None, t_pad, hd),
                                  lambda b, ki: (b, 0, 0)))
    out_shape.append(jax.ShapeDtypeStruct((b, t_pad, hd), jnp.float32))
    res = pl.pallas_call(
        entry,
        grid=(b, tk_pad // block_k),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_INTERPRET,
    )(*args)
    if biasp is not None:
        dk, dv, db, dq = res
        db = db[:, 0, :t_k]
    else:
        dk, dv, dq = res
        db = None
    return dq[:, :t].astype(q.dtype), dk[:, :t_k], dv[:, :t_k], db


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _packed_stream_attention(q, k, v, bias, seed, num_heads, causal, scale,
                             dropout_rate):
    out, _ = _packed_stream_fwd_impl(q, k, v, bias, seed, num_heads, causal,
                                     scale, dropout_rate)
    return out


def _packed_stream_fwd(q, k, v, bias, seed, num_heads, causal, scale,
                       dropout_rate):
    out, lse = _packed_stream_fwd_impl(q, k, v, bias, seed, num_heads,
                                       causal, scale, dropout_rate)
    return out, (q, k, v, bias, seed, out, lse)


def _packed_stream_bwd(num_heads, causal, scale, dropout_rate, res, g):
    q, k, v, bias, seed, out, lse = res
    dq, dk, dv, db = _packed_stream_bwd_impl(
        q, k, v, bias, seed, num_heads, causal, scale, dropout_rate, out,
        lse, g)
    dbias = db.astype(bias.dtype) if bias is not None else None
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias, None)


_packed_stream_attention.defvjp(_packed_stream_fwd, _packed_stream_bwd)

_PACKED_STREAM = True  # module A/B switch (tests also flip it)
# ~16 MB VMEM/core; the estimate below is conservative already (the
# revisited dq and the constant-index q/do/K/V refs are NOT
# double-buffered by Mosaic), so leave only headroom for transients.
# bf16 seq-2048 transformer-base lands at ~12.8 MB — inside the gate by
# design (that bench config is what this path exists for).
_STREAM_VMEM_BUDGET = 13 * 1024 * 1024


def _packed_stream_fits(t, t_k, hd, esize, num_heads, dropout=0.0):
    """Conservative VMEM bound for the packed streaming kernels: the bwd
    is the larger step — full-T q/do (+f32 dq accumulator) plus the
    double-buffered K/V/dK/dV blocks and the stats rows. The bwd estimate
    uses the geometry the backward will ACTUALLY allocate: the
    PADDLE_TPU_FLASH_BLOCK_BWD override engages only when dropout is off
    (fwd/bwd must share block geometry for mask regeneration), so the
    gate mirrors _packed_stream_bwd_impl's ``bwd=(dropout == 0.0)``."""
    block_q, block_k = _block_sizes(t, t_k)
    bq_b, bk_b = _block_sizes(t, t_k, bwd=(dropout == 0.0))
    nh_pad = max(num_heads, 8)

    def pad(x, m):
        return ((x + m - 1) // m) * m

    fwd = (2 * pad(t_k, block_k) * hd * esize   # K/V resident
           + 4 * block_q * hd * esize           # q/o double-buffered
           + nh_pad * pad(t, block_q) * 4)
    t_pad_b = pad(t, bq_b)
    bwd = (2 * t_pad_b * hd * esize             # q/do resident
           + t_pad_b * hd * 4                   # f32 dq accumulator
           + 8 * bk_b * hd * esize              # k/v/dk/dv double-buffered
           + 3 * nh_pad * t_pad_b * 4)
    return max(fwd, bwd) <= _STREAM_VMEM_BUDGET


# ---------------------------------------------------------------------------
# dense short-sequence kernels — packed [B, T, H*D] layout, whole-sequence
# blocks resident in VMEM
# ---------------------------------------------------------------------------
#
# For t_k up to ~1k the per-head problem fits VMEM outright, so the online-
# softmax streaming machinery above only adds grid/loop overhead (profiled at
# ~5% MXU on transformer-base T=256), and the [B,T,H*D]->[B*H,T,D] head split
# forces XLA transpose copies around the custom call (~7 per attention site).
# These kernels instead take the packed layout the projection matmuls
# naturally produce, loop the heads inside one grid step (static lane slices,
# no HBM relayout), and compute softmax in one shot per head. One grid step
# per batch element amortizes grid overhead ~H*n_block times better.

def _dense_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, o_ref,
                      lse_ref, *, num_heads, causal, scale, q_len, kv_len,
                      dropout_rate):
    g_blk, t_pad, hd = q_ref.shape
    tk_pad = k_ref.shape[1]
    d = hd // num_heads
    from jax.experimental import pallas as pl

    b_idx = pl.program_id(0)
    # TRANSPOSED scores [tk, t]: the softmax axis becomes the SUBLANE axis,
    # so max/sum are vreg adds instead of cross-lane shuffle reductions
    # (measured: reductions were ~0.28 ms of a 0.52 ms call in [t, tk]
    # layout). One additive mask tile per grid step, hoisted out of the
    # (g, h) loops: exp(-1e30 - m) underflows to exactly 0, so no per-head
    # compare+select passes. do/q are zero-padded, so padded q rows produce
    # ds == 0 in the backward and only garbage in discarded output rows.
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (tk_pad, t_pad), 0)
    q_pos = jax.lax.broadcasted_iota(jnp.int32, (tk_pad, t_pad), 1)
    mask = k_pos < kv_len
    if causal:
        # end-anchored diagonal (matches mha_reference for t_q != t_k)
        mask = mask & (k_pos <= q_pos + (kv_len - q_len))
    mask = jnp.where(mask, 0.0, -1e30)

    # several batch elements per grid step: at T<=512 one element is only
    # a few us of compute, so the per-step fixed cost (DMA issue, loop
    # bookkeeping) dominates a G=1 grid (measured flat 5.5us/step
    # regardless of in-kernel math, NOTES_r3.md)
    for g in range(g_blk):
        mb = mask
        if bias_ref is not None:
            mb = mb + bias_ref[g, 0, :].astype(jnp.float32)[:, None]
        for h in range(num_heads):
            sl = pl.dslice(h * d, d)
            qh = q_ref[g, :, sl]
            kh = k_ref[g, :, sl]
            vh = v_ref[g, :, sl]
            st = jax.lax.dot_general(
                kh, qh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale + mb  # [tk, t]
            m = jnp.max(st, axis=0)
            m_safe = jnp.maximum(m, -1e30)  # fully-masked rows: exp -> 0
            p = jnp.exp(st - m_safe[None, :])
            l = jnp.maximum(jnp.sum(p, axis=0), 1e-30)
            p_use = p * (1.0 / l)[None, :]  # lane-broadcast normalize
            if dropout_rate > 0.0:
                keep = _dropout_keep(
                    (tk_pad, t_pad), dropout_rate, seed_ref[0, 0],
                    ((b_idx * g_blk + g) * num_heads + h, 0, 0))
                p_use = jnp.where(keep, p_use / (1.0 - dropout_rate), 0.0)
            o_h = jax.lax.dot_general(
                p_use.astype(vh.dtype), vh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            o_ref[g, :, sl] = o_h.astype(o_ref.dtype)
            lse_ref[g, h, :] = (m_safe + jnp.log(l)).astype(jnp.float32)


def _dense_bwd_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, do_ref,
                      out_ref, lse_ref, dq_ref, dk_ref, dv_ref, db_ref, *,
                      num_heads, causal, scale, q_len, kv_len, dropout_rate):
    g_blk, t_pad, hd = q_ref.shape
    tk_pad = k_ref.shape[1]
    d = hd // num_heads
    from jax.experimental import pallas as pl

    b_idx = pl.program_id(0)
    # TRANSPOSED scores [tk, t] (matches _dense_fwd_kernel, so dropout
    # masks regenerate in the same layout and lse/delta broadcast along
    # LANES); additive mask+bias tile hoisted; lse is always finite here
    # by the fwd's m_safe clamp
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (tk_pad, t_pad), 0)
    q_pos = jax.lax.broadcasted_iota(jnp.int32, (tk_pad, t_pad), 1)
    mask = k_pos < kv_len
    if causal:
        mask = mask & (k_pos <= q_pos + (kv_len - q_len))
    mask = jnp.where(mask, 0.0, -1e30)

    for g in range(g_blk):
        mb = mask
        if bias_ref is not None:
            mb = mb + bias_ref[g, 0, :].astype(jnp.float32)[:, None]
        db_acc = (jnp.zeros((1, tk_pad), jnp.float32)
                  if db_ref is not None else None)
        for h in range(num_heads):
            sl = pl.dslice(h * d, d)
            qh = q_ref[g, :, sl]
            kh = k_ref[g, :, sl]
            vh = v_ref[g, :, sl]
            do = do_ref[g, :, sl]
            o = out_ref[g, :, sl]
            lse = lse_ref[g, h, :]
            delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                            axis=1)  # [t]
            st = jax.lax.dot_general(
                kh, qh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale + mb  # [tk, t]
            p = jnp.exp(st - lse[None, :])
            dp = jax.lax.dot_general(
                vh, do, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [tk, t]
            p_drop = p
            if dropout_rate > 0.0:
                keep = _dropout_keep(
                    (tk_pad, t_pad), dropout_rate, seed_ref[0, 0],
                    ((b_idx * g_blk + g) * num_heads + h, 0, 0))
                inv = 1.0 / (1.0 - dropout_rate)
                p_drop = jnp.where(keep, p * inv, 0.0)
                dp = jnp.where(keep, dp * inv, 0.0)
            ds_f32 = p * (dp - delta[None, :])  # [tk, t]
            ds = ds_f32.astype(qh.dtype)
            dq_ref[g, :, sl] = (jax.lax.dot_general(
                ds, kh, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
                * scale).astype(dq_ref.dtype)
            # bf16 operands on the transposed contractions too: the MXU
            # runs f32 dots at a fraction of its bf16 rate, and the
            # f32->bf16 cast is the same rounding the fwd products see
            dk_ref[g, :, sl] = (jax.lax.dot_general(
                ds, qh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
                * scale).astype(dk_ref.dtype)
            dv_ref[g, :, sl] = jax.lax.dot_general(
                p_drop.astype(vh.dtype), do, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(dv_ref.dtype)
            if db_acc is not None:
                # sum over queries is a LANE reduction in this layout;
                # run it as ones[1,t] x ds^T on the MXU instead
                db_acc = db_acc + jax.lax.dot_general(
                    jnp.ones((1, t_pad), jnp.float32), ds_f32,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)  # [1, tk]
        if db_ref is not None:
            db_ref[g, 0, :] = db_acc[0]


def _pad_last(x, m):
    r = (-x.shape[1]) % m
    return jnp.pad(x, ((0, 0), (0, r), (0, 0))) if r else x


def _pick_g(b, per_elem_bytes, budget=4 * 1024 * 1024):
    """Batch elements per grid step: enough to amortize the ~5.5us fixed
    per-step cost, bounded by the VMEM block budget (blocks are double-
    buffered across grid steps, so they cost twice their size)."""
    for g in (8, 4, 2, 1):
        if b % g == 0 and g * per_elem_bytes <= budget:
            return g
    return 1


def _dense_fwd_impl(q, k, v, bias, seed, num_heads, causal, scale,
                    dropout_rate):
    """q,k,v: packed [B, T, H*D]; bias [B, Tk] or None.
    Returns (out [B, T, H*D], lse [B, H, T_pad])."""
    from jax.experimental import pallas as pl

    b, t, hd = q.shape
    t_k = k.shape[1]
    m = 8 if _INTERPRET else 128
    qp = _pad_last(q, m)
    kp, vp = _pad_last(k, m), _pad_last(v, m)
    t_pad, tk_pad = qp.shape[1], kp.shape[1]
    g = _pick_g(b, 2 * (t_pad + tk_pad) * hd * q.dtype.itemsize)

    kernel = functools.partial(
        _dense_fwd_kernel, num_heads=num_heads, causal=causal, scale=scale,
        q_len=t, kv_len=t_k, dropout_rate=dropout_rate)
    in_specs = [
        pl.BlockSpec((g, t_pad, hd), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((g, tk_pad, hd), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((g, tk_pad, hd), lambda bi: (bi, 0, 0)),
    ]
    args = [qp, kp, vp]
    if bias is not None:
        bp = _pad_vec(bias, m)
        in_specs.append(pl.BlockSpec((g, 8, tk_pad), lambda bi: (bi, 0, 0)))
        args.append(jnp.broadcast_to(bp[:, None, :], (b, 8, tk_pad)))

    def entry(*refs):
        if bias is not None:
            q_ref, k_ref, v_ref, b_ref, s_ref, o_ref, l_ref = refs
        else:
            q_ref, k_ref, v_ref, s_ref, o_ref, l_ref = refs
            b_ref = None
        kernel(q_ref, k_ref, v_ref, b_ref, s_ref, o_ref, l_ref)

    in_specs.append(pl.BlockSpec((1, 1), lambda bi: (0, 0)))
    args.append(jnp.asarray([[seed]], jnp.uint32))
    nh_pad = max(num_heads, 8)  # sublane-tiled stats block
    out, lse = pl.pallas_call(
        entry,
        grid=(b // g,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((g, t_pad, hd), lambda bi: (bi, 0, 0)),
            pl.BlockSpec((g, nh_pad, t_pad), lambda bi: (bi, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t_pad, hd), q.dtype),
            jax.ShapeDtypeStruct((b, nh_pad, t_pad), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(*args)
    return out[:, :t], lse


def _dense_bwd_impl(q, k, v, bias, seed, num_heads, causal, scale,
                    dropout_rate, out, lse, do):
    from jax.experimental import pallas as pl

    b, t, hd = q.shape
    t_k = k.shape[1]
    m = 8 if _INTERPRET else 128
    qp, kp, vp = _pad_last(q, m), _pad_last(k, m), _pad_last(v, m)
    dop, outp = _pad_last(do, m), _pad_last(out, m)
    t_pad, tk_pad = qp.shape[1], kp.shape[1]
    nh_pad = lse.shape[1]
    g = _pick_g(b, 4 * (t_pad + tk_pad) * hd * q.dtype.itemsize)

    kernel = functools.partial(
        _dense_bwd_kernel, num_heads=num_heads, causal=causal, scale=scale,
        q_len=t, kv_len=t_k, dropout_rate=dropout_rate)
    in_specs = [
        pl.BlockSpec((g, t_pad, hd), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((g, tk_pad, hd), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((g, tk_pad, hd), lambda bi: (bi, 0, 0)),
    ]
    args = [qp, kp, vp]
    if bias is not None:
        bp = _pad_vec(bias, m)
        in_specs.append(pl.BlockSpec((g, 8, tk_pad), lambda bi: (bi, 0, 0)))
        args.append(jnp.broadcast_to(bp[:, None, :], (b, 8, tk_pad)))
    in_specs.append(pl.BlockSpec((1, 1), lambda bi: (0, 0)))
    args.append(jnp.asarray([[seed]], jnp.uint32))
    in_specs += [
        pl.BlockSpec((g, t_pad, hd), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((g, t_pad, hd), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((g, nh_pad, t_pad), lambda bi: (bi, 0, 0)),
    ]
    args += [dop, outp, lse]

    def entry(*refs):
        if bias is not None:
            (q_ref, k_ref, v_ref, b_ref, s_ref, do_ref, o_ref, l_ref,
             dq_ref, dk_ref, dv_ref, db_ref) = refs
        else:
            (q_ref, k_ref, v_ref, s_ref, do_ref, o_ref, l_ref,
             dq_ref, dk_ref, dv_ref) = refs
            b_ref = db_ref = None
        kernel(q_ref, k_ref, v_ref, b_ref, s_ref, do_ref, o_ref, l_ref,
               dq_ref, dk_ref, dv_ref, db_ref)

    out_specs = [
        pl.BlockSpec((g, t_pad, hd), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((g, tk_pad, hd), lambda bi: (bi, 0, 0)),
        pl.BlockSpec((g, tk_pad, hd), lambda bi: (bi, 0, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b, t_pad, hd), q.dtype),
        jax.ShapeDtypeStruct((b, tk_pad, hd), k.dtype),
        jax.ShapeDtypeStruct((b, tk_pad, hd), v.dtype),
    ]
    if bias is not None:
        out_specs.append(pl.BlockSpec((g, 8, tk_pad), lambda bi: (bi, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((b, 8, tk_pad), jnp.float32))
    res = pl.pallas_call(
        entry,
        grid=(b // g,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_INTERPRET,
    )(*args)
    if bias is not None:
        dq, dk, dv, db = res
        db = db[:, 0, :t_k]
    else:
        dq, dk, dv = res
        db = None
    return dq[:, :t], dk[:, :t_k], dv[:, :t_k], db


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _dense_attention(q, k, v, bias, seed, num_heads, causal, scale,
                     dropout_rate):
    out, _ = _dense_fwd_impl(q, k, v, bias, seed, num_heads, causal, scale,
                             dropout_rate)
    return out


def _dense_fwd(q, k, v, bias, seed, num_heads, causal, scale, dropout_rate):
    out, lse = _dense_fwd_impl(q, k, v, bias, seed, num_heads, causal,
                               scale, dropout_rate)
    return out, (q, k, v, bias, seed, out, lse)


def _dense_bwd(num_heads, causal, scale, dropout_rate, res, g):
    q, k, v, bias, seed, out, lse = res
    dq, dk, dv, db = _dense_bwd_impl(q, k, v, bias, seed, num_heads, causal,
                                     scale, dropout_rate, out, lse, g)
    dbias = db.astype(bias.dtype) if bias is not None else None
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias, None)


_dense_attention.defvjp(_dense_fwd, _dense_bwd)

# dense path ceiling: whole [T,HD] q/k/v/do/out blocks + per-head [T,Tk]
# f32 transients must fit the ~16 MB VMEM comfortably
_DENSE_MAX_Q = 512
_DENSE_MAX_KV = 1024
_DENSE_VMEM_BUDGET = 10 * 1024 * 1024


def _dense_fits(t, t_k, hd, esize):
    """Conservative VMEM estimate for the dense bwd step (the larger of the
    two): 4 q-length + 4 kv-length packed blocks plus ~4 per-head [t, tk]
    f32 transients."""
    t_pad = ((t + 127) // 128) * 128
    tk_pad = ((t_k + 127) // 128) * 128
    blocks = (4 * t_pad + 4 * tk_pad) * hd * esize
    transients = 4 * t_pad * tk_pad * 4
    return blocks + transients <= _DENSE_VMEM_BUDGET


# ---------------------------------------------------------------------------
# differentiable wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash_attention(q, k, v, bias, seed, causal, scale, dropout_rate):
    out, _ = _flash_fwd_impl(q, k, v, bias, seed, causal, scale,
                             dropout_rate)
    return out


def _flash_fwd(q, k, v, bias, seed, causal, scale, dropout_rate):
    out, lse = _flash_fwd_impl(q, k, v, bias, seed, causal, scale,
                               dropout_rate)
    return out, (q, k, v, bias, seed, out, lse)


def _flash_bwd(causal, scale, dropout_rate, res, g):
    q, k, v, bias, seed, out, lse = res
    dq, dk, dv, db = _flash_bwd_impl(q, k, v, bias, seed, causal, scale,
                                     dropout_rate, out, lse, g)
    dbias = db.astype(bias.dtype) if bias is not None else None
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), \
        dbias, None


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# public entry: packed [B, T, H*D] layout used by the layers API
# ---------------------------------------------------------------------------

def kernel_plan(q_shape, k_shape, num_heads, esize, causal=False,
                dropout_rate=0.0, bias_kind=None, rng_available=True,
                platform_ok=True):
    """The attention dispatch decision as a structured
    ``ops.gates.GateDecision`` (ISSUE 15): ``kernel`` is which path runs
    — ``dense_vmem`` (whole-sequence VMEM-resident, packed layout),
    ``packed_stream`` (copy-free streaming), ``head_split_stream``
    (legacy streaming + the [B,T,H,D] relayout copies), or
    ``reference`` — and ``reasons`` records every check that demoted the
    choice. This IS the dispatch logic :func:`flash_attention` runs
    (single source); the static resource pass evaluates it shape-only
    with ``platform_ok=True``.

    ``bias_kind``: None | 'key' (padding-mask form) | 'rich' (anything
    else — reference path only)."""
    from ..core.op_registry import env_flag
    from .gates import GateDecision, GateReason

    b, t, hd = q_shape
    t_k = k_shape[1]
    d = hd // max(num_heads, 1)
    reasons = []
    if not platform_ok:
        reasons.append(GateReason(
            "platform", "not on TPU (or PADDLE_TPU_NO_FLASH=1)"))
    if bias_kind == "rich":
        reasons.append(GateReason(
            "bias", "non-key-mask bias shape: only the additive "
            "[B,1,1,Tk]/[B,Tk] padding-mask form streams"))
    if d % 8 != 0:
        reasons.append(GateReason(
            "geometry", "head dim %d is not a multiple of 8 "
            "(Mosaic-unfriendly; would be a lowering error)" % d))
    if dropout_rate > 0.0 and (_INTERPRET or not rng_available):
        reasons.append(GateReason(
            "dropout", "attention dropout needs the TPU PRNG primitives "
            "(interpret mode / no rng threaded)"))
    if reasons:
        return GateDecision(False, "reference", fallback="packed_stream",
                            reasons=reasons)
    if (t <= _DENSE_MAX_Q and t_k <= _DENSE_MAX_KV
            and (not causal or t <= t_k)
            and _dense_fits(t, t_k, hd, esize)):
        return GateDecision(True, "dense_vmem")
    if causal and t != t_k:
        # the streaming kernels anchor the causal diagonal at position 0
        # while mha_reference anchors it at the sequence end; they
        # disagree for t != t_k, so only the square case streams
        reasons.append(GateReason(
            "geometry", "causal with t_q=%d != t_k=%d: streaming kernels "
            "anchor the diagonal differently from the reference" % (t, t_k)))
        return GateDecision(False, "reference", fallback="packed_stream",
                            reasons=reasons)
    if _PACKED_STREAM and not env_flag("PADDLE_TPU_SPLIT_STREAM"):
        if _packed_stream_fits(t, t_k, hd, esize, num_heads,
                               float(dropout_rate)):
            return GateDecision(True, "packed_stream")
        reasons.append(GateReason(
            "vmem", "packed streaming working set for T=%d Tk=%d H*D=%d "
            "exceeds the %.0f MB VMEM budget — falls back to the "
            "head-split path (+[B,T,H,D] relayout copies around every "
            "attention site)" % (t, t_k, hd,
                                 _STREAM_VMEM_BUDGET / 2**20)))
    else:
        reasons.append(GateReason(
            "env", "packed streaming disabled "
            "(PADDLE_TPU_SPLIT_STREAM / module A/B switch)",
            blocking=False))
    return GateDecision(True, "head_split_stream",
                        fallback="packed_stream", reasons=reasons)


def plan_for(q, k, bias, num_heads, causal, dropout_rate, rng):
    """:func:`kernel_plan` for concrete arrays: classifies the bias form
    and evaluates the live platform gate. Used by the op impl (which
    records the decision in the op's attrs) and by
    :func:`flash_attention` itself."""
    b, _, _ = q.shape
    t_k = k.shape[1]
    bias_kind = None
    if bias is not None:
        if (bias.ndim == 4 and bias.shape[1] == 1 and bias.shape[2] == 1
                and bias.shape[0] in (1, b)) or \
                (bias.ndim == 2 and bias.shape[0] in (1, b)):
            bias_kind = "key"
        else:
            bias_kind = "rich"
    return kernel_plan(q.shape, k.shape, num_heads, q.dtype.itemsize,
                       causal=causal, dropout_rate=float(dropout_rate),
                       bias_kind=bias_kind,
                       rng_available=rng is not None,
                       platform_ok=_use_pallas(q))


def flash_attention(q, k, v, num_heads, bias=None, causal=False,
                    dropout_rate=0.0, rng=None, plan=None):
    """q,k,v: [B, T, H*D] (packed heads). ``bias``: None or additive
    [B, 1, 1, Tk] / [B, Tk] key mask (the padding-mask form; richer bias
    shapes fall back to the reference path). Returns [B, T, H*D].
    ``plan``: a precomputed :func:`plan_for` decision (the op impl
    records it); None recomputes it here."""
    b, t, hd = q.shape
    d = hd // num_heads
    t_k = k.shape[1]

    key_bias = None
    ref_bias = bias
    if bias is not None:
        ba = bias
        if (ba.ndim == 4 and ba.shape[1] == 1 and ba.shape[2] == 1
                and ba.shape[0] in (1, b)):
            key_bias = jnp.broadcast_to(
                ba.reshape(ba.shape[0], t_k), (b, t_k))
        elif ba.ndim == 2 and ba.shape[0] in (1, b):
            key_bias = jnp.broadcast_to(ba, (b, t_k))
            # the reference path adds bias to [B, H, Tq, Tk] logits:
            # lift the 2-D key form so broadcasting stays right-aligned
            ref_bias = key_bias[:, None, None, :]

    scale = 1.0 / math.sqrt(d)

    if plan is None:
        plan = plan_for(q, k, bias, num_heads, causal, dropout_rate, rng)

    if dropout_rate > 0.0 and plan.kernel != "reference":
        seed = jax.random.randint(rng, (), 0, np.iinfo(np.int32).max,
                                  dtype=jnp.int32).astype(jnp.uint32)
    else:
        seed = jnp.uint32(0)

    # short sequences: whole-sequence VMEM-resident kernel on the packed
    # layout (no head-split transposes, heads looped in-kernel). Causal
    # with t > t_k would create fully-masked rows, whose additive-mask
    # softmax (uniform over tk_pad incl. padding) diverges from the
    # reference's uniform-over-real-keys — those stay on the fallback
    # (kernel_plan encodes the rule).
    if plan.kernel == "dense_vmem":
        return _dense_attention(q, k, v, key_bias, seed, num_heads, causal,
                                scale, float(dropout_rate))

    if plan.kernel == "packed_stream":
        # copy-free streaming path: the packed layout goes straight into
        # the kernels — no [B,T,H,D] head-split relayouts around the
        # custom calls (the ~36 ms/step at the seq-2048 bench config)
        return _packed_stream_attention(q, k, v, key_bias, seed, num_heads,
                                        causal, scale, float(dropout_rate))

    def split(x, t_):
        return x.reshape(b, t_, num_heads, d).transpose(0, 2, 1, 3)

    qh, kh, vh = split(q, t), split(k, t_k), split(v, t_k)

    if plan.kernel == "reference":
        # dropout applies to the attention weights, matching the kernels
        out = mha_reference(qh, kh, vh, ref_bias, causal, scale,
                            dropout_rate=dropout_rate, rng=rng)
        return out.transpose(0, 2, 1, 3).reshape(b, t, hd)

    # head-split streaming: flatten heads into the grid's leading axis
    qf = qh.reshape(b * num_heads, t, d)
    kf = kh.reshape(b * num_heads, t_k, d)
    vf = vh.reshape(b * num_heads, t_k, d)
    bf = (jnp.repeat(key_bias, num_heads, axis=0)
          if key_bias is not None else None)
    out = _flash_attention(qf, kf, vf, bf, seed, causal, scale,
                           float(dropout_rate))
    out = out.reshape(b, num_heads, t, d)
    return out.transpose(0, 2, 1, 3).reshape(b, t, hd)

"""TPU-native row gather for narrow embedding tables.

A row of a ``[V, K<128]`` f32 table occupies one (8,128) tile row padded
to 128 lanes, so XLA's row gather fetches 512 bytes per row to return
``4*K`` useful ones, and per-row DMA latency dominates: measured 8 ns/row
(7.8 GB/s useful) on v5e regardless of K — see ``tools/bench_gather.py``.

``packed_take`` reshapes the table so ``P = 128 // K`` logical rows share
one physical 128-lane row; each gathered 512-byte burst then carries P
candidate rows and a lane-select keeps the wanted one. Measured 2 ns/row,
213 GB/s — 4x faster than the plain gather, ~17x faster than what XLA
emits for the AMP-fused bf16 gather in DeepFM (bf16 sublane-packed tiles
gather ~7x slower than f32, so callers should gather f32 and cast the
[N, K] output instead — ``opimpl/tensor_ops._lookup_table`` does).

Reference capability: ``paddle/fluid/operators/lookup_table_op.cc`` (the
gather kernel; the reference's perf answer to high-dim sparse is the
pserver/pslib path — ours is keeping single-chip row ops at HBM burst
efficiency and sharding tables over the mesh, parallel/sharded_embedding).
"""

import jax
import jax.numpy as jnp

__all__ = ["packed_take", "pack_factor"]

_LANES = 128


def pack_factor(k):
    """How many logical K-rows fit one 128-lane physical row (1 = no
    packing possible; K must divide 128)."""
    if k <= 0 or k >= _LANES or _LANES % k:
        return 1
    return _LANES // k


def packed_take(w, ids):
    """``w[ids]`` for a 2-D ``[V, K]`` table, packing narrow rows so the
    gather moves full 128-lane bursts. Exact (the lane-select adds only
    zeros). Falls back to ``jnp.take`` when K doesn't divide 128.

    ids: any integer shape; returns ``ids.shape + (K,)``.

    Differentiation: custom_vjp — the cotangent of the gather is the row
    scatter-add ``dW[ids] += g``, routed through
    :func:`ops.scatter.scatter_add_rows` (the Pallas VMEM-resident
    kernel when the table qualifies, XLA's ``.at[].add`` otherwise —
    identical math to jax's native vjp of the packed formulation either
    way, so flipping the kernel gate never changes numerics class).
    """
    v, k = w.shape
    p = pack_factor(k)
    if p == 1:
        return jnp.take(w, ids, axis=0)
    idf = ids.reshape(-1).astype(jnp.int32)
    out = _packed_take_flat(w, idf)
    return out.reshape(tuple(ids.shape) + (k,))


def _packed_take_impl(w, idf):
    v, k = w.shape
    p = pack_factor(k)
    n = idf.shape[0]
    vp = -(-v // p)
    pad = vp * p - v
    wp = jnp.pad(w, ((0, pad), (0, 0))) if pad else w
    wp = wp.reshape(vp, p * k)
    rows = wp[idf // p]                              # [n, 128] burst gather
    sub = idf % p
    lane_row = jax.lax.broadcasted_iota(jnp.int32, (1, p * k), 1) // k
    picked = jnp.where(lane_row == sub[:, None], rows,
                       jnp.zeros((), w.dtype))
    return jnp.sum(picked.reshape(n, p, k), axis=1)


@jax.custom_vjp
def _packed_take_flat(w, idf):
    return _packed_take_impl(w, idf)


def _packed_take_fwd(w, idf):
    # w rides in the residuals only for its shape/dtype: it is a live
    # parameter buffer either way, so this saves nothing extra
    return _packed_take_impl(w, idf), (w, idf)


def _packed_take_bwd(res, g):
    from .scatter import scatter_add_rows

    w, idf = res
    dw = scatter_add_rows(jnp.zeros_like(w), idf, g.astype(w.dtype))
    return dw, None


_packed_take_flat.defvjp(_packed_take_fwd, _packed_take_bwd)

"""Fused vocab-projection + label-smoothed softmax cross-entropy.

The reference pairs a [.., D] x [D, V] projection with
``softmax_with_cross_entropy_op.cc`` (+ ``label_smooth_op.cc``), which
materializes the [.., V] logits (and a soft-label tensor) in memory. On TPU
that tensor dominates the loss head: for transformer-base at batch 128 /
seq 256 / V=30k the logits are 2 GB in bf16 (4 GB f32) and the profile
shows ~25 ms/step of pure HBM traffic + layout copies around them
(NOTES_r3.md).

Here the projection and the CE reduction fuse into one Pallas kernel: the
logits tile lives in VMEM, is consumed by an online (max, sumexp, sum,
logit_y) accumulation, and never reaches HBM. The backward recomputes
logits chunk-by-chunk under ``lax.scan`` from the saved row logsumexp —
peak memory is one [chunk, V] tile instead of [T, V], which also unlocks
larger batches.

Math (matches ``opimpl/nn_ops.py:smooth_softmax_ce``):
    loss = lse(z) - (1-eps) * z[y] - eps * mean(z),   z = x @ W + b
    dz   = g * (softmax(z) - (1-eps) * onehot(y) - eps/V)
"""

import functools

import jax
import jax.numpy as jnp

_INTERPRET = False  # tests flip this to run the kernel on CPU


# Above this many logit elements (T*V) the fused path engages. Below it,
# the plain projection+CE wins on the bench chip: the fused backward
# RECOMPUTES the projection (+2*T*D*V FLOPs) to avoid storing [T, V], and
# with HBM to spare that trade loses (measured: batch 128 transformer-base
# 199.9k tok/s plain vs 196.2k fused; batch 256 plain OOMs, fused runs).
_FUSED_MIN_LOGITS = 1.5e9


def _use_fused(x, w):
    if _INTERPRET:
        return True
    from ..core.op_registry import env_flag, single_tpu

    if env_flag("PADDLE_TPU_NO_FUSED_CE"):  # A/B escape hatch
        return False
    if not single_tpu():
        return False
    n_logits = (x.size // x.shape[-1]) * w.shape[1]
    return (n_logits >= _FUSED_MIN_LOGITS
            or env_flag("PADDLE_TPU_FUSED_CE"))


# ---------------------------------------------------------------------------
# forward kernel: grid (t blocks, v blocks), online stats in VMEM scratch
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, loss_ref, lse_ref,
                m_sc, s_sc, sl_sc, ly_sc, *, v_total, eps, nv):
    from jax.experimental import pallas as pl

    vi = pl.program_id(1)
    bt = x_ref.shape[0]
    bv = w_ref.shape[1]

    @pl.when(vi == 0)
    def _init():
        m_sc[...] = jnp.full((bt, 1), -jnp.inf, jnp.float32)
        s_sc[...] = jnp.zeros((bt, 1), jnp.float32)
        sl_sc[...] = jnp.zeros((bt, 1), jnp.float32)
        ly_sc[...] = jnp.zeros((bt, 1), jnp.float32)

    logits = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # [bt, bv]
    if b_ref is not None:
        logits = logits + b_ref[0:1, :].astype(jnp.float32)
    col = vi * bv + jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    valid = col < v_total

    m_old = m_sc[...]
    m_new = jnp.maximum(
        m_old, jnp.max(jnp.where(valid, logits, -jnp.inf), axis=1,
                       keepdims=True))
    alpha = jnp.exp(m_old - m_new)  # 0 on the first block (m_old = -inf)
    s_sc[...] = s_sc[...] * alpha + jnp.sum(
        jnp.where(valid, jnp.exp(logits - m_new), 0.0), axis=1,
        keepdims=True)
    m_sc[...] = m_new
    if eps:
        sl_sc[...] = sl_sc[...] + jnp.sum(
            jnp.where(valid, logits, 0.0), axis=1, keepdims=True)
    y = y_ref[...]  # [bt, 1] int32
    ly_sc[...] = ly_sc[...] + jnp.sum(
        jnp.where(col == y, logits, 0.0), axis=1, keepdims=True)

    @pl.when(vi == nv - 1)
    def _fin():
        lse = m_sc[...] + jnp.log(s_sc[...])
        loss = lse - (1.0 - eps) * ly_sc[...]
        if eps:
            loss = loss - eps * sl_sc[...] / v_total
        loss_ref[...] = loss
        lse_ref[...] = lse


def _fwd_impl(x, w, b, y, eps):
    """x [T, D], w [D, V], b [V] or None, y [T] int32.
    Returns (loss [T] f32, lse [T] f32)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t, d = x.shape
    v = w.shape[1]
    bt = min(512, max(8, ((t + 7) // 8) * 8))
    bv = 1024 if not _INTERPRET else 128
    tp = ((t + bt - 1) // bt) * bt
    vp = ((v + bv - 1) // bv) * bv
    if tp != t:
        x = jnp.pad(x, ((0, tp - t), (0, 0)))
        y = jnp.pad(y, (0, tp - t))
    if vp != v:
        w = jnp.pad(w, ((0, 0), (0, vp - v)))
    nt, nv = tp // bt, vp // bv

    y2 = y.astype(jnp.int32).reshape(tp, 1)
    args = [x, w]
    in_specs = [
        pl.BlockSpec((bt, d), lambda ti, vi: (ti, 0)),
        pl.BlockSpec((d, bv), lambda ti, vi: (0, vi)),
    ]
    if b is not None:
        bb = jnp.broadcast_to(
            jnp.pad(b, (0, vp - v)).reshape(1, vp), (8, vp))
        args.append(bb)
        in_specs.append(pl.BlockSpec((8, bv), lambda ti, vi: (0, vi)))
    args.append(y2)
    in_specs.append(pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)))

    kernel = functools.partial(_fwd_kernel, v_total=v, eps=eps, nv=nv)

    def entry(*refs):
        if b is not None:
            x_ref, w_ref, b_ref, y_ref = refs[:4]
            rest = refs[4:]
        else:
            x_ref, w_ref, y_ref = refs[:3]
            b_ref = None
            rest = refs[3:]
        kernel(x_ref, w_ref, b_ref, y_ref, *rest)

    loss, lse = pl.pallas_call(
        entry,
        grid=(nt, nv),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((tp, 1), jnp.float32),
            jax.ShapeDtypeStruct((tp, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bt, 1), jnp.float32)] * 4,
        interpret=_INTERPRET,
    )(*args)
    return loss[:t, 0], lse[:t, 0]


# ---------------------------------------------------------------------------
# backward: chunked recompute under lax.scan (peak memory one [chunk, V])
# ---------------------------------------------------------------------------

def _pick_chunk(t):
    for c in (4096, 2048, 1024, 512, 256, 128):
        if t % c == 0:
            return c
    return t


def _bwd_impl(x, w, b, y, lse, g, eps):
    t, d = x.shape
    v = w.shape[1]
    ct = _pick_chunk(t)
    nch = t // ct
    xs = x.reshape(nch, ct, d)
    ys = y.astype(jnp.int32).reshape(nch, ct)
    ls = lse.reshape(nch, ct)
    gs = g.astype(jnp.float32).reshape(nch, ct)
    bf = b.astype(jnp.float32) if b is not None else None

    def body(carry, inp):
        dw, db = carry
        xc, yc, lsec, gc = inp
        logits = jnp.dot(xc, w, preferred_element_type=jnp.float32)
        if bf is not None:
            logits = logits + bf
        p = jnp.exp(logits - lsec[:, None])
        dl = gc[:, None] * (p - (eps / v if eps else 0.0))
        oh = jax.lax.broadcasted_iota(jnp.int32, (ct, v), 1) == yc[:, None]
        dl = jnp.where(oh, dl - (1.0 - eps) * gc[:, None], dl)
        dlc = dl.astype(x.dtype)
        dxc = jax.lax.dot_general(
            dlc, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
        dw = dw + jax.lax.dot_general(
            xc, dlc, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if db is not None:
            db = db + jnp.sum(dl, axis=0)
        return (dw, db), dxc

    dw0 = jnp.zeros((d, v), jnp.float32)
    db0 = jnp.zeros((v,), jnp.float32) if b is not None else None
    (dw, db), dxs = jax.lax.scan(body, (dw0, db0), (xs, ys, ls, gs))
    dx = dxs.reshape(t, d)
    return dx, dw.astype(w.dtype), \
        db.astype(b.dtype) if b is not None else None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused(x, w, b, y, eps):
    loss, _ = _fwd_impl(x, w, b, y, eps)
    return loss


def _fused_fwd(x, w, b, y, eps):
    loss, lse = _fwd_impl(x, w, b, y, eps)
    return loss, (x, w, b, y, lse)


def _fused_bwd(eps, res, g):
    x, w, b, y, lse = res
    dx, dw, db = _bwd_impl(x, w, b, y, lse, g, eps)
    return dx, dw, db, None


_fused.defvjp(_fused_fwd, _fused_bwd)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# bf16-resident materialized path: logits stored ONCE in bf16 (half the
# plain-f32 HBM traffic), statistics and the softmax in f32 streamed from
# the bf16 tensor, and a custom vjp that hands the backward dots a bf16
# dlogits (XLA's autodiff of the f32 composition would materialize a 4 GB
# f32 dlogits).  Engages under AMP when the Pallas-fused path doesn't.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _bf16_ce(x2, w, b, y2, eps):
    loss, _ = _bf16_ce_fwd(x2, w, b, y2, eps)
    return loss


def _bf16_stats(logits, y2, eps):
    v = logits.shape[-1]
    lf = logits.astype(jnp.float32)          # fused into the streaming pass
    m = jnp.max(lf, axis=-1)
    s = jnp.sum(jnp.exp(lf - m[:, None]), axis=-1)
    lse = m + jnp.log(s)
    logit_y = jnp.take_along_axis(logits, y2[:, None],
                                  axis=-1)[:, 0].astype(jnp.float32)
    loss = lse - (1.0 - eps) * logit_y
    if eps:
        loss = loss - eps * jnp.sum(lf, axis=-1) / v
    return loss, m, s


def _bf16_ce_fwd(x2, w, b, y2, eps):
    xb = x2.astype(jnp.bfloat16)
    wb = w.astype(jnp.bfloat16)
    logits = jnp.dot(xb, wb)                 # bf16-stored [T, V]
    if b is not None:
        logits = logits + b.astype(jnp.bfloat16)
    loss, m, s = _bf16_stats(logits, y2, eps)
    # zero-size dtype carriers: cotangents must match the PRIMAL dtypes
    # (x2 may be f32 while xb is bf16; b may be None)
    protos = (x2[:0], None if b is None else b[:0])
    return loss, (xb, wb, logits, m, s, y2, protos)


def _bf16_ce_bwd(eps, res, g):
    xb, wb, logits, m, s, y2, (x_proto, b_proto) = res
    t, v = logits.shape
    p = jnp.exp(logits.astype(jnp.float32) - m[:, None]) / s[:, None]
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (t, v), 1)
              == y2[:, None])
    dz = p - (1.0 - eps) * onehot.astype(jnp.float32)
    if eps:
        dz = dz - eps / v
    dl = (dz * g[:, None].astype(jnp.float32)).astype(jnp.bfloat16)
    # bf16 OPERANDS (the traffic win) with f32-stored dot outputs: the MXU
    # accumulates f32 regardless, storing bf16 would just re-round grads
    dx = jnp.dot(dl, wb.T,
                 preferred_element_type=jnp.float32).astype(x_proto.dtype)
    dw = jnp.dot(xb.T, dl, preferred_element_type=jnp.float32)
    db = (None if b_proto is None
          else jnp.sum(dl.astype(jnp.float32), axis=0).astype(b_proto.dtype))
    return dx, dw, db, None


_bf16_ce.defvjp(_bf16_ce_fwd, _bf16_ce_bwd)


def linear_smooth_ce(x, w, b, y, eps):
    """x: [..., D] activations; w: [D, V]; b: [V] or None; y: [...] int
    labels. Returns per-position f32 loss of shape ``x.shape[:-1]``."""
    lead = x.shape[:-1]
    d = x.shape[-1]
    x2 = x.reshape(-1, d)
    y2 = y.reshape(-1).astype(jnp.int32)

    if _use_fused(x, w):
        loss = _fused(x2, w, b, y2, float(eps))
        return loss.reshape(lead)

    from ..core.op_registry import amp_enabled, env_flag, single_tpu
    # engage on op-registry AMP, or when the caller already runs bf16
    # activations (the dygraph build's per-layer casts); the F32_ACTS
    # escape hatch disables it in BOTH cases (mxu_cast hands this op a
    # bf16 x under static AMP regardless of that flag)
    wants_bf16 = ((amp_enabled() or x.dtype == jnp.bfloat16)
                  and not env_flag("PADDLE_TPU_AMP_F32_ACTS"))
    if (wants_bf16 and single_tpu()
            and not env_flag("PADDLE_TPU_NO_BF16_CE")):  # A/B escape hatch
        return _bf16_ce(x2, w, b, y2, float(eps)).reshape(lead)

    # reference path (CPU / mesh): plain projection + closed-form smooth CE
    logits = jnp.dot(x2, w, preferred_element_type=jnp.float32)
    if b is not None:
        logits = logits + b.astype(jnp.float32)
    v = w.shape[1]
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    logit_y = jnp.take_along_axis(logits, y2[:, None], axis=-1)[:, 0]
    loss = lse - (1.0 - eps) * logit_y
    if eps:
        loss = loss - eps * jnp.mean(logits, axis=-1)
    return loss.reshape(lead)

"""Fused conv + batch_norm + activation (+ residual add): Pallas epilogue
kernels for the ResNet-50 bottleneck shapes.

The unfused lowering pays a full HBM round trip per conv->BN->ReLU link:
conv writes its output, the BN statistics pass re-reads it, and the
normalize(+residual+relu) pass reads it again and writes the final
activation (RESNET_ROOFLINE.json's relu-elementwise bucket; the reference
framework fuses these chains as graph passes —
``framework/details/build_strategy.cc`` ``fuse_elewise_add_act`` /
``fuse_relu_depthwise_conv``). Here the conv runs as a Pallas blocked
matmul over the flattened spatial axis with the epilogues folded in:

  * training: ONE kernel computes the conv and accumulates the per-channel
    sum/sumsq moments in the same VMEM-resident pass (the stats read pass
    disappears), then ONE apply kernel performs scale/shift + residual +
    relu (the separate residual/relu passes disappear). HBM traffic per
    link: W(conv) + R+W(apply) vs the unfused W + R(stats) + R + W.
  * inference / use_global_stats: the BN affine folds entirely into the
    conv epilogue — ONE kernel, the intermediate never reaches HBM.

Conv-as-matmul: a KxK/pad convolution over the lane-flattened [C, H*W]
image is a sum of K*K shifted matmuls — each tap (di, dj) contributes
``W[:, :, di, dj] @ shift(x, (di-ph)*W + (dj-pw))`` with the row-wrap
columns masked. Shifts are static lane slices of a once-padded block, so
the whole tap loop runs on VMEM values (see /opt/skills/guides/
pallas_guide.md on lane layout). Supported: groups=1, dilation 1, 1x1
(stride 1; stride 2 via a pre-slice, exact for 1x1) and 3x3/pad-1 stride 1
— the ResNet-50 bottleneck bodies. Everything else (7x7 stem, stride-2
3x3) replays the original unfused ops (see ``core/epilogue_fusion.py``).

Backward: custom_vjp. The forward saves the conv output (it is in HBM
anyway), and the backward chain-rules ``jax.vjp`` of the *reference*
epilogue (stats recomputed from the saved conv output, so the BN
stat-coupling terms are exact) into ``jax.vjp`` of the plain lax conv —
bit-identical math to differentiating the unfused program, no conv
recompute.

CPU/tests: ``_INTERPRET = True`` routes the Pallas path through the
interpreter (tests/test_fused_conv.py); otherwise non-TPU falls back to
the caller's unfused replay.
"""

import functools

import jax
import jax.numpy as jnp

_INTERPRET = False  # tests flip this to run the kernels on CPU

# conservative per-program VMEM budget: double-buffered x/co/y blocks plus
# the f32 accumulator and the lane-padded shift transient
_VMEM_BUDGET = 12 * 1024 * 1024


def supported_geometry(x_shape, w_shape, strides, paddings, dilations,
                       groups):
    """True when the Pallas path covers this conv geometry."""
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    if any(d is None or int(d) <= 0 for d in tuple(x_shape) + tuple(w_shape)):
        return False
    if groups != 1 or tuple(dilations) != (1, 1):
        return False
    o, c, kh, kw = w_shape
    s = tuple(strides)
    p = tuple(paddings)
    if (kh, kw) == (1, 1):
        return p == (0, 0) and s in ((1, 1), (2, 2))
    if (kh, kw) == (3, 3):
        return p == (1, 1) and s == (1, 1)
    return False


def _fits_vmem(c, o, hw, esize, has_residual):
    w_lane_pad = hw + 2 * 512  # worst-case S transient bound
    x_bytes = 2 * c * w_lane_pad * esize          # block + shift transient
    co_bytes = 2 * o * hw * esize                 # double-buffered out
    acc_bytes = o * hw * 4
    res_bytes = 2 * o * hw * esize if has_residual else 0
    return x_bytes + co_bytes + acc_bytes + res_bytes <= _VMEM_BUDGET


def gate(x_shape, w_shape, strides, paddings, dilations, groups, esize,
         has_residual, static_only=False):
    """Structured gate (``ops.gates.GateDecision``) for the fused
    kernels (mirrors the other fused ops' gates). ``static_only=True``
    evaluates only the geometry/VMEM checks — the platform-independent
    view the static resource pass wants."""
    from .gates import GateDecision, GateReason

    reasons = []
    if not supported_geometry(x_shape, w_shape, strides, paddings,
                              dilations, groups):
        reasons.append(GateReason(
            "geometry", "unsupported conv geometry: filter %s strides %s "
            "paddings %s dilations %s groups %s (Pallas path covers the "
            "1x1 s1/s2 and 3x3 s1 p1 bottleneck shapes)"
            % (list(w_shape), list(strides), list(paddings),
               list(dilations), groups)))
    else:
        o, c, kh, kw = w_shape
        h, w = int(x_shape[2]), int(x_shape[3])
        if tuple(strides) == (2, 2):  # pre-sliced before the kernel
            h, w = (h + 1) // 2, (w + 1) // 2
        if not _fits_vmem(int(c), int(o), h * w, esize, has_residual):
            reasons.append(GateReason(
                "vmem", "[C=%d, HW=%d] image + [O=%d] output blocks "
                "exceed the %.0f MB VMEM budget"
                % (int(c), h * w, int(o), _VMEM_BUDGET / 2**20)))
    if not static_only and not reasons and not _INTERPRET:
        from ..core.op_registry import env_flag, single_tpu

        if env_flag("PADDLE_TPU_NO_FUSED_CONV"):  # A/B escape hatch
            reasons.append(GateReason("env", "PADDLE_TPU_NO_FUSED_CONV=1"))
        elif not single_tpu():
            reasons.append(GateReason(
                "platform", "not a single TPU (a mesh would make the "
                "custom call fight GSPMD)"))
    if reasons:
        return GateDecision(False, "unfused_replay",
                            fallback="pallas_fused_conv", reasons=reasons)
    return GateDecision(True, "pallas_fused_conv")


def use_pallas(x_shape, w_shape, strides, paddings, dilations, groups,
               esize, has_residual):
    """Boolean view of :func:`gate` (the pre-ISSUE-15 surface)."""
    return gate(x_shape, w_shape, strides, paddings, dilations, groups,
                esize, has_residual).admitted


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _conv_taps(x, w_ref, taps, shift_pad, width, hw):
    """[O, HW] f32 conv accumulator from the VMEM-resident [C, HW] image:
    one shifted, row-wrap-masked matmul per kernel tap."""
    acc = None
    if shift_pad:
        xp = jnp.pad(x, ((0, 0), (shift_pad, shift_pad)))
        wcol = jax.lax.broadcasted_iota(jnp.int32, (1, hw), 1) % width
    for t, (dy, dx) in enumerate(taps):
        if shift_pad:
            s = dy * width + dx
            xt = xp[:, shift_pad + s:shift_pad + s + hw]
            if dx:
                ok = (wcol + dx >= 0) & (wcol + dx < width)
                xt = jnp.where(ok, xt, jnp.zeros_like(xt))
        else:
            xt = x
        part = jax.lax.dot_general(
            w_ref[t], xt, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [O, HW]
        acc = part if acc is None else acc + part
    return acc


def _conv_moments_kernel(x_ref, w_ref, co_ref, s1_ref, s2_ref, *, taps,
                         shift_pad, width, hw):
    """Training kernel: conv + per-channel sum/sumsq of the (rounded)
    output, accumulated across the sequential batch grid into revisited
    [O, 1] outputs — the BN statistics pass never re-reads HBM."""
    from jax.experimental import pallas as pl

    acc = _conv_taps(x_ref[0], w_ref, taps, shift_pad, width, hw)
    co = acc.astype(co_ref.dtype)
    co_ref[0] = co
    # moments from the ROUNDED values: numerics match the unfused BN,
    # which reads the stored (bf16 under AMP) conv output back as f32
    cof = co.astype(jnp.float32)
    ones = jnp.ones((hw, 1), jnp.float32)
    s1 = jax.lax.dot_general(cof, ones, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    s2 = jax.lax.dot_general(cof * cof, ones, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    s1_ref[...] += s1
    s2_ref[...] += s2


def _apply_kernel(co_ref, scale_ref, shift_ref, res_ref, y_ref, *, relu):
    """BN scale/shift (+residual)(+relu) epilogue: one read, one write."""
    y = co_ref[0].astype(jnp.float32) * scale_ref[...] + shift_ref[...]
    y = y.astype(y_ref.dtype)  # activation dtype BEFORE the residual add,
    if res_ref is not None:    # matching the unfused bn.Y -> add chain
        y = y + res_ref[0]
    if relu:
        y = jnp.maximum(y, jnp.zeros_like(y))
    y_ref[0] = y


def _conv_apply_kernel(x_ref, w_ref, scale_ref, shift_ref, res_ref, y_ref,
                       *, taps, shift_pad, width, hw, relu, co_dtype):
    """Inference kernel: conv with the BN affine (+residual)(+relu) folded
    into the epilogue — the conv output never reaches HBM."""
    acc = _conv_taps(x_ref[0], w_ref, taps, shift_pad, width, hw)
    # round through the storage dtype the unfused path would have used, so
    # fused and unfused inference agree bit-for-bit under AMP
    cof = acc.astype(co_dtype).astype(jnp.float32)
    y = (cof * scale_ref[...] + shift_ref[...]).astype(y_ref.dtype)
    if res_ref is not None:
        y = y + res_ref[0]
    if relu:
        y = jnp.maximum(y, jnp.zeros_like(y))
    y_ref[0] = y


# ---------------------------------------------------------------------------
# pallas_call drivers — x flattened to [N, C, H*W]
# ---------------------------------------------------------------------------

def _tap_geometry(kh, kw, ph, pw, width):
    taps = tuple((di - ph, dj - pw) for di in range(kh) for dj in range(kw))
    shift_pad = width + max(pw, 1) if (kh, kw) != (1, 1) else 0
    return taps, shift_pad


def _w_taps(w):
    """[O, C, KH, KW] -> [KH*KW, O, C] so the kernel indexes taps on the
    leading (cheap) axis."""
    o, c, kh, kw = w.shape
    return w.transpose(2, 3, 0, 1).reshape(kh * kw, o, c)


def _conv_moments(x2, wt, taps, shift_pad, width):
    from jax.experimental import pallas as pl

    n, c, hw = x2.shape
    nt, o, _ = wt.shape
    kernel = functools.partial(_conv_moments_kernel, taps=taps,
                               shift_pad=shift_pad, width=width, hw=hw)
    co, s1, s2 = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, c, hw), lambda i: (i, 0, 0)),
            pl.BlockSpec((nt, o, c), lambda i: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, o, hw), lambda i: (i, 0, 0)),
            pl.BlockSpec((o, 1), lambda i: (0, 0)),
            pl.BlockSpec((o, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, o, hw), x2.dtype),
            jax.ShapeDtypeStruct((o, 1), jnp.float32),
            jax.ShapeDtypeStruct((o, 1), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(x2, wt)
    return co, s1[:, 0], s2[:, 0]


def _apply(co, scale, shift, residual, relu):
    from jax.experimental import pallas as pl

    n, o, hw = co.shape
    in_specs = [
        pl.BlockSpec((1, o, hw), lambda i: (i, 0, 0)),
        pl.BlockSpec((o, 1), lambda i: (0, 0)),
        pl.BlockSpec((o, 1), lambda i: (0, 0)),
    ]
    args = [co, scale.reshape(o, 1), shift.reshape(o, 1)]
    if residual is not None:
        in_specs.append(pl.BlockSpec((1, o, hw), lambda i: (i, 0, 0)))
        args.append(residual)

    def entry(*refs):
        if residual is not None:
            co_ref, sc_ref, sh_ref, res_ref, y_ref = refs
        else:
            co_ref, sc_ref, sh_ref, y_ref = refs
            res_ref = None
        _apply_kernel(co_ref, sc_ref, sh_ref, res_ref, y_ref, relu=relu)

    return pl.pallas_call(
        entry,
        grid=(n,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, o, hw), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, o, hw), co.dtype),
        interpret=_INTERPRET,
    )(*args)


def _conv_apply(x2, wt, scale, shift, residual, relu, taps, shift_pad,
                width, co_dtype):
    from jax.experimental import pallas as pl

    n, c, hw = x2.shape
    nt, o, _ = wt.shape
    in_specs = [
        pl.BlockSpec((1, c, hw), lambda i: (i, 0, 0)),
        pl.BlockSpec((nt, o, c), lambda i: (0, 0, 0)),
        pl.BlockSpec((o, 1), lambda i: (0, 0)),
        pl.BlockSpec((o, 1), lambda i: (0, 0)),
    ]
    args = [x2, wt, scale.reshape(o, 1), shift.reshape(o, 1)]
    if residual is not None:
        in_specs.append(pl.BlockSpec((1, o, hw), lambda i: (i, 0, 0)))
        args.append(residual)
    kernel = functools.partial(_conv_apply_kernel, taps=taps,
                               shift_pad=shift_pad, width=width, hw=hw,
                               relu=relu, co_dtype=co_dtype)

    def entry(*refs):
        if residual is not None:
            x_ref, w_ref, sc_ref, sh_ref, res_ref, y_ref = refs
        else:
            x_ref, w_ref, sc_ref, sh_ref, y_ref = refs
            res_ref = None
        kernel(x_ref, w_ref, sc_ref, sh_ref, res_ref, y_ref)

    return pl.pallas_call(
        entry,
        grid=(n,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, o, hw), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, o, hw), x2.dtype),
        interpret=_INTERPRET,
    )(*args)


# ---------------------------------------------------------------------------
# reference composition (the unfused math, used by the backward and by the
# numerics tests) + custom_vjp wiring
# ---------------------------------------------------------------------------

def _bn_stats(co):
    """Per-channel batch mean/var of [N, O, HW] in f32 (one-pass, exactly
    the unfused ``_batch_norm`` formulation)."""
    cof = co.astype(jnp.float32) if co.dtype == jnp.bfloat16 else co
    n = co.shape[0] * co.shape[2]
    s1 = jnp.sum(cof, axis=(0, 2))
    s2 = jnp.sum(cof * cof, axis=(0, 2))
    bm = s1 / n
    bv = jnp.maximum(s2 / n - bm * bm, 0.0)
    return bm, bv


def _epilogue_reference(co, gamma, beta, residual, bm, bv, eps, act):
    """normalize (+residual)(+act) on a conv output, matching the unfused
    batch_norm -> elementwise_add -> relu numerics exactly. ``bm``/``bv``
    None means training (stats from ``co``, differentiably — the BN
    stat-coupling terms of the backward come out of this)."""
    cof = co.astype(jnp.float32) if co.dtype == jnp.bfloat16 else co
    if bm is None:
        bm, bv = _bn_stats(co)
    inv = jax.lax.rsqrt(bv.reshape(1, -1, 1) + eps)
    y = (cof - bm.reshape(1, -1, 1)) * inv * \
        gamma.astype(jnp.float32).reshape(1, -1, 1) + \
        beta.astype(jnp.float32).reshape(1, -1, 1)
    y = y.astype(co.dtype)
    if residual is not None:
        y = y + residual.astype(y.dtype)
    if act == "relu":
        y = jax.nn.relu(y)
    return y


def _conv_reference(x2, w, height, width):
    """Plain stride-1 lax conv on the flattened layout (the pre-slice makes
    every supported geometry stride-1 by the time it reaches the kernel)."""
    n, c, hw = x2.shape
    o, _, kh, kw = w.shape
    ph, pw = (kh - 1) // 2, (kw - 1) // 2
    out = jax.lax.conv_general_dilated(
        x2.reshape(n, c, height, width), w, window_strides=(1, 1),
        padding=[(ph, ph), (pw, pw)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return out.reshape(n, o, hw)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _fused_train(x2, w, gamma, beta, residual, height, width, eps, act):
    y, bm, bv, _ = _fused_train_fwd_impl(x2, w, gamma, beta, residual,
                                         height, width, eps, act)
    return y, bm, bv


def _fused_train_fwd_impl(x2, w, gamma, beta, residual, height, width, eps,
                          act):
    o, c, kh, kw = w.shape
    taps, shift_pad = _tap_geometry(kh, kw, (kh - 1) // 2, (kw - 1) // 2,
                                    width)
    co, s1, s2 = _conv_moments(x2, _w_taps(w), taps, shift_pad, width)
    n = x2.shape[0] * x2.shape[2]
    bm = s1 / n
    bv = jnp.maximum(s2 / n - bm * bm, 0.0)
    inv = jax.lax.rsqrt(bv + eps)
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - bm * scale
    y = _apply(co, scale, shift, residual, relu=(act == "relu"))
    return y, bm, bv, co


def _fused_train_fwd(x2, w, gamma, beta, residual, height, width, eps, act):
    y, bm, bv, co = _fused_train_fwd_impl(x2, w, gamma, beta, residual,
                                          height, width, eps, act)
    return (y, bm, bv), (x2, w, gamma, beta, residual, co)


def _fused_train_bwd(height, width, eps, act, res, cts):
    x2, w, gamma, beta, residual, co = res
    dy = cts[0]  # bm/bv outputs are stop_gradient'd by the caller
    with_res = residual is not None
    if with_res:
        _, epi_vjp = jax.vjp(
            lambda co_, g_, b_, r_: _epilogue_reference(
                co_, g_, b_, r_, None, None, eps, act),
            co, gamma, beta, residual)
        dco, dgamma, dbeta, dres = epi_vjp(dy)
    else:
        _, epi_vjp = jax.vjp(
            lambda co_, g_, b_: _epilogue_reference(
                co_, g_, b_, None, None, None, eps, act),
            co, gamma, beta)
        dco, dgamma, dbeta = epi_vjp(dy)
        dres = None
    _, conv_vjp = jax.vjp(
        lambda x_, w_: _conv_reference(x_, w_, height, width), x2, w)
    dx2, dw = conv_vjp(dco.astype(co.dtype))
    return (dx2, dw, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype),
            dres.astype(residual.dtype) if with_res else None)


_fused_train.defvjp(_fused_train_fwd, _fused_train_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _fused_infer(x2, w, gamma, beta, mean, var, residual, height, width,
                 eps, act):
    o, c, kh, kw = w.shape
    taps, shift_pad = _tap_geometry(kh, kw, (kh - 1) // 2, (kw - 1) // 2,
                                    width)
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = gamma.astype(jnp.float32) * inv
    shift = beta.astype(jnp.float32) - mean.astype(jnp.float32) * scale
    return _conv_apply(x2, _w_taps(w), scale, shift, residual,
                       relu=(act == "relu"), taps=taps,
                       shift_pad=shift_pad, width=width,
                       co_dtype=x2.dtype)


def _fused_infer_fwd(x2, w, gamma, beta, mean, var, residual, height, width,
                     eps, act):
    y = _fused_infer(x2, w, gamma, beta, mean, var, residual, height, width,
                     eps, act)
    return y, (x2, w, gamma, beta, mean, var, residual)


def _fused_infer_bwd(height, width, eps, act, res, dy):
    x2, w, gamma, beta, mean, var, residual = res

    def ref(x_, w_, g_, b_, r_):
        co = _conv_reference(x_, w_, height, width).astype(x_.dtype)
        return _epilogue_reference(co, g_, b_, r_, mean, var, eps, act)

    with_res = residual is not None
    _, vjp = jax.vjp(ref, x2, w, gamma, beta,
                     residual if with_res else None)
    dx2, dw, dg, db, dres = vjp(dy)
    return (dx2, dw, dg.astype(gamma.dtype), db.astype(beta.dtype),
            jnp.zeros_like(mean), jnp.zeros_like(var),
            dres.astype(residual.dtype) if with_res else None)


_fused_infer.defvjp(_fused_infer_fwd, _fused_infer_bwd)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def fused_conv_bn_act(x, w, gamma, beta, mean, var, *, strides, paddings,
                      eps, momentum, act=None, residual=None,
                      is_test=False, use_global_stats=False):
    """NCHW conv + BN + optional residual/relu with the fused kernels.

    Returns ``(y, mean_out, var_out, saved_mean, saved_var)`` with exactly
    the unfused ops' semantics (saved_* are None on the inference path,
    matching ``_batch_norm``). Callers must have checked
    :func:`use_pallas` — this function assumes a supported geometry."""
    n, c, h, w_dim = x.shape
    if tuple(strides) == (2, 2):  # exact for the supported 1x1 geometry
        x = x[:, :, ::2, ::2]
        h, w_dim = x.shape[2], x.shape[3]
    x2 = x.reshape(n, c, h * w_dim)
    o = w.shape[0]
    res2 = None
    if residual is not None:
        res2 = residual.reshape(n, o, h * w_dim)

    if is_test or use_global_stats:
        y2 = _fused_infer(x2, w, gamma, beta, mean, var, res2, h, w_dim,
                          float(eps), act)
        return (y2.reshape(n, o, h, w_dim), mean, var, None, None)

    y2, bm, bv = _fused_train(x2, w, gamma, beta, res2, h, w_dim,
                              float(eps), act)
    bm = jax.lax.stop_gradient(bm)
    bv = jax.lax.stop_gradient(bv)
    mean_out = momentum * mean + (1 - momentum) * bm
    var_out = momentum * var + (1 - momentum) * bv
    return y2.reshape(n, o, h, w_dim), mean_out, var_out, bm, bv

"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle Fluid 1.3 (reference at /root/reference; blueprint in
SURVEY.md).

Public surface mirrors ``paddle.fluid``:

    import paddle_tpu as fluid
    x = fluid.layers.data("x", shape=[784])
    y = fluid.layers.fc(x, size=10, act="softmax")
    ...
    exe = fluid.Executor(fluid.XLAPlace(0))
    exe.run(fluid.default_startup_program())
    loss_val, = exe.run(feed={...}, fetch_list=[loss])

Execution model: programs are symbolic op graphs compiled by whole-program
``jax.jit`` into single XLA computations with donated state (see
``core/executor.py``); parallelism is mesh sharding (see ``parallel/``).
"""

from .core import framework
from .core.framework import (  # noqa: F401
    Program, Variable, Parameter,
    default_main_program, default_startup_program, program_guard,
    name_scope)
from .core.executor import (  # noqa: F401
    Executor, Scope, global_scope, scope_guard,
    XLAPlace, TPUPlace, CPUPlace, CUDAPlace)
from .core.compiler import (  # noqa: F401
    CompiledProgram, BuildStrategy, ExecutionStrategy)
from .core.param_attr import ParamAttr  # noqa: F401
from .core import initializer  # noqa: F401
from .core import unique_name  # noqa: F401

from . import layers  # noqa: F401
from . import optimizer  # noqa: F401
from . import backward  # noqa: F401
from .backward import append_backward, calc_gradient, gradients  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import metrics  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import checkpoint  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from .async_executor import AsyncExecutor  # noqa: F401
from . import streaming  # noqa: F401
from . import contrib  # noqa: F401
from .data.data_feed import DataFeedDesc  # noqa: F401
from . import dygraph  # noqa: F401
from . import data  # noqa: F401
from .data.feeder import DataFeeder  # noqa: F401
from . import profiler  # noqa: F401
from . import obs  # noqa: F401
from . import debugger  # noqa: F401
from . import analysis  # noqa: F401
from . import dlpack  # noqa: F401
from . import parallel  # noqa: F401
from .version import __version__  # noqa: F401

# convenience re-exports matching fluid's top level
from .clip import set_gradient_clip  # noqa: F401


def memory_optimize(input_program=None, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """Ref ``python/paddle/fluid/transpiler/memory_optimization_transpiler.py``
    (var reuse by liveness). The XLA build gets buffer sharing/reuse from
    the compiler already; the knob that still matters on TPU is
    rematerialization, so this flips the program's backward to recompute
    forward activations in the backward pass (``jax.checkpoint``), trading
    FLOPs for peak HBM exactly like the reference trades copies for reuse."""
    from .core import framework as _fw

    prog = input_program or _fw.default_main_program()
    hit = False
    for op in prog.global_block().ops:
        if op.type == "autodiff":
            op.attrs["remat"] = True
            hit = True
    if hit:
        prog._version += 1
    elif print_log:
        print("memory_optimize: no backward in program; XLA buffer "
              "assignment already reuses forward buffers")
    return prog


def release_memory(input_program=None, skip_opt_set=None):
    """Ref ``release_memory`` (insert delete_var ops): subsumed — buffer
    donation + XLA liveness free buffers at their last use. Kept for API
    parity; returns the program unchanged."""
    from .core import framework as _fw

    return input_program or _fw.default_main_program()

"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle Fluid 1.3 (reference at /root/reference; blueprint in
SURVEY.md).

Public surface mirrors ``paddle.fluid``:

    import paddle_tpu as fluid
    x = fluid.layers.data("x", shape=[784])
    y = fluid.layers.fc(x, size=10, act="softmax")
    ...
    exe = fluid.Executor(fluid.XLAPlace(0))
    exe.run(fluid.default_startup_program())
    loss_val, = exe.run(feed={...}, fetch_list=[loss])

Execution model: programs are symbolic op graphs compiled by whole-program
``jax.jit`` into single XLA computations with donated state (see
``core/executor.py``); parallelism is mesh sharding (see ``parallel/``).
"""

from .core import framework
from .core.framework import (  # noqa: F401
    Program, Variable, Parameter,
    default_main_program, default_startup_program, program_guard,
    name_scope)
from .core.executor import (  # noqa: F401
    Executor, Scope, global_scope, scope_guard,
    XLAPlace, TPUPlace, CPUPlace, CUDAPlace)
from .core.compiler import (  # noqa: F401
    CompiledProgram, BuildStrategy, ExecutionStrategy)
from .core.param_attr import ParamAttr  # noqa: F401
from .core import initializer  # noqa: F401
from .core import unique_name  # noqa: F401

from . import layers  # noqa: F401
from . import optimizer  # noqa: F401
from . import backward  # noqa: F401
from .backward import append_backward, calc_gradient, gradients  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import metrics  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import checkpoint  # noqa: F401
from . import inference  # noqa: F401
from .async_executor import AsyncExecutor  # noqa: F401
from . import contrib  # noqa: F401
from .data.data_feed import DataFeedDesc  # noqa: F401
from . import dygraph  # noqa: F401
from . import data  # noqa: F401
from .data.feeder import DataFeeder  # noqa: F401
from . import profiler  # noqa: F401
from . import parallel  # noqa: F401
from .version import __version__  # noqa: F401

# convenience re-exports matching fluid's top level
from .clip import set_gradient_clip  # noqa: F401

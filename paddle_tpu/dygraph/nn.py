"""Dygraph layers (ref ``python/paddle/fluid/imperative/nn.py``: Conv2D,
Pool2D, FC, BatchNorm, Embedding + extras needed by BERT)."""

import math

import jax
import jax.numpy as jnp

from ..core.initializer import (ConstantInitializer, NormalInitializer,
                                XavierInitializer)
from .base import VarBase, record, to_variable
from .layers import Layer

__all__ = ["FC", "Linear", "Conv2D", "Conv2DTranspose", "Pool2D",
           "BatchNorm", "GroupNorm", "SpectralNorm", "Embedding",
           "LayerNorm", "Dropout", "PRelu", "GRUUnit",
           "BilinearTensorProduct", "NCE"]


class FC(Layer):
    def __init__(self, name_scope=None, size=None, input_dim=None,
                 num_flatten_dims=1, act=None, param_attr=None,
                 bias_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._num_flatten_dims = num_flatten_dims
        self._act = act
        self._input_dim = input_dim
        self._w = None
        self._b = None

    def _build_once(self, input_dim):
        self._w = self.create_parameter([input_dim, self._size])
        self._b = self.create_parameter([self._size], is_bias=True)

    def forward(self, x):
        import numpy as np

        x = to_variable(x)
        flat_in = int(np.prod(x.shape[self._num_flatten_dims:]))
        if self._w is None:
            self._build_once(flat_in)
        act, size, nfd = self._act, self._size, self._num_flatten_dims

        def fn(xv, w, b):
            if xv.dtype == jnp.bfloat16:  # compute follows activation
                w, b = w.astype(xv.dtype), b.astype(xv.dtype)
            xv2 = xv.reshape(int(np.prod(xv.shape[:nfd])), -1)
            out = (xv2 @ w + b).reshape(tuple(xv.shape[:nfd]) + (size,))
            if act == "gelu":
                # tanh-approx for bf16 activations, matching the static
                # op-registry AMP policy (opimpl/math_ops.py:_gelu)
                out = jax.nn.gelu(out,
                                  approximate=xv.dtype == jnp.bfloat16)
            elif act:
                out = getattr(jax.nn, act)(out) if hasattr(jax.nn, act) \
                    else getattr(jnp, act)(out)
            return out

        return record(fn, x, self._w, self._b)


Linear = FC


class Conv2D(Layer):
    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=3, stride=1, padding=0, dilation=1, groups=1,
                 act=None, dtype="float32", **kw):
        super().__init__(name_scope, dtype)
        k = filter_size if isinstance(filter_size, (list, tuple)) else (filter_size,) * 2
        self._stride = stride if isinstance(stride, (list, tuple)) else (stride,) * 2
        self._padding = padding if isinstance(padding, (list, tuple)) else (padding,) * 2
        self._dilation = dilation if isinstance(dilation, (list, tuple)) else (dilation,) * 2
        self._groups = groups
        self._act = act
        std = math.sqrt(2.0 / (k[0] * k[1] * num_channels))
        self._filter = self.create_parameter(
            [num_filters, num_channels // groups, k[0], k[1]],
            initializer=NormalInitializer(0.0, std))
        self._bias = self.create_parameter([num_filters], is_bias=True)

    def forward(self, x):
        stride, pad, dil = self._stride, self._padding, self._dilation
        groups, act = self._groups, self._act

        def fn(xv, w, b):
            out = jax.lax.conv_general_dilated(
                xv, w, window_strides=tuple(stride),
                padding=[(pad[0], pad[0]), (pad[1], pad[1])],
                rhs_dilation=tuple(dil), feature_group_count=groups,
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            out = out + b.reshape(1, -1, 1, 1)
            return jax.nn.relu(out) if act == "relu" else out

        return record(fn, to_variable(x), self._filter, self._bias)


class Pool2D(Layer):
    def __init__(self, name_scope=None, pool_size=2, pool_type="max",
                 pool_stride=2, pool_padding=0, global_pooling=False,
                 dtype="float32", **kw):
        super().__init__(name_scope, dtype)
        self._size = pool_size if isinstance(pool_size, (list, tuple)) else (pool_size,) * 2
        self._stride = pool_stride if isinstance(pool_stride, (list, tuple)) else (pool_stride,) * 2
        self._padding = pool_padding if isinstance(pool_padding, (list, tuple)) else (pool_padding,) * 2
        self._type = pool_type
        self._global = global_pooling

    def forward(self, x):
        size, stride_, pad = self._size, self._stride, self._padding
        gpool, ptype = self._global, self._type

        def fn(xv):
            if gpool:
                red = jnp.max if ptype == "max" else jnp.mean
                return red(xv, axis=(2, 3), keepdims=True)
            window = (1, 1) + tuple(size)
            stride = (1, 1) + tuple(stride_)
            pads = [(0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])]
            if ptype == "max":
                return jax.lax.reduce_window(xv, -jnp.inf, jax.lax.max,
                                             window, stride, pads)
            sm = jax.lax.reduce_window(xv, 0.0, jax.lax.add, window,
                                       stride, pads)
            return sm / (size[0] * size[1])

        return record(fn, to_variable(x))


class BatchNorm(Layer):
    def __init__(self, name_scope=None, num_channels=None, act=None,
                 momentum=0.9, epsilon=1e-5, dtype="float32", **kw):
        super().__init__(name_scope, dtype)
        c = num_channels
        self._scale = self.create_parameter(
            [c], initializer=ConstantInitializer(1.0))
        self._bias = self.create_parameter([c], is_bias=True)
        self._mean = VarBase(jnp.zeros((c,)), stop_gradient=True,
                             name=self._full_name + ".mean")
        self._var = VarBase(jnp.ones((c,)), stop_gradient=True,
                            name=self._full_name + ".var")
        self._momentum = momentum
        self._eps = epsilon
        self._act = act

    def forward(self, x):
        x = to_variable(x)
        xv = x.value()
        cshape = (1, -1) + (1,) * (xv.ndim - 2)
        eps, act = self._eps, self._act
        if self.training:
            # the eager stats here feed ONLY the running-average update;
            # the taped fn below recomputes them so its VJP stays correct
            # (pure-fn tape nodes recompute by design)
            axes = tuple(i for i in range(xv.ndim) if i != 1)
            mu = jnp.mean(xv, axis=axes)
            var = jnp.var(xv, axis=axes)
            self._mean._value = (self._momentum * self._mean.value()
                                 + (1 - self._momentum) * jax.lax.stop_gradient(mu))
            self._var._value = (self._momentum * self._var.value()
                                + (1 - self._momentum) * jax.lax.stop_gradient(var))

            def fn(xv_, scale, bias):
                m = jnp.mean(xv_, axis=axes)
                v = jnp.var(xv_, axis=axes)
                out = (xv_ - m.reshape(cshape)) * jax.lax.rsqrt(
                    v.reshape(cshape) + eps)
                out = out * scale.reshape(cshape) + bias.reshape(cshape)
                return jax.nn.relu(out) if act == "relu" else out

            return record(fn, x, self._scale, self._bias)

        def fn(xv_, scale, bias, mu, var):
            out = (xv_ - mu.reshape(cshape)) * jax.lax.rsqrt(
                var.reshape(cshape) + eps)
            out = out * scale.reshape(cshape) + bias.reshape(cshape)
            return jax.nn.relu(out) if act == "relu" else out

        return record(fn, x, self._scale, self._bias,
                      self._mean.value(), self._var.value())


class LayerNorm(Layer):
    def __init__(self, name_scope=None, normalized_shape=None, epsilon=1e-5,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._shape = list(normalized_shape)
        self._scale = self.create_parameter(
            self._shape, initializer=ConstantInitializer(1.0))
        self._bias = self.create_parameter(self._shape, is_bias=True)
        self._eps = epsilon

    def forward(self, x):
        x = to_variable(x)
        nshape, eps = len(self._shape), self._eps

        def fn(xv, scale, bias):
            in_dtype = xv.dtype
            if in_dtype == jnp.bfloat16:  # f32 stats, bf16-resident out
                xv = xv.astype(jnp.float32)
            axes = tuple(range(xv.ndim - nshape, xv.ndim))
            mu = jnp.mean(xv, axis=axes, keepdims=True)
            var = jnp.var(xv, axis=axes, keepdims=True)
            out = (xv - mu) * jax.lax.rsqrt(var + eps) * scale + bias
            return out.astype(in_dtype)

        return record(fn, x, self._scale, self._bias)


class Embedding(Layer):
    def __init__(self, name_scope=None, size=None, is_sparse=False,
                 padding_idx=None, dtype="float32", **kw):
        super().__init__(name_scope, dtype)
        self._size = size
        self._padding_idx = padding_idx
        self._w = self.create_parameter(
            list(size), initializer=XavierInitializer())

    def forward(self, ids):
        pad_idx = self._padding_idx

        def fn(iv, w):
            iv = iv.astype(jnp.int32)
            if iv.ndim >= 2 and iv.shape[-1] == 1:
                iv = iv.squeeze(-1)
            out = jnp.take(w, iv, axis=0)
            if pad_idx is not None:
                out = out * (iv != pad_idx)[..., None].astype(out.dtype)
            return out

        # integer ids carry no gradient; mark a LOCAL copy, never the
        # caller's VarBase
        ids = VarBase(to_variable(ids).value(), stop_gradient=True)
        return record(fn, ids, self._w)


class Dropout(Layer):
    _key = jax.random.PRNGKey(1234)

    def __init__(self, name_scope=None, p=0.5):
        super().__init__(name_scope)
        self._p = p

    def forward(self, x):
        from . import base

        x = to_variable(x)
        if not self.training or self._p == 0.0:
            return x
        sub = base.next_key()
        if sub is None:  # legacy eager stream
            Dropout._key, sub = jax.random.split(Dropout._key)
        p = self._p

        def fn(xv):
            keep = jax.random.bernoulli(sub, 1.0 - p, xv.shape)
            return xv * keep / (1.0 - p)

        return record(fn, x)


class PRelu(Layer):
    def __init__(self, name_scope=None, mode="all", dtype="float32"):
        super().__init__(name_scope, dtype)
        self._alpha = self.create_parameter(
            [1], initializer=ConstantInitializer(0.25))

    def forward(self, x):
        return record(lambda xv, a: jnp.where(xv > 0, xv, a * xv),
                      to_variable(x), self._alpha)


class Conv2DTranspose(Layer):
    """Ref ``imperative/nn.py``-era Conv2DTranspose wrapping
    ``conv2d_transpose_op`` (IOHW kernel layout)."""

    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=3, stride=1, padding=0, act=None,
                 dtype="float32", **kw):
        super().__init__(name_scope, dtype)

        def pair(v):
            return tuple(v) if isinstance(v, (list, tuple)) else (v, v)

        self._stride, self._pad = pair(stride), pair(padding)
        self._act = act
        fs = pair(filter_size)
        self._w = self.create_parameter(
            [num_channels, num_filters, fs[0], fs[1]])
        self._b = self.create_parameter([num_filters], is_bias=True)

    def forward(self, x):
        from ..core.opimpl.nn_ops import conv_transpose_nchw

        s, p, act = self._stride, self._pad, self._act

        def fn(xv, w, b):
            out = conv_transpose_nchw(xv, w, s, p, (1, 1))
            out = out + b.reshape(1, -1, 1, 1)
            if act:
                out = getattr(jax.nn, act)(out)
            return out

        return record(fn, to_variable(x), self._w, self._b)


class GroupNorm(Layer):
    """Ref ``group_norm_op`` as a module (NCHW)."""

    def __init__(self, name_scope=None, channels=None, groups=1,
                 epsilon=1e-5, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups
        self._eps = epsilon
        self._scale = self.create_parameter(
            [channels], initializer=ConstantInitializer(1.0))
        self._bias = self.create_parameter([channels], is_bias=True)

    def forward(self, x):
        g, eps = self._groups, self._eps

        def fn(xv, scale, bias):
            n, c = xv.shape[0], xv.shape[1]
            xg = xv.reshape((n, g, c // g) + xv.shape[2:])
            axes = tuple(range(2, xg.ndim))
            mu = jnp.mean(xg, axis=axes, keepdims=True)
            var = jnp.var(xg, axis=axes, keepdims=True)
            y = ((xg - mu) * jax.lax.rsqrt(var + eps)).reshape(xv.shape)
            cshape = (1, c) + (1,) * (xv.ndim - 2)
            return y * scale.reshape(cshape) + bias.reshape(cshape)

        return record(fn, to_variable(x), self._scale, self._bias)


class SpectralNorm(Layer):
    """Ref ``spectral_norm_op``: weight / sigma_max via power iteration
    (u, v buffers advance eagerly per call, matching the op's in-place
    U/V update)."""

    def __init__(self, name_scope=None, weight_shape=None, dim=0,
                 power_iters=1, eps=1e-12, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._dim = dim
        self._iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        # U/V are NO-GRAD buffers (ref spectral_norm_op: persistable
        # state advanced by the kernel, never optimizer-updated) — plain
        # arrays, not registered parameters
        key = jax.random.PRNGKey(17)
        ku, kv = jax.random.split(key)
        self._u = jax.random.normal(ku, (h,), jnp.float32)
        self._v = jax.random.normal(kv, (w,), jnp.float32)

    def forward(self, weight):
        weight = to_variable(weight)
        dim, iters, eps = self._dim, self._iters, self._eps

        # power iteration with the CURRENT buffers; sigma's u, v are
        # constants w.r.t. the gradient (the reference grad kernel treats
        # them as fixed vectors), so they enter fn by closure, not as
        # differentiable inputs
        wv = weight.value()
        wm0 = jax.lax.stop_gradient(
            jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1))
        u, v = self._u, self._v
        for _ in range(iters):
            v = wm0.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm0 @ v
            u = u / (jnp.linalg.norm(u) + eps)
        u = jax.lax.stop_gradient(u)
        v = jax.lax.stop_gradient(v)
        if not isinstance(wv, jax.core.Tracer):
            # eager: advance the buffers; under jit the advance is part of
            # the trace only (buffers hold concrete values across steps)
            self._u, self._v = u, v

        def fn(w_in):
            wm = jnp.moveaxis(w_in, dim, 0).reshape(w_in.shape[dim], -1)
            sigma = u @ wm @ v
            return w_in / sigma

        return record(fn, weight)


class BilinearTensorProduct(Layer):
    """Ref ``bilinear_tensor_product_op``: out_k = x^T W_k y + b_k."""

    def __init__(self, name_scope=None, input1_dim=None, input2_dim=None,
                 output_dim=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self._w = self.create_parameter(
            [output_dim, input1_dim, input2_dim])
        self._b = self.create_parameter([output_dim], is_bias=True)

    def forward(self, x, y):
        act = self._act

        def fn(xv, yv, w, b):
            out = jnp.einsum("bi,kij,bj->bk", xv, w, yv) + b
            if act:
                out = getattr(jax.nn, act)(out)
            return out

        return record(fn, to_variable(x), to_variable(y), self._w, self._b)


class NCE(Layer):
    """Ref ``imperative`` NCE wrapping ``nce_op``: noise-contrastive loss
    with uniform negative sampling."""

    def __init__(self, name_scope=None, num_total_classes=None, dim=None,
                 num_neg_samples=10, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._n_classes = num_total_classes
        self._n_neg = num_neg_samples
        self._w = self.create_parameter([num_total_classes, dim])
        self._b = self.create_parameter([num_total_classes], is_bias=True)

    _key = jax.random.PRNGKey(4321)

    def forward(self, x, label):
        from . import base

        n_cls, n_neg = self._n_classes, self._n_neg
        sub = base.next_key()
        if sub is None:  # own eager stream, independent of Dropout's
            NCE._key, sub = jax.random.split(NCE._key)
        label = VarBase(to_variable(label).value(), stop_gradient=True)

        def fn(xv, lv, w, b):
            lv = lv.reshape(-1).astype(jnp.int32)
            bsz = xv.shape[0]
            neg = jax.random.randint(sub, (bsz, n_neg), 0, n_cls)
            pos_logit = jnp.sum(xv * w[lv], axis=-1) + b[lv]
            neg_logit = jnp.einsum("bd,bnd->bn", xv, w[neg]) + b[neg]
            # uniform noise distribution q = 1/n_classes
            log_q = -jnp.log(float(n_cls))
            pos_loss = -jax.nn.log_sigmoid(pos_logit - log_q)
            neg_loss = -jnp.sum(
                jax.nn.log_sigmoid(-(neg_logit - log_q)), axis=-1)
            return (pos_loss + neg_loss).reshape(-1, 1)

        return record(fn, to_variable(x), label, self._w, self._b)


class GRUUnit(Layer):
    """Single-step GRU cell (ref ``imperative/nn.py`` GRUUnit wrapping
    ``gru_unit_op``): gates from [x_t | h_{t-1}]."""

    def __init__(self, name_scope=None, size=None, dtype="float32", **kw):
        super().__init__(name_scope, dtype)
        # size is 3*hidden (the reference convention)
        self._hidden = size // 3
        self._gate_w = None
        self._cand_w = None

    def _build_once(self, input_dim):
        h = self._hidden
        self._gate_w = self.create_parameter([input_dim + h, 2 * h])
        self._gate_b = self.create_parameter([2 * h], is_bias=True)
        self._cand_w = self.create_parameter([input_dim + h, h])
        self._cand_b = self.create_parameter([h], is_bias=True)

    def forward(self, x, hidden):
        x = to_variable(x)
        hidden = to_variable(hidden)
        if self._gate_w is None:
            self._build_once(x.shape[-1])
        h = self._hidden

        # the gate projection is computed ONCE; hidden/reset_pre are taped
        # children of the shared gate node (reference GRUUnit's 3-output
        # contract: updated_hidden, reset_hidden_pre, gate)
        gate = record(
            lambda xv, hv, gw, gb: jax.nn.sigmoid(
                jnp.concatenate([xv, hv], axis=-1) @ gw + gb),
            x, hidden, self._gate_w, self._gate_b)

        def fn_hidden(g, xv, hv, cw, cb):
            u, r = g[..., :h], g[..., h:]
            cat_r = jnp.concatenate([xv, r * hv], axis=-1)
            c = jnp.tanh(cat_r @ cw + cb)
            return u * hv + (1.0 - u) * c

        out = record(fn_hidden, gate, x, hidden, self._cand_w,
                     self._cand_b)
        reset_pre = record(lambda g, hv: g[..., h:] * hv, gate, hidden)
        return out, reset_pre, gate

"""Dygraph layers (ref ``python/paddle/fluid/imperative/nn.py``: Conv2D,
Pool2D, FC, BatchNorm, Embedding + extras needed by BERT)."""

import math

import jax
import jax.numpy as jnp

from ..core.initializer import (ConstantInitializer, NormalInitializer,
                                XavierInitializer)
from .base import VarBase, to_variable
from .layers import Layer

__all__ = ["FC", "Linear", "Conv2D", "Pool2D", "BatchNorm", "Embedding",
           "LayerNorm", "Dropout", "PRelu"]


def _v(x):
    return x.value() if isinstance(x, VarBase) else jnp.asarray(x)


class FC(Layer):
    def __init__(self, name_scope=None, size=None, input_dim=None,
                 num_flatten_dims=1, act=None, param_attr=None,
                 bias_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._num_flatten_dims = num_flatten_dims
        self._act = act
        self._input_dim = input_dim
        self._w = None
        self._b = None

    def _build_once(self, input_dim):
        self._w = self.create_parameter([input_dim, self._size])
        self._b = self.create_parameter([self._size], is_bias=True)

    def forward(self, x):
        xv = _v(x)
        lead = xv.shape[: self._num_flatten_dims]
        xv2 = xv.reshape(int(jnp.prod(jnp.asarray(lead))) if lead else 1, -1) \
            if xv.ndim != 2 else xv
        import numpy as np
        xv2 = xv.reshape(int(np.prod(lead)), -1)
        if self._w is None:
            self._build_once(xv2.shape[-1])
        out = xv2 @ self._w.value() + self._b.value()
        out = out.reshape(tuple(lead) + (self._size,))
        if self._act:
            out = getattr(jax.nn, self._act if self._act != "relu6"
                          else "relu6")(out) if hasattr(jax.nn, self._act) \
                else getattr(jnp, self._act)(out)
        return VarBase(out)


Linear = FC


class Conv2D(Layer):
    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=3, stride=1, padding=0, dilation=1, groups=1,
                 act=None, dtype="float32", **kw):
        super().__init__(name_scope, dtype)
        k = filter_size if isinstance(filter_size, (list, tuple)) else (filter_size,) * 2
        self._stride = stride if isinstance(stride, (list, tuple)) else (stride,) * 2
        self._padding = padding if isinstance(padding, (list, tuple)) else (padding,) * 2
        self._dilation = dilation if isinstance(dilation, (list, tuple)) else (dilation,) * 2
        self._groups = groups
        self._act = act
        std = math.sqrt(2.0 / (k[0] * k[1] * num_channels))
        self._filter = self.create_parameter(
            [num_filters, num_channels // groups, k[0], k[1]],
            initializer=NormalInitializer(0.0, std))
        self._bias = self.create_parameter([num_filters], is_bias=True)

    def forward(self, x):
        xv = _v(x)
        out = jax.lax.conv_general_dilated(
            xv, self._filter.value(), window_strides=tuple(self._stride),
            padding=[(self._padding[0], self._padding[0]),
                     (self._padding[1], self._padding[1])],
            rhs_dilation=tuple(self._dilation),
            feature_group_count=self._groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        out = out + self._bias.value().reshape(1, -1, 1, 1)
        if self._act == "relu":
            out = jax.nn.relu(out)
        return VarBase(out)


class Pool2D(Layer):
    def __init__(self, name_scope=None, pool_size=2, pool_type="max",
                 pool_stride=2, pool_padding=0, global_pooling=False,
                 dtype="float32", **kw):
        super().__init__(name_scope, dtype)
        self._size = pool_size if isinstance(pool_size, (list, tuple)) else (pool_size,) * 2
        self._stride = pool_stride if isinstance(pool_stride, (list, tuple)) else (pool_stride,) * 2
        self._padding = pool_padding if isinstance(pool_padding, (list, tuple)) else (pool_padding,) * 2
        self._type = pool_type
        self._global = global_pooling

    def forward(self, x):
        xv = _v(x)
        if self._global:
            red = jnp.max if self._type == "max" else jnp.mean
            return VarBase(red(xv, axis=(2, 3), keepdims=True))
        window = (1, 1) + tuple(self._size)
        stride = (1, 1) + tuple(self._stride)
        pads = [(0, 0), (0, 0),
                (self._padding[0], self._padding[0]),
                (self._padding[1], self._padding[1])]
        if self._type == "max":
            out = jax.lax.reduce_window(xv, -jnp.inf, jax.lax.max, window,
                                        stride, pads)
        else:
            s = jax.lax.reduce_window(xv, 0.0, jax.lax.add, window, stride,
                                      pads)
            out = s / (self._size[0] * self._size[1])
        return VarBase(out)


class BatchNorm(Layer):
    def __init__(self, name_scope=None, num_channels=None, act=None,
                 momentum=0.9, epsilon=1e-5, dtype="float32", **kw):
        super().__init__(name_scope, dtype)
        c = num_channels
        self._scale = self.create_parameter(
            [c], initializer=ConstantInitializer(1.0))
        self._bias = self.create_parameter([c], is_bias=True)
        self._mean = VarBase(jnp.zeros((c,)), stop_gradient=True,
                             name=self._full_name + ".mean")
        self._var = VarBase(jnp.ones((c,)), stop_gradient=True,
                            name=self._full_name + ".var")
        self._momentum = momentum
        self._eps = epsilon
        self._act = act

    def forward(self, x):
        xv = _v(x)
        cshape = (1, -1) + (1,) * (xv.ndim - 2)
        if self.training:
            axes = tuple(i for i in range(xv.ndim) if i != 1)
            mu = jnp.mean(xv, axis=axes)
            var = jnp.var(xv, axis=axes)
            self._mean._value = (self._momentum * self._mean.value()
                                 + (1 - self._momentum) * jax.lax.stop_gradient(mu))
            self._var._value = (self._momentum * self._var.value()
                                + (1 - self._momentum) * jax.lax.stop_gradient(var))
        else:
            mu, var = self._mean.value(), self._var.value()
        out = (xv - mu.reshape(cshape)) * jax.lax.rsqrt(
            var.reshape(cshape) + self._eps)
        out = out * self._scale.value().reshape(cshape) \
            + self._bias.value().reshape(cshape)
        if self._act == "relu":
            out = jax.nn.relu(out)
        return VarBase(out)


class LayerNorm(Layer):
    def __init__(self, name_scope=None, normalized_shape=None, epsilon=1e-5,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._shape = list(normalized_shape)
        self._scale = self.create_parameter(
            self._shape, initializer=ConstantInitializer(1.0))
        self._bias = self.create_parameter(self._shape, is_bias=True)
        self._eps = epsilon

    def forward(self, x):
        xv = _v(x)
        axes = tuple(range(xv.ndim - len(self._shape), xv.ndim))
        mu = jnp.mean(xv, axis=axes, keepdims=True)
        var = jnp.var(xv, axis=axes, keepdims=True)
        out = (xv - mu) * jax.lax.rsqrt(var + self._eps)
        out = out * self._scale.value() + self._bias.value()
        return VarBase(out)


class Embedding(Layer):
    def __init__(self, name_scope=None, size=None, is_sparse=False,
                 padding_idx=None, dtype="float32", **kw):
        super().__init__(name_scope, dtype)
        self._size = size
        self._padding_idx = padding_idx
        self._w = self.create_parameter(
            list(size), initializer=XavierInitializer())

    def forward(self, ids):
        iv = _v(ids).astype(jnp.int32)
        if iv.ndim >= 2 and iv.shape[-1] == 1:
            iv = iv.squeeze(-1)
        out = jnp.take(self._w.value(), iv, axis=0)
        if self._padding_idx is not None:
            out = out * (iv != self._padding_idx)[..., None].astype(out.dtype)
        return VarBase(out)


class Dropout(Layer):
    _key = jax.random.PRNGKey(1234)

    def __init__(self, name_scope=None, p=0.5):
        super().__init__(name_scope)
        self._p = p

    def forward(self, x):
        xv = _v(x)
        if not self.training or self._p == 0.0:
            return VarBase(xv)
        Dropout._key, sub = jax.random.split(Dropout._key)
        keep = jax.random.bernoulli(sub, 1.0 - self._p, xv.shape)
        return VarBase(xv * keep / (1.0 - self._p))


class PRelu(Layer):
    def __init__(self, name_scope=None, mode="all", dtype="float32"):
        super().__init__(name_scope, dtype)
        self._alpha = self.create_parameter(
            [1], initializer=ConstantInitializer(0.25))

    def forward(self, x):
        xv = _v(x)
        a = self._alpha.value()
        return VarBase(jnp.where(xv > 0, xv, a * xv))

"""Dygraph base (ref ``python/paddle/fluid/imperative/base.py``: ``guard:29``,
``to_variable:47``).

Eager mode runs jnp ops directly; ``VarBase`` wraps an array. Autodiff is
functional (``paddle_tpu.dygraph.grad`` / ``jit_train_step``) rather than a
tape — the dygraph→XLA path jits the module's pure apply function.
"""

import contextlib

import jax.numpy as jnp
import numpy as np

_dygraph_tracer = None


def _in_dygraph_mode():
    return _dygraph_tracer is not None


def enabled():
    return _in_dygraph_mode()


@contextlib.contextmanager
def guard(place=None):
    global _dygraph_tracer
    prev = _dygraph_tracer
    _dygraph_tracer = object()
    try:
        yield
    finally:
        _dygraph_tracer = prev


class VarBase:
    """Eager tensor (ref ``imperative/layer.h:113`` VarBase)."""

    def __init__(self, value, stop_gradient=False, name=None):
        self._value = jnp.asarray(value)
        self.stop_gradient = stop_gradient
        self.name = name

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    def numpy(self):
        return np.asarray(self._value)

    def value(self):
        return self._value

    def __repr__(self):
        return "VarBase(%s)" % (self._value,)


def to_variable(value, block=None, name=None):
    if isinstance(value, VarBase):
        return value
    return VarBase(np.asarray(value), name=name)

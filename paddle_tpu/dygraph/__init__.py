"""Imperative (dygraph) mode — ref ``python/paddle/fluid/imperative/``.

Eager execution over jax arrays with a Layer/module system; ``to_variable``
wraps arrays, autograd via jax transforms on ``Layer.__call__`` graphs.
"""

from . import base
from .base import (guard, to_variable, enabled, no_grad,  # noqa: F401
                   record, VarBase)
from .layers import Layer  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import (SGDOptimizer, AdamOptimizer,  # noqa: F401
                        MomentumOptimizer, AdagradOptimizer, LambOptimizer)

"""Dygraph optimizers (ref ``imperative`` mode's use of
``fluid.optimizer.*Optimizer(...).minimize(loss)`` over tape gradients).

Tape-native: ``minimize(loss)`` runs ``loss.backward()`` (unless grads are
already populated), applies the update to each parameter's value in place,
and clears gradients. Update rules mirror the static kernels
(``core/opimpl/optimizer_ops.py`` — ref ``operators/optimizers/``);
``regularization=L2Decay(c)`` folds ``c * p`` into the grad like the
static ``append_regularization_ops`` pass."""

import jax.numpy as jnp
import numpy as np

__all__ = ["SGDOptimizer", "AdamOptimizer", "MomentumOptimizer",
           "AdagradOptimizer", "LambOptimizer"]


class _DygraphOptimizer:
    def __init__(self, learning_rate, parameter_list, regularization=None):
        self._lr = learning_rate
        self._params = list(parameter_list)
        self._reg = regularization

    def _grad(self, p):
        g = p._grad
        if self._reg is not None:
            coeff = getattr(self._reg, "_coeff", 0.0)
            if type(self._reg).__name__.startswith("L1"):
                g = g + coeff * jnp.sign(p._value)
            else:
                g = g + coeff * p._value
        return g

    def minimize(self, loss, startup_program=None, parameter_list=None):
        if all(p._grad is None for p in self._params):
            loss.backward()
        for p in self._params:
            if p._grad is None:
                continue
            self._apply(p)
        self.clear_gradients()

    def clear_gradients(self):
        for p in self._params:
            p.clear_gradient()


class SGDOptimizer(_DygraphOptimizer):
    def _apply(self, p):
        p._value = p._value - self._lr * self._grad(p)


class MomentumOptimizer(_DygraphOptimizer):
    """Ref ``momentum_op.cc``: velocity accumulation (+ Nesterov)."""

    def __init__(self, learning_rate=1e-3, momentum=0.9,
                 use_nesterov=False, parameter_list=(),
                 regularization=None):
        super().__init__(learning_rate, parameter_list, regularization)
        self._mu = momentum
        self._nesterov = use_nesterov
        self._vel = {}

    def _apply(self, p):
        g = self._grad(p)
        v = self._vel.get(id(p), jnp.zeros_like(p._value))
        v = self._mu * v + g
        self._vel[id(p)] = v
        if self._nesterov:
            p._value = p._value - self._lr * (g + self._mu * v)
        else:
            p._value = p._value - self._lr * v


class AdagradOptimizer(_DygraphOptimizer):
    """Ref ``adagrad_op.cc``: accumulated squared grads."""

    def __init__(self, learning_rate=1e-2, epsilon=1e-6,
                 parameter_list=(), regularization=None):
        super().__init__(learning_rate, parameter_list, regularization)
        self._eps = epsilon
        self._acc = {}

    def _apply(self, p):
        g = self._grad(p)
        a = self._acc.get(id(p), jnp.zeros_like(p._value))
        a = a + g * g
        self._acc[id(p)] = a
        p._value = p._value - self._lr * g / (jnp.sqrt(a) + self._eps)


class AdamOptimizer(_DygraphOptimizer):
    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameter_list=(), regularization=None):
        super().__init__(learning_rate, parameter_list, regularization)
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        self._m = {}
        self._v = {}
        self._t = 0

    def minimize(self, loss, startup_program=None, parameter_list=None):
        self._t += 1
        super().minimize(loss, startup_program, parameter_list)

    def _apply(self, p):
        k = id(p)
        m = self._m.get(k, jnp.zeros_like(p._value))
        v = self._v.get(k, jnp.zeros_like(p._value))
        g = self._grad(p)
        m = self._b1 * m + (1 - self._b1) * g
        v = self._b2 * v + (1 - self._b2) * g * g
        self._m[k], self._v[k] = m, v
        corr = np.sqrt(1 - self._b2 ** self._t) / (1 - self._b1 ** self._t)
        p._value = p._value - self._lr * corr * m / (jnp.sqrt(v) + self._eps)


class LambOptimizer(_DygraphOptimizer):
    """LAMB (same rule as the static ``lamb`` kernel,
    ``optimizer_ops.py:_lamb``): adam direction + decoupled weight decay,
    scaled by the layer-wise trust ratio — the BERT-pretraining
    optimizer at TPU-pod batch sizes."""

    def __init__(self, learning_rate=1e-3, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 parameter_list=(), regularization=None):
        super().__init__(learning_rate, parameter_list, regularization)
        self._wd = lamb_weight_decay
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        self._m = {}
        self._v = {}
        self._t = 0

    def minimize(self, loss, startup_program=None, parameter_list=None):
        self._t += 1
        super().minimize(loss, startup_program, parameter_list)

    def _apply(self, p):
        k = id(p)
        m = self._m.get(k, jnp.zeros_like(p._value))
        v = self._v.get(k, jnp.zeros_like(p._value))
        g = self._grad(p)
        m = self._b1 * m + (1 - self._b1) * g
        v = self._b2 * v + (1 - self._b2) * g * g
        self._m[k], self._v[k] = m, v
        m_hat = m / (1 - self._b1 ** self._t)
        v_hat = v / (1 - self._b2 ** self._t)
        r = m_hat / (jnp.sqrt(v_hat) + self._eps) + self._wd * p._value
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p._value)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        p._value = p._value - self._lr * trust * r

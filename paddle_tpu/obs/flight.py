"""Flight recorder — a bounded ring of reliability decisions.

Every fault-site decision (``reliability/faults.py``), circuit-breaker
transition, worker/replica respawn, EDF displacement, deadline refusal,
and per-request outcome lands here as one small dict in a
``collections.deque(maxlen=...)``. Recording is always on (a deque
append under a lock — no I/O, bounded memory); *dumping* only happens
when ``PADDLE_TPU_FLIGHT=<dir>`` is set, on three triggers:

  * an unhandled exception (chained ``sys.excepthook``),
  * ``SIGUSR2`` (poke a live process for its recent history),
  * orderly shutdown (router/worker mains call :func:`maybe_dump`).

The dump is one JSON file per process, ``<dir>/flight-<pid>.json``,
carrying the event ring plus per-kind counts. ``tools/chaos_router.py``
audits it against the drill's accepted-request ledger: every accepted
request must appear as a ``request.outcome`` event — no silent losses.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time

ENV_FLIGHT_DIR = "PADDLE_TPU_FLIGHT"

DEFAULT_CAPACITY = 4096


class FlightRecorder:
    def __init__(self, capacity=DEFAULT_CAPACITY, clock=None):
        self.capacity = capacity
        self.clock = clock or time.monotonic
        self._ring = collections.deque(maxlen=capacity)
        self._counts = collections.Counter()
        self._lock = threading.Lock()
        self._dumped = {}

    def record(self, kind, **fields):
        ev = {"kind": kind, "t": self.clock(), "wall": time.time()}
        if fields:
            ev.update(fields)
        with self._lock:
            self._ring.append(ev)
            self._counts[kind] += 1
        return ev

    def events(self, kind=None):
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def counts(self):
        """Per-kind totals since start (not truncated by the ring)."""
        with self._lock:
            return dict(self._counts)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._counts.clear()

    def dump(self, path, reason="manual"):
        with self._lock:
            payload = {
                "reason": reason,
                "pid": os.getpid(),
                "wall": time.time(),
                "capacity": self.capacity,
                "counts": dict(self._counts),
                "events": list(self._ring),
            }
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        self._dumped[reason] = path
        return path


# Process-wide recorder. Always on; dump is opt-in via env.
RECORDER = FlightRecorder()


def record(kind, **fields):
    return RECORDER.record(kind, **fields)


def flight_dir():
    return os.environ.get(ENV_FLIGHT_DIR)


def dump_path():
    d = flight_dir()
    if not d:
        return None
    return os.path.join(d, "flight-%d.json" % os.getpid())


def maybe_dump(reason="shutdown"):
    """Dump the ring if ``PADDLE_TPU_FLIGHT`` is set; else a no-op."""
    path = dump_path()
    if path is None:
        return None
    return RECORDER.dump(path, reason=reason)


_installed = False
_prev_excepthook = None


def _excepthook(exc_type, exc, tb):
    try:
        record("crash", error=exc_type.__name__, message=str(exc)[:200])
        maybe_dump(reason="crash")
    except Exception:
        pass
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def _on_sigusr2(signum, frame):
    try:
        maybe_dump(reason="sigusr2")
    except Exception:
        pass


def install():
    """Hook sys.excepthook and SIGUSR2 for crash/poke dumps.

    Safe to call more than once; signal installation is skipped off the
    main thread (library code may call this from anywhere)."""
    global _installed, _prev_excepthook
    if _installed:
        return
    _prev_excepthook = sys.excepthook
    sys.excepthook = _excepthook
    if threading.current_thread() is threading.main_thread() and hasattr(signal, "SIGUSR2"):
        try:
            signal.signal(signal.SIGUSR2, _on_sigusr2)
        except (ValueError, OSError):
            pass
    _installed = True


def load(path):
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def load_dir(d):
    """Read every ``flight-*.json`` under a directory."""
    dumps = []
    if not os.path.isdir(d):
        return dumps
    for fn in sorted(os.listdir(d)):
        if fn.startswith("flight-") and fn.endswith(".json"):
            try:
                dumps.append(load(os.path.join(d, fn)))
            except ValueError:
                continue
    return dumps

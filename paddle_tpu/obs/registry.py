"""Named metric primitives with Prometheus-text exposition.

A :class:`Registry` holds :class:`Counter`, :class:`Gauge`, and
histogram entries by name and renders them in the Prometheus text
format (``# HELP`` / ``# TYPE`` + samples). ``ServingMetrics`` builds
its ~20 ad-hoc counters on one of these (satellite 2), the router's
ping path and the worker ``stats`` verb serve the rendered text, and
:data:`MFU` is the process-wide model-vs-measured gauge that
``Executor.run`` feeds under tracing.

Stdlib-only and import-light on purpose: this module must not import
jax, ``profiler``, or anything under ``serving`` — histograms are
duck-typed (anything with ``percentiles``/``count``/``total`` works,
which ``profiler.Histogram`` does) so the dependency points the right
way.
"""

from __future__ import annotations

import re
import threading

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _check_name(name):
    if not _NAME_RE.match(name):
        raise ValueError("invalid metric name %r (want %s)" % (name, _NAME_RE.pattern))
    return name


def _fmt(v):
    if v is None:
        return "NaN"  # Prometheus' spelling for a not-yet-observed value
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Counter:
    """Monotonic counter; thread-safe."""

    kind = "counter"
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name, help=""):
        self.name = _check_name(name)
        self.help = help
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def reset(self):
        with self._lock:
            self._value = 0

    def samples(self):
        return [(self.name, self._value)]


class Gauge:
    """Point-in-time value: either ``set()`` by hand or backed by a fn."""

    kind = "gauge"
    __slots__ = ("name", "help", "_value", "_fn", "_lock")

    def __init__(self, name, help="", fn=None):
        self.name = _check_name(name)
        self.help = help
        self._value = 0.0
        self._fn = fn
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = v

    def set_function(self, fn):
        self._fn = fn

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:
                return 0.0
        return self._value

    def samples(self):
        return [(self.name, self.value)]


class _HistogramEntry:
    """Wraps a duck-typed histogram (``profiler.Histogram``) for export.

    Rendered as a Prometheus summary: quantile samples plus ``_sum``
    and ``_count`` — the sliding-window percentiles the serving tier
    already keeps map onto quantiles, not cumulative buckets.
    """

    kind = "summary"
    __slots__ = ("name", "help", "hist", "quantiles")

    def __init__(self, name, hist, help="", quantiles=(0.5, 0.95, 0.99)):
        self.name = _check_name(name)
        self.help = help
        self.hist = hist
        self.quantiles = quantiles

    def samples(self):
        ps = self.hist.percentiles([q * 100.0 for q in self.quantiles])
        vals = list(ps.values())
        out = []
        for q, v in zip(self.quantiles, vals):
            out.append(('%s{quantile="%s"}' % (self.name, q), v))
        out.append((self.name + "_sum", self.hist.total))
        out.append((self.name + "_count", self.hist.count))
        return out


class Registry:
    """A namespace of metrics; renders Prometheus exposition text."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def counter(self, name, help=""):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Counter(name, help)
            elif not isinstance(m, Counter):
                raise TypeError("metric %r already registered as %s" % (name, m.kind))
            return m

    def gauge(self, name, help="", fn=None):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Gauge(name, help, fn=fn)
            elif not isinstance(m, Gauge):
                raise TypeError("metric %r already registered as %s" % (name, m.kind))
            elif fn is not None:
                m.set_function(fn)
            return m

    def histogram(self, name, hist, help="", quantiles=(0.5, 0.95, 0.99)):
        """Register an existing duck-typed histogram under ``name``."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = _HistogramEntry(name, hist, help, quantiles)
            elif not isinstance(m, _HistogramEntry):
                raise TypeError("metric %r already registered as %s" % (name, m.kind))
            return m

    def get(self, name):
        return self._metrics.get(name)

    def values(self):
        """{name: value} for counters and gauges (histograms excluded)."""
        with self._lock:
            items = list(self._metrics.items())
        return {n: m.value for n, m in items if isinstance(m, (Counter, Gauge))}

    def prometheus_text(self):
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines = []
        for name, m in metrics:
            if m.help:
                lines.append("# HELP %s %s" % (name, m.help))
            lines.append("# TYPE %s %s" % (name, m.kind))
            for sample_name, v in m.samples():
                lines.append("%s %s" % (sample_name, _fmt(v)))
        return "\n".join(lines) + "\n" if lines else ""


class MfuGauge:
    """Live model-vs-measured agreement fed by ``Executor.run``.

    Under tracing the executor records each step's measured wall time
    next to the ``analysis/cost.py`` roofline estimate for the same
    program+batch. ``mfu_vs_model`` is roofline/measured (1.0 = the
    static model explains the step exactly; <1 = slower than modeled),
    and ``mfu`` is achieved-FLOPs over the matmul ceiling.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self._steps = 0
            self._measured_s = 0.0
            self._roofline_s = 0.0
            self._flops = 0.0
            self._peak_flops = 0.0
            self._bound = None
            self._last_measured_s = 0.0

    def record(self, measured_s, roofline):
        """Record one executed step against its roofline dict."""
        if measured_s <= 0.0 or not roofline:
            return
        with self._lock:
            self._steps += 1
            self._measured_s += measured_s
            self._last_measured_s = measured_s
            self._roofline_s += roofline.get("roofline_s") or 0.0
            self._flops += roofline.get("flops") or 0.0
            ceil = roofline.get("ceilings") or {}
            self._peak_flops = ceil.get("matmul_flops") or self._peak_flops
            self._bound = roofline.get("bound", self._bound)

    def snapshot(self):
        with self._lock:
            if self._steps == 0:
                return {"steps": 0}
            measured = self._measured_s
            out = {
                "steps": self._steps,
                "measured_s": measured,
                "last_measured_s": self._last_measured_s,
                "roofline_s": self._roofline_s,
                "mfu_vs_model": (self._roofline_s / measured) if measured > 0 else 0.0,
                "bound": self._bound,
            }
            if self._peak_flops > 0 and measured > 0:
                out["mfu"] = (self._flops / measured) / self._peak_flops
            return out

    def prometheus_lines(self):
        snap = self.snapshot()
        if not snap.get("steps"):
            return []
        lines = [
            "# TYPE paddle_tpu_mfu_vs_model gauge",
            "paddle_tpu_mfu_vs_model %s" % _fmt(snap["mfu_vs_model"]),
            "# TYPE paddle_tpu_executor_steps_traced counter",
            "paddle_tpu_executor_steps_traced %s" % _fmt(snap["steps"]),
        ]
        if "mfu" in snap:
            lines.append("# TYPE paddle_tpu_mfu gauge")
            lines.append("paddle_tpu_mfu %s" % _fmt(snap["mfu"]))
        return lines


# Process-wide MFU gauge; Executor.run feeds it, metrics exposition and
# bench read it.
MFU = MfuGauge()

"""Dapper-style request tracing with an injectable clock.

Design goals, in priority order:

1. **Disabled = free.** Call sites run unconditionally in hot paths
   (``Executor.run``, the engine batch loop, every router request), so
   the off state must cost one module-global load and zero allocations.
   ``span(...)`` returns the preallocated falsy :data:`_NULL_SPAN`
   singleton when no tracer is installed — the same fast-path shape as
   ``reliability.faults.trip``. Tag dicts are only built when a span is
   live: guard with ``if sp: sp.set(...)``.
2. **Deterministic under a fake clock.** :class:`Tracer` takes
   ``clock=`` exactly like ``reliability/policy.py``; tests drive time
   by hand and never sleep. Exports convert the injected monotonic
   clock to wall time via an offset captured at tracer start, so real
   traces line up across processes while fake-clock traces stay exact.
3. **Cross-process stitching over the rpc header.** :func:`inject`
   writes ``header["trace"] = {"tid": ..., "sid": ...}`` beside
   ``deadline_s``; unknown header keys are ignored by old peers, so the
   wire format is unchanged. Unlike the deadline (re-derived per hop),
   the trace id propagates VERBATIM; each hop re-parents by re-injecting
   its own current span context before forwarding. :func:`extract`
   works without an active tracer so a hop that merely forwards does
   not need tracing enabled to preserve the id.

Spans are recorded into a bounded in-memory list and flushed as JSON
lines to ``<dir>/trace-<pid>.jsonl`` when ``PADDLE_TPU_TRACE=<dir>``
(or an explicit ``trace_dir=``) is set — one file per process, stitched
afterwards by trace id (``tools/trace_view.py``). :func:`chrome_trace`
converts span dicts to the chrome://tracing / Perfetto JSON array
format the reference emits from ``tools/timeline.py``.
"""

from __future__ import annotations

import atexit
import json
import os
import random
import threading
import time

ENV_TRACE_DIR = "PADDLE_TPU_TRACE"

# Header key carrying the propagated context; see serving/rpc.py docs.
HEADER_KEY = "trace"

_ID_BITS = 64


def _new_id() -> str:
    return "%016x" % random.getrandbits(_ID_BITS)


class Span:
    """One timed, named region. Truthy; use as a context manager."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "t1",
                 "tags", "tid", "_tracer")

    def __init__(self, tracer, name):
        self._tracer = tracer
        self.name = name
        self.trace_id = None
        self.span_id = None
        self.parent_id = None
        self.t0 = 0.0
        self.t1 = 0.0
        self.tags = None
        self.tid = 0

    def set(self, **tags):
        """Attach tags. Allocates only when the span is live."""
        if self.tags is None:
            self.tags = tags
        else:
            self.tags.update(tags)
        return self

    @property
    def duration(self):
        return self.t1 - self.t0

    def context(self):
        """(trace_id, span_id) of this span, for explicit parenting."""
        return (self.trace_id, self.span_id)

    def __bool__(self):
        return True

    def __enter__(self):
        self._tracer._enter(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.set(error=exc_type.__name__)
        self._tracer._exit(self)
        return False


class _NullSpan:
    """Falsy no-op stand-in returned when tracing is disabled."""

    __slots__ = ()

    def set(self, **tags):
        return self

    def context(self):
        return None

    @property
    def duration(self):
        return 0.0

    def __bool__(self):
        return False

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Collects spans for this process.

    ``clock`` follows the ``reliability/policy.py`` convention: any
    zero-arg callable returning monotonic seconds; ``None`` means
    ``time.perf_counter``. With a real clock, ``_wall_offset`` maps
    span times onto ``time.time()`` so multi-process exports align;
    with a fake clock the offset is forced to 0.0 so tests are exact.
    """

    def __init__(self, clock=None, trace_dir=None, max_spans=65536):
        self.clock = clock or time.perf_counter
        self._wall_offset = 0.0 if clock is not None else time.time() - self.clock()
        self.trace_dir = trace_dir
        self.max_spans = max_spans
        self.spans = []
        self.dropped = 0
        self._lock = threading.Lock()
        self._local = threading.local()
        self._flushed = 0

    # -- thread-local context stack -------------------------------------

    def _stack(self):
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self):
        """(trace_id, span_id) at the top of this thread's stack, or None."""
        st = getattr(self._local, "stack", None)
        if st:
            return st[-1]
        return None

    def activate(self, ctx):
        """Push a remote context (from :func:`extract`) as the ambient
        parent for this thread. Returns a token for :meth:`deactivate`."""
        st = self._stack()
        st.append(ctx)
        return len(st)

    def deactivate(self, token):
        st = self._stack()
        del st[token - 1:]

    # -- span lifecycle -------------------------------------------------

    def span(self, name, parent=None):
        sp = Span(self, name)
        if parent is not None:
            sp.trace_id, sp.parent_id = parent[0], parent[1]
        return sp

    def _enter(self, sp):
        if sp.trace_id is None:
            cur = self.current()
            if cur is not None:
                sp.trace_id, sp.parent_id = cur
            else:
                sp.trace_id = _new_id()
        sp.span_id = _new_id()
        sp.tid = threading.get_ident()
        self._stack().append((sp.trace_id, sp.span_id))
        sp.t0 = self.clock()

    def _exit(self, sp):
        sp.t1 = self.clock()
        st = self._stack()
        if st and st[-1] == (sp.trace_id, sp.span_id):
            st.pop()
        else:  # mis-nested exit: drop back to this span's frame if present
            try:
                idx = len(st) - 1 - st[::-1].index((sp.trace_id, sp.span_id))
                del st[idx:]
            except ValueError:
                pass
        rec = {
            "name": sp.name,
            "trace_id": sp.trace_id,
            "span_id": sp.span_id,
            "parent_id": sp.parent_id,
            "t0": sp.t0 + self._wall_offset,
            "dur": sp.t1 - sp.t0,
            "pid": os.getpid(),
            "tid": sp.tid,
        }
        if sp.tags:
            rec["tags"] = sp.tags
        with self._lock:
            if len(self.spans) < self.max_spans:
                self.spans.append(rec)
            else:
                self.dropped += 1

    # -- flushing -------------------------------------------------------

    def drain(self):
        """Return all recorded spans and clear the buffer."""
        with self._lock:
            out, self.spans = self.spans, []
            self._flushed = 0
        return out

    def flush(self):
        """Append spans recorded since the last flush to the trace file.

        No-op unless ``trace_dir`` is set. Keeps already-flushed spans
        out of the file on repeated calls (atexit + explicit flush)."""
        if not self.trace_dir:
            return None
        with self._lock:
            new = self.spans[self._flushed:]
            self._flushed = len(self.spans)
        if not new:
            return self.path()
        os.makedirs(self.trace_dir, exist_ok=True)
        path = self.path()
        with open(path, "a", encoding="utf-8") as f:
            for rec in new:
                f.write(json.dumps(rec) + "\n")
        return path

    def path(self):
        if not self.trace_dir:
            return None
        return os.path.join(self.trace_dir, "trace-%d.jsonl" % os.getpid())


# -- module-level fast-path API ----------------------------------------

_TRACER = None
_atexit_installed = False


def start(clock=None, trace_dir=None, max_spans=65536):
    """Install a process-global tracer and return it."""
    global _TRACER, _atexit_installed
    _TRACER = Tracer(clock=clock, trace_dir=trace_dir, max_spans=max_spans)
    if trace_dir and not _atexit_installed:
        atexit.register(_atexit_flush)
        _atexit_installed = True
    return _TRACER


def stop():
    """Flush (if a trace dir is set) and uninstall the global tracer."""
    global _TRACER
    t = _TRACER
    if t is not None:
        t.flush()
    _TRACER = None
    return t


def active():
    return _TRACER


def maybe_start_from_env(clock=None):
    """Start tracing if ``PADDLE_TPU_TRACE`` names a directory."""
    d = os.environ.get(ENV_TRACE_DIR)
    if d and _TRACER is None:
        return start(clock=clock, trace_dir=d)
    return _TRACER


def _atexit_flush():
    t = _TRACER
    if t is not None:
        t.flush()


def span(name, parent=None):
    """Open a span under the global tracer; falsy no-op when disabled."""
    t = _TRACER
    if t is None:
        return _NULL_SPAN
    return t.span(name, parent=parent)


def current():
    """Ambient (trace_id, span_id) for this thread, or None."""
    t = _TRACER
    if t is None:
        return None
    return t.current()


def inject(header, ctx=None):
    """Write the current (or given) context into an rpc header dict."""
    if ctx is None:
        t = _TRACER
        if t is None:
            return header
        ctx = t.current()
    if ctx is not None:
        header[HEADER_KEY] = {"tid": ctx[0], "sid": ctx[1]}
    return header


def extract(header):
    """Read a propagated context out of an rpc header, tracer or not."""
    c = header.get(HEADER_KEY)
    if not c:
        return None
    try:
        return (c["tid"], c["sid"])
    except (TypeError, KeyError):
        return None


def flush():
    t = _TRACER
    if t is None:
        return None
    return t.flush()


# -- export -------------------------------------------------------------

def chrome_trace(spans):
    """Convert span dicts to chrome://tracing "X" complete events."""
    events = []
    for s in spans:
        ev = {
            "name": s["name"],
            "ph": "X",
            "ts": s["t0"] * 1e6,
            "dur": s["dur"] * 1e6,
            "pid": s.get("pid", 0),
            "tid": s.get("tid", 0),
            "args": dict(s.get("tags") or {}),
        }
        ev["args"]["trace_id"] = s["trace_id"]
        ev["args"]["span_id"] = s["span_id"]
        if s.get("parent_id"):
            ev["args"]["parent_id"] = s["parent_id"]
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def load_dir(trace_dir):
    """Read every ``trace-*.jsonl`` under a directory into one span list."""
    spans = []
    if not os.path.isdir(trace_dir):
        return spans
    for fn in sorted(os.listdir(trace_dir)):
        if not (fn.startswith("trace-") and fn.endswith(".jsonl")):
            continue
        with open(os.path.join(trace_dir, fn), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        spans.append(json.loads(line))
                    except ValueError:
                        continue
    return spans

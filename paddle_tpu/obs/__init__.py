"""``paddle_tpu.obs`` — the stdlib-only telemetry plane (ISSUE 17).

Three pillars, each importable on its own and none touching jax (so the
serving router, the workers, and the tools can all load them in under a
millisecond):

  * :mod:`~paddle_tpu.obs.trace` — Dapper-style request tracing:
    context-manager spans with thread-local context, 64-bit trace/span
    ids, an injectable clock (the ``reliability/policy.py`` fake-clock
    discipline), cross-process propagation through the ``serving/rpc.py``
    frame header, and Perfetto/chrome-trace export. One
    ``RouterClient.predict`` yields ONE stitched trace spanning the
    router door, the dispatch hop, the worker queue, the engine
    micro-batch, and ``Executor.run``.
  * :mod:`~paddle_tpu.obs.registry` — named Counter/Gauge/Histogram
    primitives with Prometheus-text exposition, unifying
    ``ServingMetrics``' ad-hoc counters; plus the live MFU/roofline
    gauge ``Executor.run`` feeds under tracing.
  * :mod:`~paddle_tpu.obs.flight` — a bounded ring buffer of
    reliability events (fault-site decisions, breaker transitions,
    respawns, EDF displacements, deadline refusals, per-request
    outcomes), dumped as JSON on unhandled crash, SIGUSR2, and
    shutdown; ``tools/chaos_router.py`` audits the dump against its
    accepted-request ledger.

The disabled hot path costs zero allocations: ``trace.span(...)``
returns a module singleton when no tracer is active (the
``faults.trip`` fast-path pattern), and ``flight.record`` appends one
dict to a bounded deque — nothing grows without bound anywhere here.
"""

from . import flight, registry, trace  # noqa: F401

__all__ = ["trace", "registry", "flight"]

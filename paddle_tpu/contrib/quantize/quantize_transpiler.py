"""Quantization-aware training transpiler.

Reference: ``python/paddle/fluid/contrib/quantize/quantize_transpiler.py``
(``QuantizeTranspiler.training_transpile:145``, ``freeze_program:255``,
``convert_to_int8:371``). Same program-rewriting capability, re-designed
for the jax/XLA path: fake-quant ops carry straight-through gradients
(``core/opimpl/quant_ops.py``) so the QAT backward needs no grad-op
surgery, and per-channel weight scales map onto the MXU's preference for
channel-major quantized weights.

Contract: call ``training_transpile`` BEFORE ``minimize`` — the autodiff
op records the forward op list at minimize time, so fake-quant ops
inserted afterwards would be invisible to the backward.
"""

import json
import os

import numpy as np

from ...core import framework
from ...core.framework import Operator

__all__ = ["QuantizeTranspiler", "export_int8_params", "load_int8_params",
           "INT8_PARAMS_FILE", "INT8_MANIFEST_FILE"]

INT8_PARAMS_FILE = "params.int8.npz"
INT8_MANIFEST_FILE = "int8_manifest.json"

_QUANTIZABLE = {
    "mul": ("X", "Y"),
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
}


class QuantizeTranspiler:
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="channel_wise_abs_max",
                 moving_rate=0.9):
        if activation_quantize_type not in ("abs_max",
                                            "moving_average_abs_max"):
            raise ValueError("unknown activation_quantize_type %r"
                             % activation_quantize_type)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.act_type = activation_quantize_type
        self.weight_type = weight_quantize_type
        self.moving_rate = moving_rate
        self._weight_quants = {}  # weight var name -> quant axis

    # -- QAT ----------------------------------------------------------------
    def training_transpile(self, program=None, startup_program=None):
        """Insert fake-quant ops on the inputs + weights of every
        quantizable op, in place."""
        program = program or framework.default_main_program()
        startup = startup_program or framework.default_startup_program()
        block = program.global_block()
        if any(op.type == "autodiff" for op in block.ops):
            raise RuntimeError("training_transpile must run BEFORE "
                               "minimize()/append_backward")
        new_ops = []
        quanted = {}  # var name -> already-quantized replacement Variable
        for op in block.ops:
            if op.type in _QUANTIZABLE:
                act_slot, w_slot = _QUANTIZABLE[op.type]
                for slot in (act_slot, w_slot):
                    src = op.input(slot)
                    if src is None:
                        continue
                    if src.name in quanted:
                        op.inputs[slot] = [quanted[src.name]]
                        continue
                    is_weight = (slot == w_slot) and src.persistable
                    qv = self._insert_quant(block, startup, new_ops, src,
                                            is_weight, op)
                    quanted[src.name] = qv
                    op.inputs[slot] = [qv]
            new_ops.append(op)
        block.ops = new_ops
        program._version += 1
        return program

    def _insert_quant(self, block, startup, new_ops, src, is_weight, op):
        qv = block.create_var(
            name=src.name + ".quantized", shape=src.shape, dtype="float32")
        sv = block.create_var(
            name=src.name + ".quant_scale", shape=(), dtype="float32")
        if is_weight:
            # conv weights OIHW -> channel axis 0; mul weights [in, out]
            # -> output-channel axis 1
            axis = 0 if op.type != "mul" else 1
            self._weight_quants[src.name] = axis
            new_ops.append(Operator(
                block, "fake_channel_wise_quantize_abs_max",
                {"X": src}, {"Out": qv, "OutScale": sv},
                {"bit_length": self.weight_bits, "quant_axis": axis}))
        elif self.act_type == "abs_max":
            new_ops.append(Operator(
                block, "fake_quantize_abs_max", {"X": src},
                {"Out": qv, "OutScale": sv},
                {"bit_length": self.activation_bits}))
        else:
            state = block.create_var(name=src.name + ".quant_state",
                                     shape=(), dtype="float32",
                                     persistable=True)
            sb = startup.global_block()
            ssv = sb.create_var(name=state.name, shape=(),
                                dtype="float32", persistable=True)
            sb.append_op("fill_constant", outputs={"Out": ssv},
                         attrs={"shape": (), "dtype": "float32",
                                "value": 0.0})
            new_ops.append(Operator(
                block, "fake_quantize_moving_average_abs_max",
                {"X": src, "InScale": state},
                {"Out": qv, "OutScale": state},
                {"bit_length": self.activation_bits,
                 "moving_rate": self.moving_rate}))
        return qv

    # -- deployment ---------------------------------------------------------
    def freeze_program(self, program, place=None, scope=None):
        """Bake trained weights onto the quantization grid (in the scope)
        and drop the weight fake-quant ops; activation quant ops switch to
        their frozen (is_test) scales. Ref ``freeze_program:255``."""
        from ...core.executor import global_scope

        scope = scope or global_scope()
        block = program.global_block()
        kept = []
        for op in block.ops:
            if (op.type == "fake_channel_wise_quantize_abs_max"
                    and op.input("X").name in self._weight_quants):
                wname = op.input("X").name
                w = np.asarray(scope.get(wname))
                axis = self._weight_quants[wname]
                qw, _ = self._quant_np(w, axis)
                scope.set(wname, qw)  # weight now ON the int grid
                # rewire consumers of the quantized var back to the
                # (now pre-quantized) weight
                qname = op.output("Out").name
                for other in block.ops:
                    for slot, vs in other.inputs.items():
                        other.inputs[slot] = [
                            op.input("X") if v.name == qname else v
                            for v in vs]
                continue
            if op.type in ("fake_quantize_abs_max",
                           "fake_quantize_moving_average_abs_max"):
                op.attrs["is_test"] = True
            kept.append(op)
        block.ops = kept
        program._version += 1
        return program

    def convert_to_int8(self, program, scope=None):
        """Return {weight name: (int8 array, per-channel float scales)} for
        deployment storage (ref ``convert_to_int8:371`` rewrites vars to
        INT8 tensors; serialization-ready dict here)."""
        from ...core.executor import global_scope

        scope = scope or global_scope()
        out = {}
        for wname, axis in self._weight_quants.items():
            w = np.asarray(scope.get(wname))
            _, scale = self._quant_np(w, axis)
            qmax = float(2 ** (self.weight_bits - 1) - 1)
            i8 = np.round(
                np.clip(w / np.maximum(scale, 1e-8), -1, 1) * qmax
            ).astype(np.int8)
            out[wname] = (i8, scale.reshape(-1))
        return out

    def _quant_np(self, w, axis):
        red = tuple(i for i in range(w.ndim) if i != axis)
        scale = np.max(np.abs(w), axis=red, keepdims=True)
        qmax = float(2 ** (self.weight_bits - 1) - 1)
        s = np.maximum(scale, 1e-8)
        qw = np.round(np.clip(w / s, -1, 1) * qmax) / qmax * s
        return qw.astype(w.dtype), scale

    def export_int8(self, dirname, scope=None):
        """Write the deployable int8 export next to a
        ``save_inference_model`` directory: ``params.int8.npz`` holding
        each quantized weight as int8 + its per-channel scales, and an
        ``int8_manifest.json`` with the quant axes/bits. ``Predictor``
        (and therefore ``ServingEngine``) auto-detects the pair and
        serves from it (``AnalysisConfig.enable_int8``). Call AFTER
        ``freeze_program`` so the export round-trips losslessly onto the
        frozen quantization grid."""
        weights = self.convert_to_int8(None, scope=scope)
        return export_int8_params(
            dirname, weights,
            axes={n: a for n, a in self._weight_quants.items()},
            weight_bits=self.weight_bits)


def export_int8_params(dirname, weights, axes, weight_bits=8):
    """Serialize ``convert_to_int8``'s ``{name: (int8, scales)}`` dict.
    4x smaller on disk than the fp32 params; the load path
    (:func:`load_int8_params`) dequantizes back onto the exact
    quantization grid the frozen program computed with."""
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for name, (i8, scale) in weights.items():
        arrays[name] = np.asarray(i8, dtype=np.int8)
        arrays[name + "@scale"] = np.asarray(scale,
                                             dtype=np.float32).reshape(-1)
    path = os.path.join(dirname, INT8_PARAMS_FILE)
    np.savez(path, **arrays)
    with open(os.path.join(dirname, INT8_MANIFEST_FILE), "w") as f:
        json.dump({"weight_bits": int(weight_bits),
                   "weights": {n: int(a) for n, a in axes.items()}},
                  f, indent=1)
    return path


def load_int8_params(dirname, scope, require=False):
    """Load the int8 export into ``scope``, dequantizing each weight as
    ``(int8 / qmax) * scale`` — the exact expression ``freeze_program``
    baked, so int8-served outputs match the frozen fp32 model bit-for-bit
    up to float rounding. Returns the weight names loaded ([] when the
    directory carries no export; raises when ``require``)."""
    path = os.path.join(dirname, INT8_PARAMS_FILE)
    man_path = os.path.join(dirname, INT8_MANIFEST_FILE)
    if not (os.path.exists(path) and os.path.exists(man_path)):
        if require:
            raise ValueError(
                "int8 serving requested but %r has no %s/%s export "
                "(QuantizeTranspiler.export_int8 writes it)"
                % (dirname, INT8_PARAMS_FILE, INT8_MANIFEST_FILE))
        return []
    import jax.numpy as jnp

    with open(man_path) as f:
        man = json.load(f)
    qmax = float(2 ** (int(man.get("weight_bits", 8)) - 1) - 1)
    data = np.load(path, allow_pickle=False)
    loaded = []
    for name, axis in man["weights"].items():
        i8 = data[name]
        scale = data[name + "@scale"]
        shape = [1] * i8.ndim
        shape[int(axis)] = -1
        w = (i8.astype(np.float32) / qmax) * scale.reshape(shape)
        scope.set(name, jnp.asarray(w))
        loaded.append(name)
    return loaded

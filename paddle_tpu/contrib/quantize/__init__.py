from .quantize_transpiler import (QuantizeTranspiler,  # noqa: F401
                                  export_int8_params, load_int8_params)

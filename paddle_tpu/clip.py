"""Gradient clipping (ref ``python/paddle/fluid/clip.py``:
GradientClipByValue/Norm/GlobalNorm, set_gradient_clip,
append_gradient_clip_ops, ErrorClipByValue)."""

from .core.framework import Parameter

__all__ = ["GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "set_gradient_clip",
           "append_gradient_clip_ops", "ErrorClipByValue"]


class BaseGradientClipAttr:
    def _process(self, params_grads):
        raise NotImplementedError


class ErrorClipByValue:
    """Kept for API parity (ref clip.py ErrorClipByValue); forward-error
    clipping has no role when backward is exact vjp."""

    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip_one(self, block, grad):
        out = block.create_var(shape=grad.shape, dtype=str(grad.dtype))
        block.append_op("clip", {"X": grad}, {"Out": out},
                        {"min": self.min, "max": self.max})
        return out

    def _process(self, params_grads):
        out = []
        for p, g in params_grads:
            block = p.block.program.global_block()
            out.append((p, self._clip_one(block, g)))
        return out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        out = []
        for p, g in params_grads:
            block = p.block.program.global_block()
            o = block.create_var(shape=g.shape, dtype=str(g.dtype))
            block.append_op("clip_by_norm", {"X": g}, {"Out": o},
                            {"max_norm": self.clip_norm})
            out.append((p, o))
        return out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        if not params_grads:
            return params_grads
        block = params_grads[0][0].block.program.global_block()
        sq_norms = []
        for _, g in params_grads:
            sq = block.create_var(shape=(), dtype=str(g.dtype))
            block.append_op("squared_l2_norm", {"X": g}, {"Out": sq}, {})
            sq_norms.append(sq)
        total = block.create_var(shape=(), dtype="float32")
        block.append_op("sum", {"X": sq_norms}, {"Out": total}, {})
        gnorm = block.create_var(shape=(), dtype="float32")
        block.append_op("sqrt", {"X": total}, {"Out": gnorm}, {})
        # scale = clip / max(gnorm, clip)
        maxed = block.create_var(shape=(), dtype="float32")
        clip_c = block.create_var(shape=(), dtype="float32")
        block.append_op("fill_constant", outputs={"Out": clip_c},
                        attrs={"shape": (), "dtype": "float32",
                               "value": self.clip_norm})
        block.append_op("elementwise_max", {"X": gnorm, "Y": clip_c},
                        {"Out": maxed}, {})
        factor = block.create_var(shape=(), dtype="float32")
        block.append_op("elementwise_div", {"X": clip_c, "Y": maxed},
                        {"Out": factor}, {})
        out = []
        for p, g in params_grads:
            o = block.create_var(shape=g.shape, dtype=str(g.dtype))
            block.append_op("elementwise_mul", {"X": g, "Y": factor},
                            {"Out": o}, {"axis": -1})
            out.append((p, o))
        return out


_global_clip = None


def set_gradient_clip(clip, param_list=None, program=None):
    """Set a program-wide clip strategy (ref ``clip.py`` set_gradient_clip);
    per-param ``ParamAttr.gradient_clip`` overrides it."""
    global _global_clip
    _global_clip = clip
    if param_list:
        for p in param_list:
            if isinstance(p, Parameter):
                p.gradient_clip_attr = clip


def append_gradient_clip_ops(params_grads):
    # partition by clip attr: per-param attrs first, else global
    if not params_grads:
        return params_grads
    default = []
    out = []
    for p, g in params_grads:
        attr = getattr(p, "gradient_clip_attr", None) or _global_clip
        if attr is None:
            out.append((p, g))
        else:
            default.append((attr, p, g))
    # group global-norm clips so the norm is computed across the whole group
    by_attr = {}
    for attr, p, g in default:
        by_attr.setdefault(id(attr), (attr, []))[1].append((p, g))
    # Sparse (rows, values) grads flow through the same clip ops: the
    # autodiff emits them row-merged with zeros in duplicate slots (we
    # request that below), so a squared_l2_norm over the values equals the
    # dense-grad norm, and an elementwise scale of the values scales the
    # logical dense grad (ref clip.py merges SelectedRows before clipping
    # for the same reason).
    sparse_rows = {p.name: g.sparse_rows_var for _, p, g in default
                   if getattr(g, "sparse_rows_var", None) is not None}
    if sparse_rows:
        from .backward import require_merged_sparse
        require_merged_sparse(default[0][1].block.program)
    for attr, group in by_attr.values():
        processed = attr._process(group)
        for p, g in processed:
            if p.name in sparse_rows:
                g.sparse_rows_var = sparse_rows[p.name]
        out.extend(processed)
    return out

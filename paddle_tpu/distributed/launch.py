"""Multi-process launcher (ref ``python/paddle/distributed/launch.py``):

    python -m paddle_tpu.distributed.launch --nproc_per_node=2 \\
        [--started_port 6170] [--log_dir logs] \\
        [--max_restarts N --restart_backoff S] train.py [args...]

Spawns one worker per process slot with the PADDLE_TRAINER_* env protocol
(``PADDLE_TRAINER_ID``, ``PADDLE_TRAINER_ENDPOINTS``,
``PADDLE_CURRENT_ENDPOINT``) that ``parallel/env.py:init_distributed``
consumes to form the jax.distributed world. Multi-node: pass
``--cluster_node_ips`` + ``--node_ip`` and run the launcher once per node,
exactly like the reference.

Failure semantics (default): first worker failure terminates the rest and
the launcher exits with that worker's code (the reference's fate-sharing
behavior, which external whole-job restart setups rely on).

Elastic mode (``--max_restarts N``): a worker crash restarts the WHOLE
local group up to N times, with an exponential ``--restart_backoff``
schedule (``paddle_tpu.reliability.RetryPolicy``) between attempts;
state recovery is the workers' job via ``checkpoint.resume_or_init`` /
``AutoCheckpoint`` (SURVEY §5.3 — the pserver ``checkpoint_notify`` +
external-restart analog). Group restart — not restart-in-place of the
one crashed process — because a jax.distributed world is all-or-nothing:
a surviving peer would hang in its next collective waiting for the lost
rank, and a respawned rank cannot rejoin an already-initialized world.
Exhausting the budget falls back to fate-sharing. Worker log files are
flushed/closed before a restart and reopened in append mode so one
``workerlog.<id>`` carries the whole incarnation history; workers can
read ``PADDLE_RESTART_COUNT`` to tell which incarnation they are.

SIGTERM/SIGINT to the launcher are forwarded to the workers and the
workers are reaped — Ctrl-C never orphans the subprocess tree.
"""

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "reap_procs"]


def reap_procs(procs, sig=signal.SIGTERM, grace_s=10.0):
    """Signal every live ``Popen`` in ``procs`` and wait it out;
    stragglers get SIGKILL. The one way any supervisor here (this
    launcher, ``serving.router.Router``) ends a child — never orphan
    the subprocess tree, never wait unboundedly."""
    live = [p for p in procs if p is not None and p.poll() is None]
    for p in live:
        try:
            p.send_signal(sig)
        except OSError:
            pass
    deadline = time.time() + grace_s
    for p in live:
        try:
            p.wait(max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            try:
                p.wait(5.0)
            except subprocess.TimeoutExpired:
                pass


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description=__doc__.splitlines()[0])
    ap.add_argument("--nproc_per_node", type=int, default=1)
    ap.add_argument("--cluster_node_ips", type=str, default="127.0.0.1")
    ap.add_argument("--node_ip", type=str, default="127.0.0.1")
    ap.add_argument("--started_port", type=int, default=6170)
    ap.add_argument("--log_dir", type=str, default=None)
    ap.add_argument("--max_restarts", type=int, default=0,
                    help="whole-group crash-restart budget (0 = "
                         "fate-sharing, the default)")
    ap.add_argument("--restart_backoff", type=float, default=1.0,
                    help="base seconds before a group restart "
                         "(doubles per attempt)")
    ap.add_argument("training_script", type=str)
    ap.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return ap.parse_args(argv)


class _Worker:
    """One process slot: its trainer id, live Popen, open log file, and
    which restart incarnation it is on."""

    __slots__ = ("tid", "proc", "log", "restarts")

    def __init__(self, tid):
        self.tid = tid
        self.proc = None
        self.log = None
        self.restarts = 0

    def close_log(self):
        if self.log is not None:
            try:
                self.log.flush()
                self.log.close()
            except OSError:
                pass
            self.log = None


def _reap(workers, sig=signal.SIGTERM, grace_s=10.0):
    """Signal every live worker and wait it out; stragglers get SIGKILL."""
    reap_procs([w.proc for w in workers], sig=sig, grace_s=grace_s)


def launch(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    ips = args.cluster_node_ips.split(",")
    if args.node_ip not in ips:
        sys.exit("--node_ip %s not in --cluster_node_ips %s"
                 % (args.node_ip, args.cluster_node_ips))
    endpoints = [
        "%s:%d" % (ip, args.started_port + i)
        for ip in ips for i in range(args.nproc_per_node)
    ]
    node_rank = ips.index(args.node_ip)
    local_ids = range(node_rank * args.nproc_per_node,
                      (node_rank + 1) * args.nproc_per_node)

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    # per-worker backoff schedule, shared with the serving layer's retry
    # machinery: base * 2**k, deterministic (jitter would only desync the
    # operator's expectations here)
    if args.max_restarts > 0:
        from ..reliability import RetryPolicy

        backoffs = RetryPolicy(max_attempts=args.max_restarts + 1,
                               base_delay_s=args.restart_backoff,
                               max_delay_s=60.0, multiplier=2.0,
                               jitter=0.0).delays()
    else:
        backoffs = []

    def spawn(w, log_mode="w"):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(w.tid),
            "PADDLE_TRAINERS_NUM": str(len(endpoints)),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_CURRENT_ENDPOINT": endpoints[w.tid],
            "PADDLE_RESTART_COUNT": str(w.restarts),
        })
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        if args.log_dir:
            w.log = open(os.path.join(args.log_dir,
                                      "workerlog.%d" % w.tid), log_mode)
        w.proc = subprocess.Popen(cmd, env=env, stdout=w.log,
                                  stderr=subprocess.STDOUT
                                  if w.log else None)

    workers = [_Worker(tid) for tid in local_ids]
    for w in workers:
        spawn(w)

    # forward termination to the workers: SIGTERM raises into the wait
    # loop, which tears the tree down on the same path as Ctrl-C. Only
    # installable from the main thread (in-process/test callers elsewhere
    # keep their own handling).
    def _on_term(signum, frame):
        raise KeyboardInterrupt
    prev_term = None
    try:
        prev_term = signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass

    rc = 0
    restarts_used = 0
    remaining = {w.tid: w for w in workers}
    try:
        while remaining:
            crashed = None
            for tid, w in list(remaining.items()):
                code = w.proc.poll()
                if code is None:
                    continue
                if code == 0:
                    w.close_log()
                    del remaining[tid]
                    continue
                crashed = (tid, code)
                break
            if crashed is not None:
                tid, code = crashed
                if restarts_used < args.max_restarts:
                    # elastic: tear the WHOLE group down (a partial world
                    # would hang in its next collective), flush/close the
                    # logs, back off, respawn everyone; each worker
                    # recovers its own state through
                    # resume_or_init/AutoCheckpoint
                    delay = (backoffs[restarts_used]
                             if restarts_used < len(backoffs)
                             else backoffs[-1] if backoffs else 0.0)
                    restarts_used += 1
                    print("launch: worker %d exited %d; restarting the "
                          "group (%d/%d) in %.1fs"
                          % (tid, code, restarts_used, args.max_restarts,
                             delay), file=sys.stderr)
                    _reap(list(remaining.values()))
                    for w in workers:
                        w.close_log()
                    time.sleep(delay)
                    for w in workers:
                        w.restarts = restarts_used
                        spawn(w, log_mode="a")
                    remaining = {w.tid: w for w in workers}
                    continue
                # fate-sharing: budget spent (or elastic mode off)
                rc = code
                del remaining[tid]
                _reap(list(remaining.values()))
                remaining = {}
                break
            time.sleep(0.2)
    except KeyboardInterrupt:
        # Ctrl-C / SIGTERM: forward and reap — never orphan the workers
        _reap(list(remaining.values()))
        rc = 128 + signal.SIGTERM
    finally:
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
        for w in workers:
            w.close_log()
    return rc


if __name__ == "__main__":
    sys.exit(launch())

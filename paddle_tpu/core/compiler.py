"""CompiledProgram: multi-device execution strategies.

Reference: ``python/paddle/fluid/compiler.py:62`` (CompiledProgram +
``with_data_parallel:116``) wrapping the C++ ParallelExecutor
(``parallel_executor.cc:184``) — SSA graph, NCCL allreduce insertion,
threaded dataflow scheduling. The TPU-native equivalent is declarative:
choose a ``jax.sharding.Mesh`` and shard the batch axis (data parallel)
and/or parameter axes (tensor parallel / sharded "reduce mode"); GSPMD
inserts and schedules the collectives over ICI.

BuildStrategy/ExecutionStrategy are accepted for API parity; the knobs that
have TPU meaning are mapped (reduce_strategy -> parameter sharding a la
ZeRO), the rest are no-ops documented as subsumed by XLA.
"""

import jax

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


class ExecutionStrategy:
    """Accepted for parity (ref ``pybind.cc:1021``); XLA owns scheduling."""

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 1


class BuildStrategy:
    """Ref ``details/build_strategy.h:35-140``. ``reduce_strategy=Reduce``
    shards optimizer accumulators over the dp axis (ZeRO-style; see
    ``executor._mesh_shardings``) — the capability the reference implements
    with ReduceOpHandle parameter-partitioning. Verified by
    ``tests/test_parallel.py::test_zero_reduce_strategy_shards_optimizer_state``."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.memory_optimize = True   # XLA buffer assignment: always on
        self.enable_inplace = True    # buffer donation: always on
        self.fuse_elewise_add_act_ops = True  # XLA fusion: always on
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class CompiledProgram:
    def __init__(self, program):
        self._program = program
        self._mesh = None
        self._dp_axis = None
        self._sp_axis = None
        self._build_strategy = None
        self._exec_strategy = None
        self._seq_feeds = None
        self._pp_axis = None
        self._pp_boundaries = None
        self._pp_nmicro = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None, mesh=None, dp_axis="dp",
                           sp_axis=None, sequence_feeds=None):
        """Shard the batch over a device mesh axis (ref
        ``compiler.py:116``). ``mesh`` defaults to a 1-D mesh over all local
        devices — the analog of ParallelExecutor claiming all visible GPUs.

        ``sequence_feeds``: with ``sp_axis`` set, the feed names whose dim 1
        is the sequence axis to shard — model specs carry them as
        ``spec.sequence_feeds``. With None, feeds shard on dp only,
        unless PADDLE_TPU_SP_HEURISTIC=1 opts into the longest-dim-1
        shape guess (a warning names the classified feeds)."""
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._dp_axis = dp_axis
        self._sp_axis = sp_axis
        self._seq_feeds = (tuple(sorted(sequence_feeds))
                           if sequence_feeds is not None else None)
        self._mesh = mesh
        self._places = places
        return self

    def with_pipeline(self, loss_name=None, mesh=None, pp_axis="pp",
                      boundaries=None, n_microbatches=None):
        """Pipeline-parallel training over ``mesh``'s ``pp_axis``.

        The program's forward is split into ``mesh.shape[pp_axis]`` stages
        at the producers of the named ``boundaries`` variables; each device
        runs its stage, microbatches ride a ppermute ring, and the backward
        (via the program's autodiff op) follows the GPipe reverse schedule.
        New TPU-first capability — the 2019 reference has no pipeline
        engine (SURVEY §2.5D); contrast ``pipeline_apply`` for the raw
        homogeneous-stack form.

        Per-microbatch losses are averaged (the data-parallel convention).
        Fetching forward activations other than the loss falls back to a
        replicated recompute of those ops. ``n_microbatches`` defaults to
        the number of stages."""
        if not boundaries:
            raise ValueError("with_pipeline requires boundaries: the "
                             "activation var names to cut stages at")
        self._pp_axis = pp_axis
        self._pp_boundaries = tuple(
            b.name if hasattr(b, "name") else str(b) for b in boundaries)
        self._pp_nmicro = n_microbatches
        self._mesh = mesh
        self._places = None
        return self

    def with_inference_optimize(self, config=None):
        # analysis passes are subsumed by XLA; keep chainable API
        return self

    def _resolve_mesh(self):
        if self._mesh is not None:
            return self._mesh
        from jax.sharding import Mesh
        import numpy as np
        devices = self._places or jax.devices()
        axis = self._pp_axis or self._dp_axis or "dp"
        self._mesh = Mesh(np.array(devices), (axis,))
        return self._mesh

"""Static per-op cost rules (FLOPs + HBM bytes + row ops) — ISSUE 15.

Registered via :func:`core.op_registry.register_cost`, beside the shape
rules; consumed by ``analysis/cost.py``'s :func:`estimate_program`. The
registry-parity test (``tests/test_cost_engine.py``) holds every op with
a shape rule to having a cost rule (or an explicit zero-cost
registration), so a new op cannot silently fall out of the roofline.

Modeling convention — the FLOOR stance of the committed per-bucket
rooflines (``tools/attribute_resnet.py`` pre-refactor, now delegated
here; ``RESNET_ROOFLINE.json``'s note):

  * elementwise/activation/reduction/cast ops ride a producer's fusion
    epilogue: zero extra HBM traffic, FLOPs counted;
  * irreducible passes charge bytes: matmul/conv operand streams,
    same-shape residual merges (2R+1W of a distant tensor), transposes
    (a real relayout), max-pool select-and-scatter, optimizer state
    passes (master precision, f32);
  * conv backward carries the BN/relu riders the fused lowering pays:
    one extra full activation pass on each of the dX and dW fusions
    (relu mask + BN x-hat reads ride dX, dgamma/dbeta reduction reads
    ride dW) — batch_norm itself then charges zero, exactly the
    committed accounting;
  * embedding lookups / scatter-adds charge ROWS, not bytes (TPU row
    ops are latency-bound — ``ROW_OP_FLOORS.json``); the roofline adds
    the row term on top of max(compute, HBM).

Backward columns (``bwd_*``) are charged only for ops an ``autodiff``
op actually replays — the engine handles that; rules just fill both.
"""

from ..op_registry import register_cost, register_zero_cost
from .shape_rules import _COMPARE, _ELEMENTWISE, _LIKE_X, _LOGICAL


def _prod(dims):
    n = 1
    for d in dims:
        n *= int(d)
    return n


def _nel(ctx, var):
    return None if var is None else ctx.nelems(var)


# ---------------------------------------------------------------------------
# elementwise / unary / reductions: FLOPs only (epilogue-fused floor),
# except same-shape >=3-D merges (residual adds) which read a distant
# tensor: 2 reads + 1 write, the committed residual accounting
# ---------------------------------------------------------------------------

def _elementwise_cost(ctx, op):
    out = op.output("Out")
    n = _nel(ctx, out)
    if n is None:
        ctx.add(op, unresolved=True)
        return
    xs = ctx.shape(op.input("X"))
    ys = ctx.shape(op.input("Y"))
    merge = (xs is not None and ys is not None and xs == ys
             and len(xs) >= 3)
    ctx.add(op, flops=n, bwd_flops=n,
            hbm_bytes=3 * n * ctx.esize(out) if merge else 0,
            note="residual merge: 2R+1W" if merge else None)


for _n in _ELEMENTWISE:
    register_cost(_n)(_elementwise_cost)


def _flops_like_out(ctx, op, slot="Out", per_elem=1):
    v = op.output(slot) or op.output("Y")
    n = _nel(ctx, v)
    if n is None:
        ctx.add(op, unresolved=True)
        return
    ctx.add(op, flops=per_elem * n, bwd_flops=per_elem * n)


for _n in _COMPARE + _LOGICAL + ("logical_not",):
    register_cost(_n)(_flops_like_out)

for _n in _LIKE_X:
    register_cost(_n)(_flops_like_out)

register_cost("dropout")(_flops_like_out)


def _reduce_cost(ctx, op):
    n = _nel(ctx, op.input("X"))
    if n is None:
        ctx.add(op, unresolved=True)
        return
    ctx.add(op, flops=n, bwd_flops=n)


for _n in ("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
           "reduce_prod", "mean"):
    register_cost(_n)(_reduce_cost)


@register_cost("sum")
def _sum_cost(ctx, op):
    vs = op.input_list("X")
    ns = [_nel(ctx, v) for v in vs]
    if any(n is None for n in ns):
        ctx.add(op, unresolved=True)
        return
    ctx.add(op, flops=sum(ns), bwd_flops=sum(ns))


# views / scalar bookkeeping / trace-time constants: fold away
register_zero_cost(
    "cast", "reshape", "reshape2", "squeeze", "squeeze2", "unsqueeze",
    "unsqueeze2", "flatten", "flatten2", "concat", "split", "stack",
    "slice", "expand", "shape", "fill_constant", "uniform_random",
    "gaussian_random", "truncated_gaussian_random",
    "fill_constant_batch_size_like", "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like", "accuracy")


@register_cost("transpose", "transpose2")
def _transpose_cost(ctx, op):
    # a real relayout: read + write, both directions (the seq-2048
    # head-split copies were exactly this bucket)
    n = _nel(ctx, op.input("X"))
    if n is None:
        ctx.add(op, unresolved=True)
        return
    e = ctx.esize(op.input("X"))
    ctx.add(op, hbm_bytes=2 * n * e, bwd_hbm_bytes=2 * n * e)


# ---------------------------------------------------------------------------
# matmul family: 2MNK forward, 4MNK backward (dX + dW); operand streams
# ---------------------------------------------------------------------------

@register_cost("mul")
def _mul_cost(ctx, op):
    xv, yv = op.input("X"), op.input("Y")
    xs, ys = ctx.shape(xv), ctx.shape(yv)
    if xs is None or ys is None:
        ctx.add(op, unresolved=True)
        return
    xnc = op.attr("x_num_col_dims", 1)
    ync = op.attr("y_num_col_dims", 1)
    m, k = _prod(xs[:xnc]), _prod(xs[xnc:])
    n2 = _prod(ys[ync:])
    f = 2.0 * m * k * n2
    e = ctx.esize(xv)
    streams = (m * k + k * n2 + m * n2) * e
    ctx.add(op, flops=f, hbm_bytes=streams,
            bwd_flops=2 * f, bwd_hbm_bytes=2 * streams)


@register_cost("matmul")
def _matmul_cost(ctx, op):
    xv, yv = op.input("X"), op.input("Y")
    xs, ys = ctx.shape(xv), ctx.shape(yv)
    if xs is None or ys is None or len(xs) < 2 or len(ys) < 2:
        ctx.add(op, unresolved=True)
        return
    if op.attr("transpose_X", False):
        xs = xs[:-2] + (xs[-1], xs[-2])
    if op.attr("transpose_Y", False):
        ys = ys[:-2] + (ys[-1], ys[-2])
    batch = max(_prod(xs[:-2]), _prod(ys[:-2]))
    m, k, n2 = xs[-2], xs[-1], ys[-1]
    f = 2.0 * batch * m * k * n2
    e = ctx.esize(xv)
    streams = (_prod(xs) + _prod(ys)
               + batch * m * n2) * e
    ctx.add(op, flops=f, hbm_bytes=streams,
            bwd_flops=2 * f, bwd_hbm_bytes=2 * streams)


# ---------------------------------------------------------------------------
# conv / pool / norm: the committed resnet bytes model, per-op
# ---------------------------------------------------------------------------

@register_cost("conv2d", "depthwise_conv2d")
def _conv2d_cost(ctx, op):
    xv, wv = op.input("Input"), op.input("Filter")
    ov = op.output("Output")
    xs, ws, os_ = ctx.shape(xv), ctx.shape(wv), ctx.shape(ov)
    if xs is None or ws is None or os_ is None or len(xs) != 4 \
            or len(ws) != 4 or len(os_) != 4:
        ctx.add(op, unresolved=True)
        return
    n, c, h, w_ = xs
    o, _, kh, kw = ws
    _, _, oh, ow = os_
    f = 2.0 * n * o * oh * ow * c * kh * kw
    e = ctx.esize(xv)
    xb = n * c * h * w_ * e
    yb = n * o * oh * ow * e
    wb = o * c * kh * kw * e
    # images carry no gradient: a conv fed straight from a data var has
    # no dX (XLA DCEs it) — the stem-conv exclusion, per-op
    has_dx = not getattr(xv, "is_data", False)
    stride2 = h > oh  # resnet uses stride only to halve resolution
    # dX of a stride-2 conv lowers as lhs_dilated (zero-stuffed) conv on
    # the MXU: 4x the MAC grid — a lowering property, so it is floor
    dx_f = f * (4 if stride2 else 1) if has_dx else 0.0
    dx_b = (yb + wb + xb) if has_dx else 0
    dw_b = xb + yb + o * c * kh * kw * 4  # f32 dW
    # BN/relu ride the conv fusions: one extra full activation pass on
    # each of dX (relu mask + BN x-hat) and dW (dgamma/dbeta reads).
    # The note carries the dx/dw split so the per-bucket attribution
    # (tools/attribute_resnet.py) can rebuild its buckets from THESE
    # numbers instead of a second model.
    ctx.add(op, flops=f, hbm_bytes=xb + wb + yb,
            bwd_flops=f + dx_f, bwd_hbm_bytes=dx_b + dw_b + 2 * yb,
            note={"kind": "conv", "dx_flops": dx_f, "dx_bytes": dx_b,
                  "dw_flops": f, "dw_bytes": dw_b, "ride_bytes": 2 * yb,
                  "fwd_1x": f if has_dx else 0.0})


@register_cost("fused_conv2d")
def _fused_conv2d_cost(ctx, op):
    """The epilogue-fused chain: conv streams + one residual read when
    present; BN stats ride the output pass (that is the point of the
    fusion), backward identical to the unfused conv's accounting."""
    xv, wv = op.input("Input"), op.input("Filter")
    ov = op.output("Y")
    xs, ws, os_ = ctx.shape(xv), ctx.shape(wv), ctx.shape(ov)
    if xs is None or ws is None or os_ is None or len(xs) != 4 \
            or len(ws) != 4:
        ctx.add(op, unresolved=True)
        return
    n, c, h, w_ = xs
    o, _, kh, kw = ws
    oh, ow = os_[2], os_[3]
    f = 2.0 * n * o * oh * ow * c * kh * kw
    e = ctx.esize(xv)
    xb = n * c * h * w_ * e
    yb = n * o * oh * ow * e
    wb = o * c * kh * kw * e
    rb = yb if op.input("Residual") is not None else 0
    has_dx = not getattr(xv, "is_data", False)
    stride2 = h > oh
    dx_f = f * (4 if stride2 else 1) if has_dx else 0.0
    dx_b = (yb + wb + xb) if has_dx else 0
    dw_b = xb + yb + o * c * kh * kw * 4
    ctx.add(op, flops=f, hbm_bytes=xb + wb + yb + rb,
            bwd_flops=f + dx_f, bwd_hbm_bytes=dx_b + dw_b + 2 * yb)


@register_cost("pool2d")
def _pool2d_cost(ctx, op):
    xb_n = _nel(ctx, op.input("X"))
    ob_n = _nel(ctx, op.output("Out"))
    if xb_n is None or ob_n is None:
        ctx.add(op, unresolved=True)
        return
    e = ctx.esize(op.input("X"))
    xb, ob = xb_n * e, ob_n * e
    if op.attr("pooling_type", "max") == "max":
        # fwd read+write; bwd select-and-scatter reads x, dy, writes dx
        ctx.add(op, hbm_bytes=xb + ob, bwd_hbm_bytes=xb + 2 * ob)
    else:
        ctx.add(op, hbm_bytes=xb + ob, bwd_hbm_bytes=xb + ob)


@register_cost("batch_norm")
def _batch_norm_cost(ctx, op):
    # rides the conv fusions in this lowering (measured standalone BN
    # ~0.6 ms = fused): fwd stats/scale/shift fuse into the conv output
    # pass; the backward's activation re-reads are charged on the conv
    # rule's dX/dW riders — charging them here too would double-count
    n = _nel(ctx, op.input("X"))
    ctx.add(op, flops=2 * (n or 0), bwd_flops=2 * (n or 0),
            note="bytes ride the conv epilogue fusions")


@register_cost("layer_norm", "group_norm")
def _layer_norm_cost(ctx, op):
    # a two-pass statistic op XLA cannot fully fuse away: read + write
    # forward, one extra activation read backward (x-hat)
    n = _nel(ctx, op.input("X"))
    if n is None:
        ctx.add(op, unresolved=True)
        return
    e = ctx.esize(op.input("X"))
    ctx.add(op, flops=8 * n, hbm_bytes=2 * n * e,
            bwd_flops=8 * n, bwd_hbm_bytes=3 * n * e)


# ---------------------------------------------------------------------------
# embedding / indexing: ROW ops (latency-bound, priced per-row)
# ---------------------------------------------------------------------------

def _lookup_cost(ctx, op):
    ids = ctx.shape(op.input("Ids"))
    if ids is None:
        ctx.add(op, unresolved=True)
        return
    if len(ids) >= 2 and ids[-1] == 1:
        ids = ids[:-1]  # LoD-era trailing [.., 1] squeeze
    n = _prod(ids)
    # fwd: n gathered rows; bwd: the densify / sharded backward is one
    # scatter-add of the same n rows
    ctx.add(op, row_reads=n, bwd_row_writes=n)


register_cost("lookup_table", "sharded_lookup_table")(_lookup_cost)


@register_cost("gather")
def _gather_cost(ctx, op):
    idx = ctx.shape(op.input("Index"))
    if idx is None:
        ctx.add(op, unresolved=True)
        return
    n = _prod(idx)
    ctx.add(op, row_reads=n, bwd_row_writes=n)


@register_cost("scatter")
def _scatter_cost(ctx, op):
    idx = ctx.shape(op.input("Ids"))
    if idx is None:
        ctx.add(op, unresolved=True)
        return
    n = _prod(idx)
    ctx.add(op, row_writes=n, bwd_row_reads=n)


@register_cost("one_hot")
def _one_hot_cost(ctx, op):
    n = _nel(ctx, op.output("Out"))
    ctx.add(op, flops=n or 0)


# ---------------------------------------------------------------------------
# losses / metrics / search
# ---------------------------------------------------------------------------

def _loss_cost(per_elem):
    def rule(ctx, op):
        v = op.input("X") or op.input("Logits")
        n = _nel(ctx, v)
        if n is None:
            ctx.add(op, unresolved=True)
            return
        ctx.add(op, flops=per_elem * n, bwd_flops=per_elem * n)
    return rule


register_cost("cross_entropy")(_loss_cost(3))
register_cost("softmax_with_cross_entropy",
              "smooth_softmax_with_cross_entropy")(_loss_cost(5))


@register_cost("fused_linear_smooth_ce")
def _fused_ce_cost(ctx, op):
    # vocab projection + smoothed CE in one op: the matmul dominates
    xs, ws = ctx.shape(op.input("X")), ctx.shape(op.input("W"))
    if xs is None or ws is None or len(ws) != 2:
        ctx.add(op, unresolved=True)
        return
    rows = _prod(xs[:-1])
    d, v = ws
    f = 2.0 * rows * d * v
    e = ctx.esize(op.input("X"))
    streams = (rows * d + d * v + rows) * e  # logits stay in VMEM
    ctx.add(op, flops=f, hbm_bytes=streams,
            bwd_flops=2 * f, bwd_hbm_bytes=2 * streams)


@register_cost("pow")
def _pow_cost(ctx, op):
    n = _nel(ctx, op.input("X"))
    ctx.add(op, flops=n or 0, bwd_flops=n or 0)


register_zero_cost("range", "sequence_mask")


@register_cost("top_k")
def _top_k_cost(ctx, op):
    n = _nel(ctx, op.input("X"))
    ctx.add(op, flops=2 * (n or 0))


@register_cost("argmax", "argmin")
def _arg_cost(ctx, op):
    n = _nel(ctx, op.input("X"))
    ctx.add(op, flops=n or 0)


# ---------------------------------------------------------------------------
# attention (the Pallas kernel family) + incremental decode
# ---------------------------------------------------------------------------

@register_cost("flash_attention")
def _flash_attention_cost(ctx, op):
    qs = ctx.shape(op.input("Q"))
    ks = ctx.shape(op.input("K"))
    if qs is None or ks is None or len(qs) != 3 or len(ks) != 3:
        ctx.add(op, unresolved=True)
        return
    b, t, hd = qs
    t_k = ks[1]
    e = ctx.esize(op.input("Q"))
    f = 4.0 * b * t * t_k * hd  # QK^T + AV
    # streaming kernels: q/k/v read + o write forward; backward re-reads
    # the streams and writes dq/dk/dv (flash recompute keeps logits out
    # of HBM — that is the kernel's point)
    fwd_b = (2 * b * t * hd + 2 * b * t_k * hd) * e
    ctx.add(op, flops=f, hbm_bytes=fwd_b,
            bwd_flops=2.5 * f, bwd_hbm_bytes=2 * fwd_b)


@register_cost("kv_cache_write")
def _kv_cache_write_cost(ctx, op):
    n = _nel(ctx, op.input("X"))
    if n is None:
        ctx.add(op, unresolved=True)
        return
    e = ctx.esize(op.input("X"))
    ctx.add(op, hbm_bytes=2 * n * e)  # read token slice, write rows


@register_cost("cached_attention")
def _cached_attention_cost(ctx, op):
    ks = ctx.shape(op.input("CacheK"))
    qs = ctx.shape(op.input("Q"))
    if ks is None or qs is None or len(ks) != 3:
        ctx.add(op, unresolved=True)
        return
    b, cap, hd = ks
    e = ctx.esize(op.input("Q"))
    ctx.add(op, flops=4.0 * b * cap * hd,
            hbm_bytes=(2 * b * cap * hd + 2 * b * hd) * e)


@register_cost("kv_cache_write_chunk")
def _kv_cache_write_chunk_cost(ctx, op):
    n = _nel(ctx, op.input("X"))
    if n is None:
        ctx.add(op, unresolved=True)
        return
    e = ctx.esize(op.input("X"))
    ctx.add(op, hbm_bytes=2 * n * e)  # read chunk slice, write rows


@register_cost("cached_attention_chunk")
def _cached_attention_chunk_cost(ctx, op):
    ks = ctx.shape(op.input("CacheK"))
    qs = ctx.shape(op.input("Q"))
    if ks is None or qs is None or len(ks) != 3 or len(qs) != 3:
        ctx.add(op, unresolved=True)
        return
    b, cap, hd = ks
    kq = qs[1]
    if kq == -1 or cap == -1:
        ctx.add(op, unresolved=True)
        return
    e = ctx.esize(op.input("Q"))
    ctx.add(op, flops=4.0 * b * kq * cap * hd,
            hbm_bytes=(2 * b * cap * hd + 2 * b * kq * hd) * e)


# ---------------------------------------------------------------------------
# optimizer updates: master-precision (f32) state passes, batch-amortized
# ---------------------------------------------------------------------------

def _opt_cost(state_passes):
    """read+write of param + each optimizer-state slot, f32 master
    precision (the committed adam accounting: 6 passes)."""

    def rule(ctx, op):
        p = op.input("Param") or op.input("ParamOut")
        n = _nel(ctx, p)
        if n is None:
            ctx.add(op, unresolved=True)
            return
        ctx.add(op, hbm_bytes=state_passes * n * 4)
    return rule


register_cost("sgd")(_opt_cost(2))                    # p rw
register_cost("momentum", "adagrad", "sparse_decay")(_opt_cost(4))
register_cost("lars_momentum")(_opt_cost(4))
register_cost("adam", "adamax", "adadelta", "rmsprop",
              "decayed_adagrad", "lamb")(_opt_cost(6))  # p/m/v rw
register_cost("ftrl")(_opt_cost(6))

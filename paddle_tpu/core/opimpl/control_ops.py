"""Autodiff + control-flow + LR-schedule ops.

``autodiff`` is the TPU-native replacement for the reference's
source-to-source backward pass (``python/paddle/fluid/backward.py:394``
``append_backward``, which emits per-op grad OpDescs via C++ GradOpMakers):
here a single symbolic op re-traces the forward slice under ``jax.grad``.
Because the executor traces the whole program into one jit, XLA CSEs the
replayed forward against the already-traced forward — zero duplicate compute,
and the backward is scheduled/fused globally by XLA instead of op-by-op.

Control flow: ``cond_block`` / ``while_block`` lower sub-block bodies to
``lax.cond`` / ``lax.while_loop`` (ref ``conditional_block_op.cc`` /
``while_op.cc`` interpret sub-BlockDescs).
"""

import jax
import jax.numpy as jnp

from ..op_registry import (register, get, put, run_op, RNG_KEY, RNG0_KEY,
                           ENV0_KEY, PP_KEY, GRAD_SCALE_KEY, REPLAY_KEY)


def _loss_seed(env, loss_name, loss_val):
    """BuildStrategy.GradientScaleStrategy (ref ``build_strategy.h:35``):
    scale the loss cotangent. ``One`` multiplies by the dp world size
    (sum-of-device-grads semantics); ``Customized`` reads the user-fed
    ``<loss>@GRAD`` cotangent, matching the reference's custom loss@GRAD
    tensor."""
    gs = env.get(GRAD_SCALE_KEY)
    if gs is None:
        return jnp.sum(loss_val)
    if gs == "customized":
        seed = env.get(loss_name + "@GRAD")
        if seed is None:
            raise ValueError(
                "GradientScaleStrategy.Customized requires feeding the "
                "loss cotangent as '%s@GRAD'" % loss_name)
        return jnp.sum(loss_val * seed.reshape(loss_val.shape)
                       .astype(loss_val.dtype))
    return jnp.sum(loss_val) * float(gs)


def _replay_base(env, fwd_ops, export):
    """(base_env, fwd_out_names) for an autodiff forward replay.

    The replay must start from the STEP-START env snapshot, not the
    post-forward env the op runs in: in-place ops (e.g. the LR schedule's
    step-counter increment) would otherwise apply twice. When ``export``,
    also return the set of names whose replayed values are re-exported into
    the outer env — overwriting them makes the outer forward trace dead code
    (XLA cannot be trusted to CSE the replayed forward against it; without
    the export the step computes the whole forward twice, measured ~1.3x
    step time on the transformer bench)."""
    base_env = env.get(ENV0_KEY, env)
    fwd_out_names = set()
    if export and ENV0_KEY in env:
        for f in fwd_ops:
            fwd_out_names.update(f.output_arg_names)
        fwd_out_names.add(RNG_KEY)
    return base_env, fwd_out_names


@register("autodiff")
def _autodiff(env, op):
    fwd_ops = op.attr("fwd_ops")
    wrt_names = op.attr("wrt_names")
    sparse_names = set(op.attr("sparse_wrt_names") or ())
    loss_var = op.input("Loss")
    rng0 = env.get(RNG0_KEY)

    # SelectedRows-parity sparse grads: instead of differentiating w.r.t. a
    # sparse table (whose gather-vjp is a full-table scatter), differentiate
    # w.r.t. a zero delta ADDED to each lookup's output — d loss/d delta is
    # exactly the per-row cotangent, and (ids, cotangent) is the sparse
    # (rows, values) gradient (ref ``lookup_table_op.cc`` grad kernel).
    sites = {}  # fwd idx -> (delta key, table, out name, ids name, pad idx)
    for i, f in enumerate(fwd_ops):
        if (f.type in ("lookup_table", "sharded_lookup_table")
                and f.input("W") is not None
                and f.input("W").name in sparse_names):
            sites[i] = ("@delta@%d" % i, f.input("W").name,
                        f.output("Out").name, f.input("Ids").name,
                        f.attr("padding_idx", -1))

    dense_wrt = [n for n in wrt_names if n not in sparse_names]

    # Under remat the aux export is skipped: making every activation a
    # primal output of the jax.checkpoint region would keep it live through
    # the backward and defeat rematerialization.
    base_env, fwd_out_names = _replay_base(env, fwd_ops,
                                           export=not op.attr("remat"))

    pp_cfg = env.get(PP_KEY)
    if pp_cfg is not None:
        # pipeline-parallel replay: the forward runs as a microbatched
        # stage pipeline over the pp mesh axis; jax.grad through it yields
        # the GPipe reverse schedule. Only the loss is re-exported — any
        # other fetched forward output falls back to the (replicated)
        # outer trace, and unfetched outer compute is DCE'd by XLA.
        from ...parallel.pipeline import pipeline_program_loss

        pp_loss = pipeline_program_loss(
            base_env, fwd_ops, loss_var.name, pp_cfg, run_op,
            rng0 if rng0 is not None else jax.random.PRNGKey(0),
            shape_env=env)
        if op.attr("remat"):
            # recompute each microbatch's stages in the backward instead of
            # keeping every scan-stashed activation live
            pp_loss = jax.checkpoint(pp_loss)
        # Sparse tables DENSIFY under pipeline parallelism: the GPipe scan
        # accumulates a dense table grad (the per-lookup delta trick can't
        # thread through the microbatch scan carry), and the (rows,
        # values) contract is preserved with rows = arange(V) — exact for
        # every optimizer path (scatter-add of all rows == dense update),
        # at the documented cost of full-table grad traffic per step.
        pp_wrt = dense_wrt + sorted(sparse_names)
        args = {n: env[n] for n in pp_wrt}
        grads_w, aux = jax.grad(pp_loss, has_aux=True)(args)
        env.update(aux)
        callback = op.attr("grad_callback")
        for name, v in zip(wrt_names, op.output_list("Grads")):
            g = grads_w[name]
            if callback is not None:
                g = callback(name, g)
            put(env, v, g)
            if name in sparse_names:
                rv = getattr(v, "sparse_rows_var", None)
                if rv is not None:
                    env[rv.name] = jnp.arange(env[name].shape[0],
                                              dtype=jnp.int32)
        return

    def loss_fn(args):
        local = dict(base_env)
        # nested autodiff ops inside the replay must see the same replay
        # base, or they'd fall back to the mid-replay env and double-apply
        # in-place ops (the bug the step-start snapshot exists to prevent)
        local[ENV0_KEY] = base_env
        local[REPLAY_KEY] = True
        local.update(args["w"])
        if rng0 is not None:
            local[RNG_KEY] = rng0
        for i, f in enumerate(fwd_ops):
            run_op(local, f)
            site = sites.get(i)
            if site is not None:
                out_name = site[2]
                local[out_name] = local[out_name] + args["d"][site[0]]
        aux = {n: local[n] for n in fwd_out_names if n in local}
        return _loss_seed(env, loss_var.name, local[loss_var.name]), aux

    if op.attr("remat"):
        # coarse rematerialization (≡ reference memory_optimize pass):
        # recompute forward activations in the backward instead of saving
        loss_fn = jax.checkpoint(loss_fn)

    # delta shapes come from the already-traced forward outputs in env
    deltas = {key: jnp.zeros_like(env[out_name])
              for key, _, out_name, _, _ in sites.values()}
    args = {"w": {n: env[n] for n in dense_wrt}, "d": deltas}
    grads, aux = jax.grad(loss_fn, has_aux=True)(args)
    env.update(aux)

    callback = op.attr("grad_callback")
    out_vars = op.output_list("Grads")
    assert len(out_vars) == len(wrt_names)
    for name, v in zip(wrt_names, out_vars):
        if name in sparse_names:
            from ..op_registry import merge_sparse_rows

            vocab, emb_dim = env[name].shape[0], env[name].shape[-1]
            rows_parts, val_parts = [], []
            for key, table, out_name, ids_name, pad in sites.values():
                if table != name:
                    continue
                ids = env[ids_name].reshape(-1).astype(jnp.int32)
                vals = grads["d"][key].reshape(-1, emb_dim)
                if pad is not None and pad >= 0:
                    # the padding row's grad is zero (the lookup masks its
                    # output); park padded slots on the dropped sentinel
                    padded = ids == pad
                    ids = jnp.where(padded, vocab, ids)
                    vals = jnp.where(padded[:, None], 0, vals)
                rows_parts.append(ids)
                val_parts.append(vals)
            rows = jnp.concatenate(rows_parts, axis=0)
            g = jnp.concatenate(val_parts, axis=0)
            if op.attr("merge_sparse", False):
                # duplicate slots are merged at the source ONLY when a
                # downstream consumer needs each row exactly once — norm
                # clips and sparse_decay (they set this attr via
                # backward.require_merged_sparse). Plain optimizer paths
                # either scatter-ADD (duplicates accumulate correctly) or
                # re-merge internally (lazy adam/momentum), and the
                # argsort+segment merge costs ~7 ms/step on the DeepFM
                # bench, so it must not run unconditionally.
                rows, g = merge_sparse_rows(rows, g, vocab)
            if callback is not None:
                g = callback(name, g)
            put(env, v, g)
            rv = getattr(v, "sparse_rows_var", None)
            if rv is not None:
                env[rv.name] = rows
        else:
            g = grads["w"][name]
            if callback is not None:
                g = callback(name, g)
            put(env, v, g)


@register("autodiff_vjp")
def _autodiff_vjp(env, op):
    """calc_gradient: vjp of arbitrary targets w.r.t. arbitrary inputs."""
    fwd_ops = op.attr("fwd_ops")
    wrt_names = op.attr("wrt_names")
    targets = op.input_list("Targets")
    tgs = op.input_list("TargetGrads")
    rng0 = env.get(RNG0_KEY)

    base_env, fwd_out_names = _replay_base(env, fwd_ops, export=True)

    def f(wrt_vals):
        local = dict(base_env)
        local[ENV0_KEY] = base_env
        local[REPLAY_KEY] = True
        local.update(wrt_vals)
        if rng0 is not None:
            local[RNG_KEY] = rng0
        for fo in fwd_ops:
            run_op(local, fo)
        # re-export the replayed forward (same dedup rationale as _autodiff)
        aux = {n: local[n] for n in fwd_out_names if n in local}
        return tuple(local[t.name] for t in targets), aux

    primals, vjp_fn, aux = jax.vjp(f, {n: env[n] for n in wrt_names},
                                   has_aux=True)
    env.update(aux)
    if tgs:
        cot = tuple(get(env, t) for t in tgs)
    else:
        cot = tuple(jnp.ones_like(p) for p in primals)
    (grads,) = vjp_fn(cot)
    for name, v in zip(wrt_names, op.output_list("Grads")):
        put(env, v, grads[name])


@register("cond_block")
def _cond_block(env, op):
    """lax.cond over two traced sub-blocks. Output vars are merged from the
    branch results (both branches must produce all outputs)."""
    pred = get(env, op.input("Cond")).reshape(())
    true_ops = op.attr("true_ops")
    false_ops = op.attr("false_ops")
    true_names = op.attr("true_out_names") or [v.name for v in op.output_list("Out")]
    false_names = op.attr("false_out_names") or true_names

    def run_branch(ops, names):
        def fn(_):
            local = dict(env)
            for o in ops:
                run_op(local, o)
            return tuple(local[n] for n in names)
        return fn

    outs = jax.lax.cond(pred, run_branch(true_ops, true_names),
                        run_branch(false_ops, false_names), None)
    for v, o in zip(op.output_list("Out"), outs):
        put(env, v, o)


@register("while_block")
def _while_block(env, op):
    """lax.while_loop over a sub-block body. Carry = the loop vars listed in
    the op's ``Carry`` slot; the condition reads carry[0] (a bool scalar
    recomputed by the body), matching the reference while_op's contract of a
    boolean condition var."""
    body_ops = op.attr("body_ops")
    cond_name = op.attr("cond_name")
    carry_vars = op.input_list("Carry")
    carry_names = [v.name for v in carry_vars]
    # tensor-array fill levels ride along as hidden carries so
    # array_length stays correct across iterations
    aux_names = [n + "@LEN" for n in carry_names if n + "@LEN" in env]
    all_names = [cond_name] + carry_names + aux_names

    def cond_fn(carry):
        return carry[0].reshape(()).astype(bool)

    def body_fn(carry):
        local = dict(env)
        local.update({n: c for n, c in zip(all_names, carry)})
        for o in body_ops:
            run_op(local, o)
        return tuple(local[n] for n in all_names)

    init = tuple(env[n] for n in all_names)
    final = jax.lax.while_loop(cond_fn, body_fn, init)
    for v, val in zip(op.output_list("Out"), final[1:1 + len(carry_names)]):
        put(env, v, val)
    for n, val in zip(aux_names, final[1 + len(carry_names):]):
        env[n] = val


@register("scan_block")
def _scan_block(env, op):
    """lax.scan over a traced step (used by StaticRNN): xs are [T, ...]
    stacked inputs, carry vars persist across steps, ys are stacked outputs.
    TPU-idiomatic replacement for the reference ``recurrent_op.cc``."""
    step_ops = op.attr("step_ops")
    x_vars = op.input_list("X")          # scanned inputs (leading time axis)
    init_vars = op.input_list("Init")    # carry inits
    x_names = op.attr("x_step_names")    # names the step body reads per-step
    carry_names = op.attr("carry_names")  # names read (pre) & written (post)
    carry_out_names = op.attr("carry_out_names")
    y_names = op.attr("y_names")         # per-step outputs to stack

    def step(carry, xs_t):
        local = dict(env)
        local.update({n: c for n, c in zip(carry_names, carry)})
        local.update({n: x for n, x in zip(x_names, xs_t)})
        for o in step_ops:
            run_op(local, o)
        new_carry = tuple(local[n] for n in carry_out_names)
        ys = tuple(local[n] for n in y_names)
        return new_carry, ys

    init = tuple(get(env, v) for v in init_vars)
    xs = tuple(get(env, v) for v in x_vars)
    final_carry, ys = jax.lax.scan(step, init, xs)
    for v, val in zip(op.output_list("Last"), final_carry):
        put(env, v, val)
    for v, val in zip(op.output_list("Ys"), ys):
        put(env, v, val)


# ---------------- learning-rate schedule ops ----------------
# The reference builds these from counter vars + math ops appended by
# ``layers/learning_rate_scheduler.py``; here each schedule is one fused op
# reading the global step counter (a persistable state var).

@register("lr_exponential_decay")
def _lr_exp_decay(env, op):
    step = get(env, op.input("Step")).reshape(()).astype(jnp.float32)
    lr0 = op.attr("learning_rate")
    decay_steps = op.attr("decay_steps")
    decay_rate = op.attr("decay_rate")
    div = step / decay_steps
    if op.attr("staircase", False):
        div = jnp.floor(div)
    put(env, op.output("Out"), (lr0 * jnp.power(decay_rate, div)).reshape(()))


@register("lr_natural_exp_decay")
def _lr_natural_exp(env, op):
    step = get(env, op.input("Step")).reshape(()).astype(jnp.float32)
    lr0 = op.attr("learning_rate")
    decay_steps = op.attr("decay_steps")
    decay_rate = op.attr("decay_rate")
    div = step / decay_steps
    if op.attr("staircase", False):
        div = jnp.floor(div)
    put(env, op.output("Out"), (lr0 * jnp.exp(-decay_rate * div)).reshape(()))


@register("lr_inverse_time_decay")
def _lr_inverse_time(env, op):
    step = get(env, op.input("Step")).reshape(()).astype(jnp.float32)
    lr0 = op.attr("learning_rate")
    decay_steps = op.attr("decay_steps")
    decay_rate = op.attr("decay_rate")
    div = step / decay_steps
    if op.attr("staircase", False):
        div = jnp.floor(div)
    put(env, op.output("Out"), (lr0 / (1.0 + decay_rate * div)).reshape(()))


@register("lr_polynomial_decay")
def _lr_polynomial(env, op):
    step = get(env, op.input("Step")).reshape(()).astype(jnp.float32)
    lr0 = op.attr("learning_rate")
    decay_steps = op.attr("decay_steps")
    end_lr = op.attr("end_learning_rate", 1e-4)
    power = op.attr("power", 1.0)
    if op.attr("cycle", False):
        div = jnp.ceil(jnp.maximum(step / decay_steps, 1.0))
        decay = decay_steps * div
    else:
        decay = decay_steps
        step = jnp.minimum(step, decay_steps)
    out = (lr0 - end_lr) * jnp.power(1 - step / decay, power) + end_lr
    put(env, op.output("Out"), out.reshape(()))


@register("lr_piecewise_decay")
def _lr_piecewise(env, op):
    step = get(env, op.input("Step")).reshape(()).astype(jnp.float32)
    boundaries = jnp.asarray(op.attr("boundaries"), dtype=jnp.float32)
    values = jnp.asarray(op.attr("values"), dtype=jnp.float32)
    idx = jnp.searchsorted(boundaries, step, side="right")
    put(env, op.output("Out"), values[idx].reshape(()))


@register("lr_cosine_decay")
def _lr_cosine(env, op):
    step = get(env, op.input("Step")).reshape(()).astype(jnp.float32)
    lr0 = op.attr("learning_rate")
    step_each_epoch = op.attr("step_each_epoch")
    epochs = op.attr("epochs")
    cur_epoch = jnp.floor(step / step_each_epoch)
    out = lr0 * 0.5 * (jnp.cos(cur_epoch * jnp.pi / epochs) + 1)
    put(env, op.output("Out"), out.reshape(()))


@register("lr_noam_decay")
def _lr_noam(env, op):
    step = jnp.maximum(get(env, op.input("Step")).reshape(()).astype(jnp.float32), 1.0)
    d_model = op.attr("d_model")
    warmup = op.attr("warmup_steps")
    out = d_model ** -0.5 * jnp.minimum(step ** -0.5, step * warmup ** -1.5)
    put(env, op.output("Out"), out.reshape(()))


@register("lr_linear_warmup")
def _lr_linear_warmup(env, op):
    step = get(env, op.input("Step")).reshape(()).astype(jnp.float32)
    base = get(env, op.input("Base")).reshape(())
    warmup = op.attr("warmup_steps")
    start_lr = op.attr("start_lr")
    end_lr = op.attr("end_lr")
    warm = start_lr + (end_lr - start_lr) * step / warmup
    put(env, op.output("Out"), jnp.where(step < warmup, warm, base).reshape(()))

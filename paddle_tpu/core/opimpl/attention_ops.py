"""Attention + sampling op registrations (bridge to ``paddle_tpu.ops``),
plus the incremental-decode primitives (KV-cache write + cached attention)
the serving tier's step programs are built from."""

import math

import jax
import jax.numpy as jnp

from ..op_registry import register, get, put, next_rng


@register("flash_attention")
def _flash_attention_op(env, op):
    from ...ops.flash_attention import flash_attention, plan_for

    from ..op_registry import mxu_cast

    q = get(env, op.input("Q"))
    k = get(env, op.input("K"))
    v = get(env, op.input("V"))
    bias = get(env, op.input("Bias"))
    out_dtype = q.dtype
    q, k, v = mxu_cast(q, k, v)
    dropout = op.attr("dropout_rate", 0.0)
    rng = next_rng(env) if dropout > 0.0 else None
    plan = plan_for(q, k, bias, op.attr("num_heads", 1),
                    op.attr("causal", False), dropout, rng)
    # trace-time record: which attention kernel this op actually takes
    # and why a demotion happened (ISSUE 15 no-silent-fallback contract)
    op.attrs["_kernel_choice"] = plan.to_dict()
    out = flash_attention(q, k, v, op.attr("num_heads", 1), bias=bias,
                          causal=op.attr("causal", False),
                          dropout_rate=dropout, rng=rng, plan=plan)
    put(env, op.output("Out"), out.astype(out_dtype))


@register("kv_cache_write")
def _kv_cache_write(env, op):
    """Per-row cache update: Cache [B, C, ...], X [B, ...], Pos [B] ->
    Out[b, Pos[b]] = X[b], other entries untouched. Each row writes ONLY
    its own slot — the property the continuous batcher's solo-vs-batched
    bitwise-parity guarantee rests on (a dead slot's garbage write cannot
    leak into a live row). Out-of-range positions drop (a retired slot fed
    a zero position is harmless either way)."""
    cache = get(env, op.input("Cache"))
    x = get(env, op.input("X"))
    pos = get(env, op.input("Pos")).reshape(-1).astype(jnp.int32)
    b = cache.shape[0]
    put(env, op.output("Out"),
        cache.at[jnp.arange(b), pos].set(x.astype(cache.dtype),
                                         mode="drop"))


@register("cached_attention")
def _cached_attention(env, op):
    """One-token attention over a fixed-capacity KV cache: Q [B, H*D],
    CacheK/CacheV [B, C, H*D], Pos [B] (the index the current token was
    just written at). Row b attends over cache positions <= Pos[b] only —
    positions past the row's own fill level (including every slot of a
    dead row) are masked out before the softmax. Numerics mirror
    ``ops.flash_attention.mha_reference``: logits * 1/sqrt(D), f32
    softmax. Strictly per-row: no cross-row reduction anywhere."""
    q = get(env, op.input("Q"))
    k = get(env, op.input("CacheK"))
    v = get(env, op.input("CacheV"))
    pos = get(env, op.input("Pos")).reshape(-1).astype(jnp.int32)
    h = int(op.attr("num_heads", 1))
    b, c, hd = k.shape
    d = hd // h
    qh = q.reshape(b, h, d)
    kh = k.reshape(b, c, h, d)
    vh = v.reshape(b, c, h, d)
    scale = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhd,bchd->bhc", qh, kh) * scale
    mask = jnp.arange(c)[None, None, :] <= pos[:, None, None]
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
        q.dtype)
    ctx = jnp.einsum("bhc,bchd->bhd", probs, vh)
    put(env, op.output("Out"), ctx.reshape(b, hd))


@register("kv_cache_write_chunk")
def _kv_cache_write_chunk(env, op):
    """K-row cache update (the chunked-prefill / speculative-verify
    sibling of ``kv_cache_write``): Cache [B, C, ...], X [B, K, ...],
    Pos [B, K] -> Out[b, Pos[b, j]] = X[b, j]. Still strictly per-row —
    row b scatters only into its own cache rows, so the batcher's
    solo-vs-batched parity property carries over to chunk dispatches.
    Out-of-range positions drop: the scheduler pads partial chunks with
    Pos = capacity, so a padded lane writes nothing."""
    cache = get(env, op.input("Cache"))
    x = get(env, op.input("X"))
    pos = get(env, op.input("Pos")).astype(jnp.int32)
    b = cache.shape[0]
    put(env, op.output("Out"),
        cache.at[jnp.arange(b)[:, None], pos].set(x.astype(cache.dtype),
                                                  mode="drop"))


@register("cached_attention_chunk")
def _cached_attention_chunk(env, op):
    """K-query attention over a fixed-capacity KV cache: Q [B, K, H*D],
    CacheK/CacheV [B, C, H*D], Pos [B, K] (the index each query token
    was just written at). Query j of row b attends cache positions
    <= Pos[b, j] — per-query causal masking over the filled prefix plus
    the chunk's own earlier tokens, exactly the step op's semantics
    applied K times. Numerics mirror ``cached_attention`` (1/sqrt(D)
    scale, f32 softmax); still strictly per-row."""
    q = get(env, op.input("Q"))
    k = get(env, op.input("CacheK"))
    v = get(env, op.input("CacheV"))
    pos = get(env, op.input("Pos")).astype(jnp.int32)
    h = int(op.attr("num_heads", 1))
    b, c, hd = k.shape
    kq = q.shape[1]
    d = hd // h
    qh = q.reshape(b, kq, h, d)
    kh = k.reshape(b, c, h, d)
    vh = v.reshape(b, c, h, d)
    scale = 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bchd->bhqc", qh, kh) * scale
    mask = jnp.arange(c)[None, None, None, :] <= pos[:, None, :, None]
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(
        q.dtype)
    ctx = jnp.einsum("bhqc,bchd->bqhd", probs, vh)
    put(env, op.output("Out"), ctx.reshape(b, kq, hd))


@register("sampling_id")
def _sampling_id(env, op):
    x = get(env, op.input("X"))  # [B, C] probabilities
    put(env, op.output("Out"),
        jax.random.categorical(next_rng(env), jnp.log(jnp.maximum(x, 1e-20)),
                               axis=-1).astype(jnp.int64))

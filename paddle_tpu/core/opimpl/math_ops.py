"""Elementwise / activation / reduction / matmul ops.

Reference: ``paddle/fluid/operators/`` root + ``elementwise/`` +
``reduce_ops/`` + ``activation_op.cc``. All lower to jnp/lax so XLA fuses
them into neighboring matmuls (the TPU replacement for the reference's
``fused_elemwise_activation`` op and jit/ codegen kernels).
"""

import jax
import jax.numpy as jnp

from ..op_registry import register, get, get_list, put, bcast_y

# ---------------- elementwise binary family ----------------

_BINOPS = {
    "elementwise_add": jnp.add,
    "elementwise_sub": jnp.subtract,
    "elementwise_mul": jnp.multiply,
    "elementwise_div": jnp.divide,
    "elementwise_max": jnp.maximum,
    "elementwise_min": jnp.minimum,
    "elementwise_pow": jnp.power,
    "elementwise_mod": jnp.mod,
    "elementwise_floordiv": jnp.floor_divide,
}


def _make_binop(name, fn, harmonize=True):
    # harmonize=False for comparisons: demoting an f32 operand to bf16
    # would change mask RESULTS at rounding boundaries, and bool outputs
    # gain no bf16-residency benefit.
    @register(name)
    def _impl(env, op, fn=fn):
        x = get(env, op.input("X"))
        y = get(env, op.input("Y"))
        y = bcast_y(x, y, op.attr("axis", -1))
        if harmonize:
            from ..op_registry import amp_harmonize
            x, y = amp_harmonize(x, y)
        put(env, op.output("Out"), fn(x, y))


# mod/floordiv produce discrete outputs that can flip at bf16 rounding
# boundaries (same rationale as comparisons) — keep them out of harmonize
for _n, _f in _BINOPS.items():
    _make_binop(_n, _f,
                harmonize=_n not in ("elementwise_mod",
                                     "elementwise_floordiv"))

_CMPOPS = {
    "less_than": jnp.less,
    "less_equal": jnp.less_equal,
    "greater_than": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
}

for _n, _f in _CMPOPS.items():
    _make_binop(_n, _f, harmonize=False)


@register("logical_and")
def _logical_and(env, op):
    put(env, op.output("Out"),
        jnp.logical_and(get(env, op.input("X")), get(env, op.input("Y"))))


@register("logical_or")
def _logical_or(env, op):
    put(env, op.output("Out"),
        jnp.logical_or(get(env, op.input("X")), get(env, op.input("Y"))))


@register("logical_xor")
def _logical_xor(env, op):
    put(env, op.output("Out"),
        jnp.logical_xor(get(env, op.input("X")), get(env, op.input("Y"))))


@register("logical_not")
def _logical_not(env, op):
    put(env, op.output("Out"), jnp.logical_not(get(env, op.input("X"))))


# ---------------- activations (ref activation_op.cc) ----------------

def _unary(name, fn):
    @register(name)
    def _impl(env, op, fn=fn):
        put(env, op.output("Out"), fn(get(env, op.input("X"))))


_UNARY = {
    "sigmoid": jax.nn.sigmoid,
    "logsigmoid": jax.nn.log_sigmoid,
    "exp": jnp.exp,
    "tanh": jnp.tanh,
    "tanh_shrink": lambda x: x - jnp.tanh(x),
    "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt,
    "abs": jnp.abs,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "round": jnp.round,
    "cos": jnp.cos,
    "sin": jnp.sin,
    "reciprocal": lambda x: 1.0 / x,
    "log": jnp.log,
    "square": jnp.square,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "sign": jnp.sign,
    "erf": jax.scipy.special.erf,
}

for _n, _f in _UNARY.items():
    _unary(_n, _f)


@register("relu6")
def _relu6(env, op):
    t = op.attr("threshold", 6.0)
    put(env, op.output("Out"), jnp.clip(get(env, op.input("X")), 0.0, t))


@register("leaky_relu")
def _leaky_relu(env, op):
    a = op.attr("alpha", 0.02)
    x = get(env, op.input("X"))
    put(env, op.output("Out"), jnp.where(x > 0, x, a * x))


@register("elu")
def _elu(env, op):
    a = op.attr("alpha", 1.0)
    x = get(env, op.input("X"))
    put(env, op.output("Out"), jnp.where(x > 0, x, a * (jnp.exp(x) - 1)))


@register("prelu")
def _prelu(env, op):
    x = get(env, op.input("X"))
    alpha = get(env, op.input("Alpha"))
    mode = op.attr("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    put(env, op.output("Out"), jnp.where(x > 0, x, alpha * x))


@register("gelu")
def _gelu(env, op):
    from ..op_registry import amp_enabled, env_flag
    # tanh-approx under AMP (the standard TPU BERT choice): erf's
    # polynomial lowering costs ~0.9 ms/layer at BERT-base shapes and its
    # vjp chain re-fuses into dW matmul operands (NOTES_r4.md); exact erf
    # stays the default for f32 runs and under PADDLE_TPU_AMP_F32_ACTS
    approx = op.attr("approximate",
                     amp_enabled()
                     and not env_flag("PADDLE_TPU_AMP_F32_ACTS"))
    put(env, op.output("Out"),
        jax.nn.gelu(get(env, op.input("X")), approximate=approx))


@register("brelu")
def _brelu(env, op):
    put(env, op.output("Out"),
        jnp.clip(get(env, op.input("X")), op.attr("t_min", 0.0), op.attr("t_max", 24.0)))


@register("stanh")
def _stanh(env, op):
    a = op.attr("scale_a", 0.67)
    b = op.attr("scale_b", 1.7159)
    put(env, op.output("Out"), b * jnp.tanh(a * get(env, op.input("X"))))


@register("hard_sigmoid")
def _hard_sigmoid(env, op):
    slope = op.attr("slope", 0.2)
    offset = op.attr("offset", 0.5)
    put(env, op.output("Out"),
        jnp.clip(slope * get(env, op.input("X")) + offset, 0.0, 1.0))


@register("hard_shrink")
def _hard_shrink(env, op):
    t = op.attr("threshold", 0.5)
    x = get(env, op.input("X"))
    put(env, op.output("Out"), jnp.where(jnp.abs(x) > t, x, 0.0))


@register("soft_shrink")
def _soft_shrink(env, op):
    lam = op.attr("lambda", 0.5)
    x = get(env, op.input("X"))
    put(env, op.output("Out"),
        jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, 0.0)))


@register("thresholded_relu")
def _thresholded_relu(env, op):
    t = op.attr("threshold", 1.0)
    x = get(env, op.input("X"))
    put(env, op.output("Out"), jnp.where(x > t, x, 0.0))


@register("swish")
def _swish(env, op):
    b = op.attr("beta", 1.0)
    x = get(env, op.input("X"))
    put(env, op.output("Out"), x * jax.nn.sigmoid(b * x))


@register("pow")
def _pow(env, op):
    put(env, op.output("Out"),
        jnp.power(get(env, op.input("X")), op.attr("factor", 1.0)))


@register("maxout")
def _maxout(env, op):
    x = get(env, op.input("X"))  # NCHW
    groups = op.attr("groups")
    n, c, h, w = x.shape
    put(env, op.output("Out"),
        x.reshape(n, c // groups, groups, h, w).max(axis=2))


# ---------------- scale / clip ----------------

@register("scale")
def _scale(env, op):
    x = get(env, op.input("X"))
    s = op.attr("scale", 1.0)
    b = op.attr("bias", 0.0)
    if op.attr("bias_after_scale", True):
        out = x * s + b
    else:
        out = (x + b) * s
    put(env, op.output("Out"), out)


@register("clip")
def _clip(env, op):
    put(env, op.output("Out"),
        jnp.clip(get(env, op.input("X")), op.attr("min"), op.attr("max")))


@register("clip_by_norm")
def _clip_by_norm(env, op):
    x = get(env, op.input("X"))
    max_norm = op.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    put(env, op.output("Out"),
        jnp.where(norm > max_norm, x * (max_norm / jnp.maximum(norm, 1e-12)), x))


@register("squared_l2_norm")
def _squared_l2_norm(env, op):
    x = get(env, op.input("X"))
    put(env, op.output("Out"), jnp.sum(jnp.square(x)).reshape(()))


@register("norm")
def _norm(env, op):
    # l2_normalize along axis (ref norm_op.cc)
    x = get(env, op.input("X"))
    axis = op.attr("axis", -1)
    eps = op.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    put(env, op.output("Out"), x / norm)
    put(env, op.output("Norm"), norm)


# ---------------- matmul family ----------------

@register("mul")
def _mul(env, op):
    """Reference ``mul_op``: flatten x at x_num_col_dims, y at y_num_col_dims,
    then 2-D matmul (``operators/mul_op.cc``). Lowers to a single MXU matmul.
    """
    x = get(env, op.input("X"))
    y = get(env, op.input("Y"))
    xnc = op.attr("x_num_col_dims", 1)
    ync = op.attr("y_num_col_dims", 1)
    import functools as _ft
    import operator as _operator

    def _prod(dims):
        # NOT np.prod: symbolic batch dims (jax.export shape polymorphism)
        # must stay symbolic through the reshape
        return _ft.reduce(_operator.mul, dims, 1)

    xs, ys = x.shape, y.shape
    x2 = x.reshape((_prod(xs[:xnc]), _prod(xs[xnc:])))
    y2 = y.reshape((_prod(ys[:ync]), _prod(ys[ync:])))
    from ..op_registry import mxu_cast, mxu_acc_dtype
    x2, y2 = mxu_cast(x2, y2)
    out = jnp.matmul(x2, y2, preferred_element_type=mxu_acc_dtype(x2))
    out_shape = xs[:xnc] + ys[ync:]
    put(env, op.output("Out"), out.reshape(out_shape))


@register("matmul")
def _matmul(env, op):
    x = get(env, op.input("X"))
    y = get(env, op.input("Y"))
    if op.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2)
    if op.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2)
    from ..op_registry import mxu_cast, mxu_acc_dtype
    x, y = mxu_cast(x, y)
    out = jnp.matmul(x, y, preferred_element_type=mxu_acc_dtype(x))
    alpha = op.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    put(env, op.output("Out"), out)


@register("sum")
def _sum(env, op):
    xs = get_list(env, op, "X")
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    put(env, op.output("Out"), out)


@register("mean")
def _mean(env, op):
    put(env, op.output("Out"), jnp.mean(get(env, op.input("X"))).reshape(()))


# ---------------- reductions (ref reduce_ops/) ----------------

def _reduce(name, fn):
    @register(name)
    def _impl(env, op, fn=fn):
        x = get(env, op.input("X"))
        dim = op.attr("dim", [0])
        keep = op.attr("keep_dim", False)
        if op.attr("reduce_all", False) or dim is None:
            axis = None
        else:
            axis = tuple(d if d >= 0 else d + x.ndim for d in dim)
        out = fn(x, axis=axis, keepdims=keep)
        if axis is None and not keep:
            out = out.reshape(())
        put(env, op.output("Out"), out)


for _n, _f in {
    "reduce_sum": jnp.sum,
    "reduce_mean": jnp.mean,
    "reduce_max": jnp.max,
    "reduce_min": jnp.min,
    "reduce_prod": jnp.prod,
}.items():
    _reduce(_n, _f)


@register("cumsum")
def _cumsum(env, op):
    x = get(env, op.input("X"))
    axis = op.attr("axis", -1)
    if op.attr("flatten", False):
        x = x.reshape(-1)
        axis = 0
    axis = axis % x.ndim
    reverse = op.attr("reverse", False)
    # reverse = flip, cumsum, flip-back; exclusive shifts within the
    # (possibly flipped) frame so the combination composes correctly
    xx = jnp.flip(x, axis) if reverse else x
    out = jnp.cumsum(xx, axis=axis)
    if op.attr("exclusive", False):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        sl = tuple(slice(0, -1) if i == axis else slice(None)
                   for i in range(x.ndim))
        out = jnp.pad(out, pad)[sl]
    if reverse:
        out = jnp.flip(out, axis)
    put(env, op.output("Out"), out)


# ---------------- search / sort ----------------

# index outputs are int32, not int64: without x64 mode jax truncates an
# explicit int64 request to int32 anyway, emitting a UserWarning per trace
# (the resnet50 bench tail in BENCH_r05.json) — request the real dtype
@register("argmax")
def _argmax(env, op):
    put(env, op.output("Out"),
        jnp.argmax(get(env, op.input("X")), axis=op.attr("axis", -1)).astype(jnp.int32))


@register("argmin")
def _argmin(env, op):
    put(env, op.output("Out"),
        jnp.argmin(get(env, op.input("X")), axis=op.attr("axis", -1)).astype(jnp.int32))


@register("argsort")
def _argsort(env, op):
    x = get(env, op.input("X"))
    axis = op.attr("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    put(env, op.output("Indices"), idx.astype(jnp.int32))
    put(env, op.output("Out"), jnp.sort(x, axis=axis))


@register("top_k")
def _top_k(env, op):
    x = get(env, op.input("X"))
    k = op.attr("k", 1)
    vals, idx = jax.lax.top_k(x, k)
    put(env, op.output("Out"), vals)
    put(env, op.output("Indices"), idx.astype(jnp.int32))


@register("isfinite")
def _isfinite(env, op):
    # ref isfinite_op: reduces to a single bool "contains inf/nan"
    x = get(env, op.input("X"))
    put(env, op.output("Out"), jnp.all(jnp.isfinite(x)).reshape((1,)))

"""Tensor creation / manipulation / random / embedding ops.

Reference: ``paddle/fluid/operators/`` (fill_constant, uniform/gaussian
random, reshape, transpose, concat, split, slice, gather, scatter, expand,
lookup_table, one_hot, cast, ...). Random ops draw from the executor's
threaded PRNG key — functional randomness, the jax replacement for the
reference's per-device curand generators.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..op_registry import register, get, get_list, put, next_rng
from ..framework import convert_np_dtype


def _canonical(dtype):
    """x64 is disabled: pre-canonicalize 64-bit requests to the jax
    default width instead of letting jnp warn-and-truncate every call."""
    return jax.dtypes.canonicalize_dtype(dtype)


@register("fill_constant")
def _fill_constant(env, op):
    shape = op.attr("shape")
    dtype = _canonical(convert_np_dtype(op.attr("dtype", "float32")))
    value = op.attr("value", 0.0)
    put(env, op.output("Out"), jnp.full(tuple(shape), value, dtype=dtype))


@register("fill_constant_batch_size_like")
def _fill_constant_batch_size_like(env, op):
    ref = get(env, op.input("Input"))
    shape = list(op.attr("shape"))
    in_idx = op.attr("input_dim_idx", 0)
    out_idx = op.attr("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = _canonical(convert_np_dtype(op.attr("dtype", "float32")))
    put(env, op.output("Out"), jnp.full(tuple(shape), op.attr("value", 0.0),
                                        dtype=dtype))


@register("fill_zeros_like")
def _fill_zeros_like(env, op):
    put(env, op.output("Out"), jnp.zeros_like(get(env, op.input("X"))))


@register("uniform_random")
def _uniform_random(env, op):
    shape = tuple(op.attr("shape"))
    dtype = convert_np_dtype(op.attr("dtype", "float32"))
    lo, hi = op.attr("min", -1.0), op.attr("max", 1.0)
    put(env, op.output("Out"),
        jax.random.uniform(next_rng(env), shape, dtype=jnp.dtype(dtype),
                           minval=lo, maxval=hi))


@register("uniform_random_batch_size_like")
def _uniform_random_batch_size_like(env, op):
    ref = get(env, op.input("Input"))
    shape = list(op.attr("shape"))
    shape[op.attr("output_dim_idx", 0)] = ref.shape[op.attr("input_dim_idx", 0)]
    dtype = convert_np_dtype(op.attr("dtype", "float32"))
    put(env, op.output("Out"),
        jax.random.uniform(next_rng(env), tuple(shape), dtype=jnp.dtype(dtype),
                           minval=op.attr("min", -1.0), maxval=op.attr("max", 1.0)))


@register("gaussian_random_batch_size_like")
def _gaussian_random_batch_size_like(env, op):
    ref = get(env, op.input("Input"))
    shape = list(op.attr("shape"))
    shape[op.attr("output_dim_idx", 0)] = ref.shape[op.attr("input_dim_idx", 0)]
    dtype = convert_np_dtype(op.attr("dtype", "float32"))
    mean, std = op.attr("mean", 0.0), op.attr("std", 1.0)
    put(env, op.output("Out"),
        mean + std * jax.random.normal(next_rng(env), tuple(shape),
                                       dtype=jnp.dtype(dtype)))


@register("gaussian_random")
def _gaussian_random(env, op):
    shape = tuple(op.attr("shape"))
    dtype = convert_np_dtype(op.attr("dtype", "float32"))
    mean, std = op.attr("mean", 0.0), op.attr("std", 1.0)
    put(env, op.output("Out"),
        mean + std * jax.random.normal(next_rng(env), shape, dtype=jnp.dtype(dtype)))


@register("truncated_gaussian_random")
def _truncated_gaussian_random(env, op):
    shape = tuple(op.attr("shape"))
    dtype = convert_np_dtype(op.attr("dtype", "float32"))
    mean, std = op.attr("mean", 0.0), op.attr("std", 1.0)
    put(env, op.output("Out"),
        mean + std * jax.random.truncated_normal(
            next_rng(env), -2.0, 2.0, shape, dtype=jnp.dtype(dtype)))


@register("randint")
def _randint(env, op):
    shape = tuple(op.attr("shape"))
    put(env, op.output("Out"),
        jax.random.randint(next_rng(env), shape, op.attr("low", 0), op.attr("high"),
                           dtype=jnp.int64))


@register("assign")
def _assign(env, op):
    put(env, op.output("Out"), get(env, op.input("X")))


@register("assign_value")
def _assign_value(env, op):
    vals = np.array(op.attr("values"),
                    dtype=convert_np_dtype(op.attr("dtype", "float32")))
    put(env, op.output("Out"), jnp.asarray(vals.reshape(op.attr("shape"))))


@register("cast")
def _cast(env, op):
    dtype = convert_np_dtype(op.attr("out_dtype"))
    put(env, op.output("Out"), get(env, op.input("X")).astype(jnp.dtype(dtype)))


@register("concat")
def _concat(env, op):
    xs = get_list(env, op, "X")
    put(env, op.output("Out"), jnp.concatenate(xs, axis=op.attr("axis", 0)))


@register("split")
def _split(env, op):
    x = get(env, op.input("X"))
    axis = op.attr("axis", 0)
    num = op.attr("num", 0)
    sections = op.attr("sections")
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    for v, o in zip(op.output_list("Out"), outs):
        put(env, v, o)


@register("reshape", "reshape2")
def _reshape(env, op):
    x = get(env, op.input("X"))
    shape = list(op.attr("shape"))
    # ref reshape_op: 0 means copy input dim, -1 inferred
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    put(env, op.output("Out"), jnp.reshape(x, shape))


@register("squeeze", "squeeze2")
def _squeeze(env, op):
    x = get(env, op.input("X"))
    axes = op.attr("axes", [])
    if axes:
        axes = tuple(a if a >= 0 else a + x.ndim for a in axes)
        out = x
        for a in sorted(axes, reverse=True):
            out = jnp.squeeze(out, axis=a)
    else:
        out = jnp.squeeze(x)
    put(env, op.output("Out"), out)


@register("unsqueeze", "unsqueeze2")
def _unsqueeze(env, op):
    x = get(env, op.input("X"))
    out = x
    for a in sorted(op.attr("axes")):
        out = jnp.expand_dims(out, axis=a)
    put(env, op.output("Out"), out)


@register("flatten", "flatten2")
def _flatten(env, op):
    x = get(env, op.input("X"))
    axis = op.attr("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    put(env, op.output("Out"), x.reshape((lead, -1)))


@register("transpose", "transpose2")
def _transpose(env, op):
    put(env, op.output("Out"),
        jnp.transpose(get(env, op.input("X")), axes=op.attr("axis")))


@register("slice")
def _slice(env, op):
    x = get(env, op.input("Input"))
    axes = op.attr("axes")
    starts = op.attr("starts")
    ends = op.attr("ends")
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    put(env, op.output("Out"), x[tuple(idx)])


@register("strided_slice")
def _strided_slice(env, op):
    x = get(env, op.input("Input"))
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(op.attr("axes"), op.attr("starts"),
                           op.attr("ends"), op.attr("strides")):
        idx[a] = slice(s, e, st)
    put(env, op.output("Out"), x[tuple(idx)])


@register("gather")
def _gather(env, op):
    x = get(env, op.input("X"))
    idx = get(env, op.input("Index")).astype(jnp.int32)
    put(env, op.output("Out"), jnp.take(x, idx.reshape(-1), axis=0))


@register("gather_nd")
def _gather_nd(env, op):
    x = get(env, op.input("X"))
    idx = get(env, op.input("Index")).astype(jnp.int32)
    put(env, op.output("Out"), x[tuple(jnp.moveaxis(idx, -1, 0))])


@register("scatter")
def _scatter(env, op):
    x = get(env, op.input("X"))
    idx = get(env, op.input("Ids")).astype(jnp.int32).reshape(-1)
    upd = get(env, op.input("Updates"))
    if op.attr("overwrite", True):
        out = x.at[idx].set(upd)
    else:
        out = x.at[idx].add(upd)
    put(env, op.output("Out"), out)


@register("expand")
def _expand(env, op):
    x = get(env, op.input("X"))
    times = op.attr("expand_times")
    put(env, op.output("Out"), jnp.tile(x, times))


@register("expand_as")
def _expand_as(env, op):
    x = get(env, op.input("X"))
    target = get(env, op.input("target_tensor"))
    put(env, op.output("Out"), jnp.broadcast_to(x, target.shape))


@register("stack")
def _stack(env, op):
    xs = get_list(env, op, "X")
    put(env, op.output("Y"), jnp.stack(xs, axis=op.attr("axis", 0)))


@register("unstack")
def _unstack(env, op):
    x = get(env, op.input("X"))
    axis = op.attr("axis", 0)
    outs = [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)]
    for v, o in zip(op.output_list("Y"), outs):
        put(env, v, o)


@register("range")
def _range(env, op):
    start = get(env, op.input("Start")).reshape(())
    end = get(env, op.input("End")).reshape(())
    step = get(env, op.input("Step")).reshape(())
    # shapes must be static under jit: range length from var metadata
    n = op.output("Out").shape[0]
    put(env, op.output("Out"), start + step * jnp.arange(n, dtype=start.dtype))


@register("shape")
def _shape(env, op):
    x = get(env, op.input("Input"))
    put(env, op.output("Out"), jnp.asarray(x.shape, dtype=jnp.int32))


@register("lookup_table")
def _lookup_table(env, op):
    """Embedding lookup (ref ``lookup_table_op.cc``). padding_idx rows give
    zeros. Sparse-grad (SelectedRows) is realized by XLA's gather-vjp
    (scatter-add) — see optimizer sparse paths for the update side.
    Narrow tables (K dividing 128) gather via the packed-row layout
    (ops/rowops.py) — 4x the plain row-gather rate on TPU."""
    from ...ops.rowops import packed_take
    w = get(env, op.input("W"))
    ids = get(env, op.input("Ids")).astype(jnp.int32)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    padding_idx = op.attr("padding_idx", -1)
    # the packed layout's pad+reshape mixes rows across shards, so a
    # mesh-sharded table (annotated by the transpiler) takes the plain
    # gather — GSPMD/shard_map owns its partitioning
    w_sharded = getattr(op.input("W"), "sharding", None) is not None
    out = (packed_take(w, ids) if w.ndim == 2 and not w_sharded
           else jnp.take(w, ids, axis=0))
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    from ..op_registry import amp_out_cast
    put(env, op.output("Out"), amp_out_cast(out))


@register("one_hot")
def _one_hot(env, op):
    ids = get(env, op.input("X")).astype(jnp.int32)
    depth = op.attr("depth")
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    put(env, op.output("Out"), jax.nn.one_hot(ids, depth, dtype=jnp.float32))


@register("pad")
def _pad(env, op):
    x = get(env, op.input("X"))
    paddings = op.attr("paddings")  # flat [before0, after0, before1, ...]
    pads = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    put(env, op.output("Out"),
        jnp.pad(x, pads, constant_values=op.attr("pad_value", 0.0)))


@register("pad2d")
def _pad2d(env, op):
    x = get(env, op.input("X"))  # NCHW
    p = op.attr("paddings")  # [top, bottom, left, right]
    mode = op.attr("mode", "constant")
    pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        out = jnp.pad(x, pads, constant_values=op.attr("pad_value", 0.0))
    elif mode == "reflect":
        out = jnp.pad(x, pads, mode="reflect")
    else:
        out = jnp.pad(x, pads, mode="edge")
    put(env, op.output("Out"), out)


@register("reverse")
def _reverse(env, op):
    x = get(env, op.input("X"))
    out = x
    for a in op.attr("axis"):
        out = jnp.flip(out, axis=a)
    put(env, op.output("Out"), out)


@register("roll")
def _roll(env, op):
    put(env, op.output("Out"),
        jnp.roll(get(env, op.input("X")), op.attr("shifts"), op.attr("axis")))


@register("where")
def _where(env, op):
    put(env, op.output("Out"),
        jnp.where(get(env, op.input("Condition")),
                  get(env, op.input("X")), get(env, op.input("Y"))))


@register("increment")
def _increment(env, op):
    x = get(env, op.input("X"))
    put(env, op.output("Out"), x + op.attr("step", 1.0))

"""Metric ops: accuracy / auc / precision-recall.

Reference: ``paddle/fluid/operators/metrics/`` (accuracy_op, auc_op).
AUC keeps histogram state in persistable vars updated functionally each step
(the executor writes them back), matching the reference's stateful AUC op.
"""

import jax
import jax.numpy as jnp

from ..op_registry import register, get, put


@register("accuracy")
def _accuracy(env, op):
    # int32 on purpose: int64 is unavailable without x64 mode, and an
    # explicit astype(int64) emits a truncation UserWarning on every bench
    # step (BENCH_r05.json tail) while silently computing in int32 anyway
    pred_idx = get(env, op.input("Indices")).astype(jnp.int32)  # [N, k] ids
    label = get(env, op.input("Label")).astype(jnp.int32)
    if label.ndim == 1:
        label = label[:, None]
    correct = jnp.any(pred_idx == label, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = pred_idx.shape[0]
    put(env, op.output("Accuracy"), (num_correct / total).reshape(()))
    put(env, op.output("Correct"), num_correct.astype(jnp.int32).reshape((1,)))
    put(env, op.output("Total"), jnp.asarray([total], dtype=jnp.int32))


@register("auc")
def _auc(env, op):
    """Streaming AUC over threshold buckets (ref ``auc_op.cc``)."""
    preds = get(env, op.input("Predict"))  # [N, 2] binary probs
    labels = get(env, op.input("Label")).astype(jnp.int32).reshape(-1)
    stat_pos = get(env, op.input("StatPos"))
    stat_neg = get(env, op.input("StatNeg"))
    num_thresholds = op.attr("num_thresholds", 4095)
    pos_prob = preds[:, -1]
    bucket = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32),
                      0, num_thresholds)
    pos_hist = jnp.zeros_like(stat_pos).at[bucket].add(labels.astype(stat_pos.dtype))
    neg_hist = jnp.zeros_like(stat_neg).at[bucket].add((1 - labels).astype(stat_neg.dtype))
    new_pos = stat_pos + pos_hist
    new_neg = stat_neg + neg_hist
    # trapezoid over descending thresholds
    tp = jnp.cumsum(new_pos[::-1])
    fp = jnp.cumsum(new_neg[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp_prev = jnp.concatenate([jnp.zeros((1,), tp.dtype), tp[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros((1,), fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp_prev) * (tp + tp_prev) / 2.0)
    auc = jnp.where(tot_pos * tot_neg > 0, area / (tot_pos * tot_neg + 1e-12), 0.0)
    put(env, op.output("AUC"), auc.reshape(()))
    put(env, op.output("StatPosOut"), new_pos)
    put(env, op.output("StatNegOut"), new_neg)


@register("precision_recall")
def _precision_recall(env, op):
    pred_idx = get(env, op.input("Indices")).astype(jnp.int32).reshape(-1)
    label = get(env, op.input("Labels")).astype(jnp.int32).reshape(-1)
    cls_num = op.attr("class_number")
    onehot_p = jax.nn.one_hot(pred_idx, cls_num)
    onehot_l = jax.nn.one_hot(label, cls_num)
    tp = jnp.sum(onehot_p * onehot_l, axis=0)
    fp = jnp.sum(onehot_p * (1 - onehot_l), axis=0)
    fn = jnp.sum((1 - onehot_p) * onehot_l, axis=0)
    precision = tp / jnp.maximum(tp + fp, 1e-12)
    recall = tp / jnp.maximum(tp + fn, 1e-12)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    macro = jnp.stack([jnp.mean(precision), jnp.mean(recall), jnp.mean(f1)])
    tps, fps, fns = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    micro_p = tps / jnp.maximum(tps + fps, 1e-12)
    micro_r = tps / jnp.maximum(tps + fns, 1e-12)
    micro_f = 2 * micro_p * micro_r / jnp.maximum(micro_p + micro_r, 1e-12)
    micro = jnp.stack([micro_p, micro_r, micro_f])
    put(env, op.output("BatchMetrics"), jnp.concatenate([macro, micro]))
    put(env, op.output("AccumStatesInfo"), jnp.stack([tp, fp, fn], axis=1))

"""Op implementations, grouped like the reference's operators/ tree
(``paddle/fluid/operators/``): math, tensor manipulation, nn, rnn,
optimizers, metrics, control flow. Importing this package registers all ops.
"""

from . import math_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import metric_ops  # noqa: F401
from . import control_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import attention_ops  # noqa: F401
from . import decode_ops  # noqa: F401
from . import loss_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import shape_rules  # noqa: F401  (static InferShape rules)
from . import cost_rules  # noqa: F401  (static roofline cost rules)

"""Static infer-shape/dtype rules for the core op set.

The analog of the per-op ``InferShape`` methods every reference operator
implements (``paddle/fluid/operators/*_op.cc``, run by
``OperatorWithKernel::RunImpl`` before the kernel): each rule derives the
output shapes/dtypes of one op type from its inputs' static shapes and
checks them against the declared output Variables — so a shape bug is
reported at the op that created it (with the user's code line) instead of
surfacing as an XLA trace error inside the jitted step.

Registered via :func:`core.op_registry.register_shape`, alongside the
lowerings. Rules receive an ``analysis.passes.ShapeCtx`` and the symbolic
op; -1 dims are wildcards (the batch dim). Ops without a rule are skipped
by the propagation pass — their declared output shapes are trusted. A rule
raises :class:`core.op_registry.ShapeError` when the inputs are
statically infeasible (e.g. a contraction-dim mismatch).
"""

import numpy as np

from ..op_registry import register_shape, ShapeError, static_bcast_shape
from ..framework import convert_np_dtype


def _prod(dims):
    out = 1
    for d in dims:
        if d == -1:
            return -1
        out *= int(d)
    return out


def _norm_axis(a, rank):
    return a + rank if a < 0 else a


# ---------------------------------------------------------------------------
# elementwise / comparison / logical (reference elementwise_op.h broadcast)
# ---------------------------------------------------------------------------

_ELEMENTWISE = ("elementwise_add", "elementwise_sub", "elementwise_mul",
                "elementwise_div", "elementwise_max", "elementwise_min",
                "elementwise_pow", "elementwise_mod", "elementwise_floordiv")
_COMPARE = ("less_than", "less_equal", "greater_than", "greater_equal",
            "equal", "not_equal")
_LOGICAL = ("logical_and", "logical_or", "logical_xor")


def _binop_rule(bool_out):
    def rule(ctx, op):
        xv, yv = op.input("X"), op.input("Y")
        xs, ys = ctx.shape(xv), ctx.shape(yv)
        dtype = np.dtype(bool) if bool_out else ctx.dtype(xv)
        if xs is None or ys is None:
            ctx.set(op.output("Out"), None, dtype)
            return
        try:
            out = static_bcast_shape(xs, ys, op.attr("axis", -1))
        except ValueError as e:
            raise ShapeError("%s (X='%s' %s, Y='%s' %s)" % (
                e, xv.name, list(xs), yv.name, list(ys)))
        ctx.set(op.output("Out"), out, dtype)
    return rule


for _n in _ELEMENTWISE:
    register_shape(_n)(_binop_rule(bool_out=False))
for _n in _COMPARE + _LOGICAL:
    register_shape(_n)(_binop_rule(bool_out=True))


@register_shape("logical_not")
def _logical_not_shape(ctx, op):
    ctx.set(op.output("Out"), ctx.shape(op.input("X")), np.dtype(bool))


# ---------------------------------------------------------------------------
# shape-preserving unaries (activations, scale, clip, dropout, softmax...)
# ---------------------------------------------------------------------------

_LIKE_X = (
    # activation_op.cc table
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "sqrt", "rsqrt",
    "abs", "ceil", "floor", "round", "cos", "sin", "reciprocal", "log",
    "square", "softplus", "softsign", "relu", "sign", "erf",
    "relu6", "leaky_relu", "elu", "gelu", "brelu", "stanh", "hard_sigmoid",
    "hard_shrink", "soft_shrink", "thresholded_relu", "swish", "selu",
    "prelu",
    # shape-preserving tensor/nn ops
    "scale", "clip", "softmax", "log_softmax", "label_smooth",
    "sigmoid_cross_entropy_with_logits", "increment", "fill_zeros_like",
    "square_error_cost", "assign",
)


def _like_x_rule(ctx, op):
    ctx.set(op.output("Out"), ctx.shape(op.input("X")),
            ctx.dtype(op.input("X")))


for _n in _LIKE_X:
    register_shape(_n)(_like_x_rule)


@register_shape("dropout")
def _dropout_shape(ctx, op):
    xs, dt = ctx.shape(op.input("X")), ctx.dtype(op.input("X"))
    ctx.set(op.output("Out"), xs, dt)
    ctx.set(op.output("Mask"), xs, dt)


@register_shape("cast")
def _cast_shape(ctx, op):
    ctx.set(op.output("Out"), ctx.shape(op.input("X")),
            convert_np_dtype(op.attr("out_dtype")))


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

@register_shape("mean")
def _mean_shape(ctx, op):
    ctx.set(op.output("Out"), (), ctx.dtype(op.input("X")))


def _reduce_rule(ctx, op):
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("X"))
    if xs is None:
        ctx.set(op.output("Out"), None, dt)
        return
    dim = op.attr("dim", [0])
    keep = op.attr("keep_dim", False)
    if op.attr("reduce_all", False) or dim is None:
        out = tuple([1] * len(xs)) if keep else ()
        ctx.set(op.output("Out"), out, dt)
        return
    axes = {_norm_axis(d, len(xs)) for d in dim}
    bad = [a for a in axes if a < 0 or a >= len(xs)]
    if bad:
        raise ShapeError("reduce dim %s out of range for rank %d"
                         % (sorted(bad), len(xs)))
    out = tuple(1 if i in axes else d for i, d in enumerate(xs)) if keep \
        else tuple(d for i, d in enumerate(xs) if i not in axes)
    ctx.set(op.output("Out"), out, dt)


for _n in ("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
           "reduce_prod"):
    register_shape(_n)(_reduce_rule)


@register_shape("sum")
def _sum_shape(ctx, op):
    vs = op.input_list("X")
    shapes = [ctx.shape(v) for v in vs]
    out = None
    for v, s in zip(vs, shapes):
        if s is None:
            continue
        if out is None:
            out = s
            continue
        try:
            out = static_bcast_shape(out, s, -1)
        except ValueError:
            raise ShapeError(
                "sum inputs have incompatible shapes; '%s' is %s vs %s"
                % (v.name, list(s), list(out)))
    ctx.set(op.output("Out"), out, ctx.dtype(vs[0]) if vs else None)


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------

@register_shape("mul")
def _mul_shape(ctx, op):
    xv, yv = op.input("X"), op.input("Y")
    xs, ys = ctx.shape(xv), ctx.shape(yv)
    if xs is None or ys is None:
        ctx.set(op.output("Out"), None, ctx.dtype(xv))
        return
    xnc = op.attr("x_num_col_dims", 1)
    ync = op.attr("y_num_col_dims", 1)
    if not (0 < xnc < max(len(xs), 1) + 1 and 0 < ync < max(len(ys), 1) + 1):
        raise ShapeError("num_col_dims (%d, %d) out of range for shapes "
                         "%s, %s" % (xnc, ync, list(xs), list(ys)))
    k1, k2 = _prod(xs[xnc:]), _prod(ys[:ync])
    if k1 != -1 and k2 != -1 and k1 != k2:
        raise ShapeError(
            "contraction dims differ: X '%s' %s flattens to [*, %d] but "
            "Y '%s' %s flattens to [%d, *]"
            % (xv.name, list(xs), k1, yv.name, list(ys), k2))
    ctx.set(op.output("Out"), tuple(xs[:xnc]) + tuple(ys[ync:]),
            ctx.dtype(xv))


@register_shape("matmul")
def _matmul_shape(ctx, op):
    xv, yv = op.input("X"), op.input("Y")
    xs, ys = ctx.shape(xv), ctx.shape(yv)
    if xs is None or ys is None or len(xs) < 2 or len(ys) < 2:
        ctx.set(op.output("Out"), None, ctx.dtype(xv))
        return
    if op.attr("transpose_X", False):
        xs = xs[:-2] + (xs[-1], xs[-2])
    if op.attr("transpose_Y", False):
        ys = ys[:-2] + (ys[-1], ys[-2])
    if xs[-1] != -1 and ys[-2] != -1 and xs[-1] != ys[-2]:
        raise ShapeError(
            "matmul contraction mismatch: X '%s' ends in %d but Y '%s' "
            "starts with %d (effective shapes %s x %s)"
            % (xv.name, xs[-1], yv.name, ys[-2], list(xs), list(ys)))
    try:
        batch = static_bcast_shape(xs[:-2], ys[:-2], -1)
    except ValueError:
        raise ShapeError("matmul batch dims %s and %s do not broadcast"
                         % (list(xs[:-2]), list(ys[:-2])))
    ctx.set(op.output("Out"), tuple(batch) + (xs[-2], ys[-1]),
            ctx.dtype(xv))


# ---------------------------------------------------------------------------
# tensor manipulation
# ---------------------------------------------------------------------------

@register_shape("concat")
def _concat_shape(ctx, op):
    vs = op.input_list("X")
    shapes = [ctx.shape(v) for v in vs]
    if any(s is None for s in shapes) or not shapes:
        ctx.set(op.output("Out"), None, ctx.dtype(vs[0]) if vs else None)
        return
    rank = len(shapes[0])
    if any(len(s) != rank for s in shapes):
        raise ShapeError("concat inputs have mixed ranks: %s"
                         % [list(s) for s in shapes])
    axis = _norm_axis(op.attr("axis", 0), rank)
    out = list(shapes[0])
    total = 0
    for s in shapes:
        for i in range(rank):
            if i == axis:
                continue
            if out[i] == -1:
                out[i] = s[i]
            elif s[i] != -1 and s[i] != out[i]:
                raise ShapeError(
                    "concat inputs disagree on non-concat dim %d: %s"
                    % (i, [list(t) for t in shapes]))
        total = -1 if (total == -1 or s[axis] == -1) else total + s[axis]
    out[axis] = total
    ctx.set(op.output("Out"), tuple(out), ctx.dtype(vs[0]))


@register_shape("split")
def _split_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("X"))
    outs = op.output_list("Out")
    if xs is None:
        for v in outs:
            ctx.set(v, None, dt)
        return
    axis = _norm_axis(op.attr("axis", 0), len(xs))
    sections = op.attr("sections")
    if sections:
        if xs[axis] != -1 and sum(sections) != xs[axis]:
            raise ShapeError("split sections %s do not sum to dim %d"
                             % (sections, xs[axis]))
        sizes = sections
    else:
        num = op.attr("num", 0) or len(outs)
        if xs[axis] != -1 and xs[axis] % num != 0:
            raise ShapeError("split num %d does not divide dim %d"
                             % (num, xs[axis]))
        sizes = [(-1 if xs[axis] == -1 else xs[axis] // num)] * num
    for v, size in zip(outs, sizes):
        ctx.set(v, xs[:axis] + (size,) + xs[axis + 1:], dt)


@register_shape("reshape", "reshape2")
def _reshape_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("X"))
    shape = list(op.attr("shape") or ())
    if xs is None or not shape:
        ctx.set(op.output("Out"), None, dt)
        return
    out = []
    for i, s in enumerate(shape):
        if s == 0:  # ref reshape_op: 0 copies the input dim
            if i >= len(xs):
                raise ShapeError("reshape dim %d copies input dim %d but "
                                 "input rank is %d" % (i, i, len(xs)))
            out.append(xs[i])
        else:
            out.append(int(s))
    n_in = _prod(xs)
    negs = [i for i, s in enumerate(out) if s == -1]
    if len(negs) > 1:
        raise ShapeError("reshape target %s has more than one -1" % (out,))
    if negs:
        rest = _prod([s for s in out if s != -1])
        if n_in != -1 and rest > 0:
            if n_in % rest != 0:
                raise ShapeError(
                    "cannot reshape %s (%d elements) into %s"
                    % (list(xs), n_in, out))
            out[negs[0]] = n_in // rest
    elif n_in != -1:
        if _prod(out) != n_in:
            raise ShapeError("cannot reshape %s (%d elements) into %s "
                             "(%d elements)" % (list(xs), n_in, out,
                                                _prod(out)))
    ctx.set(op.output("Out"), tuple(out), dt)


@register_shape("squeeze", "squeeze2")
def _squeeze_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("X"))
    if xs is None:
        ctx.set(op.output("Out"), None, dt)
        return
    axes = op.attr("axes", [])
    if axes:
        axes = {_norm_axis(a, len(xs)) for a in axes}
        for a in axes:
            if xs[a] not in (1, -1):
                raise ShapeError("squeeze axis %d has size %d (must be 1) "
                                 "in %s" % (a, xs[a], list(xs)))
        out = tuple(d for i, d in enumerate(xs) if i not in axes)
    else:
        out = tuple(d for d in xs if d != 1)
    ctx.set(op.output("Out"), out, dt)


@register_shape("unsqueeze", "unsqueeze2")
def _unsqueeze_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("X"))
    if xs is None:
        ctx.set(op.output("Out"), None, dt)
        return
    out = list(xs)
    for a in sorted(op.attr("axes")):
        out.insert(a if a >= 0 else a + len(out) + 1, 1)
    ctx.set(op.output("Out"), tuple(out), dt)


@register_shape("flatten", "flatten2")
def _flatten_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("X"))
    if xs is None:
        ctx.set(op.output("Out"), None, dt)
        return
    axis = op.attr("axis", 1)
    lead = _prod(xs[:axis]) if axis > 0 else 1
    trail = _prod(xs[axis:])
    ctx.set(op.output("Out"), (lead, trail), dt)


@register_shape("transpose", "transpose2")
def _transpose_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("X"))
    perm = op.attr("axis")
    if xs is None or perm is None:
        ctx.set(op.output("Out"), None, dt)
        return
    if sorted(_norm_axis(a, len(xs)) for a in perm) != list(range(len(xs))):
        raise ShapeError("transpose perm %s is not a permutation of rank %d"
                         % (perm, len(xs)))
    ctx.set(op.output("Out"),
            tuple(xs[_norm_axis(a, len(xs))] for a in perm), dt)


@register_shape("stack")
def _stack_shape(ctx, op):
    vs = op.input_list("X")
    shapes = [ctx.shape(v) for v in vs]
    if any(s is None for s in shapes) or not shapes:
        ctx.set(op.output("Out") or op.output("Y"), None,
                ctx.dtype(vs[0]) if vs else None)
        return
    base = shapes[0]
    for s in shapes[1:]:
        if len(s) != len(base) or any(
                a != -1 and b != -1 and a != b for a, b in zip(s, base)):
            raise ShapeError("stack inputs disagree: %s"
                             % [list(t) for t in shapes])
    axis = _norm_axis(op.attr("axis", 0), len(base) + 1)
    out = base[:axis] + (len(vs),) + base[axis:]
    ctx.set(op.output("Out") or op.output("Y"), out, ctx.dtype(vs[0]))


@register_shape("slice")
def _slice_shape(ctx, op):
    xs = ctx.shape(op.input("Input"))
    dt = ctx.dtype(op.input("Input"))
    if xs is None:
        ctx.set(op.output("Out"), None, dt)
        return
    out = list(xs)
    for a, s, e in zip(op.attr("axes"), op.attr("starts"), op.attr("ends")):
        a = _norm_axis(a, len(xs))
        d = xs[a]
        if d == -1:
            out[a] = (e - s) if (s >= 0 and 0 <= e < 10 ** 6) else -1
            continue
        s2 = min(d, s + d if s < 0 else s)
        e2 = min(d, e + d if e < 0 else e)
        out[a] = max(0, e2 - s2)
    ctx.set(op.output("Out"), tuple(out), dt)


@register_shape("gather")
def _gather_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    idx = ctx.shape(op.input("Index"))
    dt = ctx.dtype(op.input("X"))
    if xs is None or idx is None:
        ctx.set(op.output("Out"), None, dt)
        return
    # the lowering flattens the index to 1-D (jnp.take along axis 0)
    ctx.set(op.output("Out"), (_prod(idx),) + tuple(xs[1:]), dt)


@register_shape("expand")
def _expand_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("X"))
    times = op.attr("expand_times")
    if xs is None or times is None:
        ctx.set(op.output("Out"), None, dt)
        return
    if len(times) != len(xs):
        raise ShapeError("expand_times %s rank != input rank %d"
                         % (times, len(xs)))
    ctx.set(op.output("Out"),
            tuple(-1 if d == -1 else d * t for d, t in zip(xs, times)), dt)


@register_shape("shape")
def _shape_shape(ctx, op):
    xs = ctx.shape(op.input("X") or op.input("Input"))
    ctx.set(op.output("Out"), (len(xs),) if xs is not None else None,
            np.dtype(np.int32))


# ---------------------------------------------------------------------------
# fills / random
# ---------------------------------------------------------------------------

def _attr_shape_rule(ctx, op):
    shape = op.attr("shape")
    ctx.set(op.output("Out"),
            tuple(int(s) for s in shape) if shape is not None else None,
            convert_np_dtype(op.attr("dtype", "float32")))


for _n in ("fill_constant", "uniform_random", "gaussian_random",
           "truncated_gaussian_random"):
    register_shape(_n)(_attr_shape_rule)


def _batch_size_like_rule(ctx, op):
    ref = op.input("Input") or op.input("X")
    rs = ctx.shape(ref)
    shape = op.attr("shape")
    if shape is None:
        ctx.set(op.output("Out"), None, None)
        return
    out = [int(s) for s in shape]
    in_idx = op.attr("input_dim_idx", 0)
    out_idx = op.attr("output_dim_idx", 0)
    if rs is not None and 0 <= in_idx < len(rs) and 0 <= out_idx < len(out):
        out[out_idx] = rs[in_idx]
    ctx.set(op.output("Out"), tuple(out),
            convert_np_dtype(op.attr("dtype", "float32")))


for _n in ("fill_constant_batch_size_like", "uniform_random_batch_size_like",
           "gaussian_random_batch_size_like"):
    register_shape(_n)(_batch_size_like_rule)


# ---------------------------------------------------------------------------
# embedding / indexing
# ---------------------------------------------------------------------------

def _ids_shape(ids):
    """Lowerings squeeze a trailing [.., 1] ids dim (LoD-era convention)."""
    if ids is not None and len(ids) >= 2 and ids[-1] == 1:
        return ids[:-1]
    return ids


@register_shape("lookup_table", "sharded_lookup_table")
def _lookup_table_shape(ctx, op):
    # sharded_lookup_table is the transpiled (mesh-routed) form of
    # lookup_table — identical shape contract, so transpiled programs
    # verify as first-class citizens (ISSUE 13)
    ws = ctx.shape(op.input("W"))
    ids = _ids_shape(ctx.shape(op.input("Ids")))
    if ws is None or ids is None:
        ctx.set(op.output("Out"), None, ctx.dtype(op.input("W")))
        return
    if len(ws) != 2:
        raise ShapeError("%s W '%s' must be 2-D, got %s"
                         % (op.type, op.input("W").name, list(ws)))
    ctx.set(op.output("Out"), tuple(ids) + (ws[1],),
            ctx.dtype(op.input("W")))


@register_shape("scatter")
def _scatter_shape(ctx, op):
    """Row scatter (set/add): Out has X's shape; Updates' trailing dims
    must match X's (the sparse-grad accumulation path — optimizer.py
    grad-acc and the ops/scatter.py kernel's symbolic form)."""
    xs = ctx.shape(op.input("X"))
    us = ctx.shape(op.input("Updates"))
    dt = ctx.dtype(op.input("X"))
    if xs is not None and us is not None and len(us) >= 1 \
            and len(xs) >= 1:
        xt, ut = tuple(xs[1:]), tuple(us[1:])
        if len(xt) == len(ut) and any(
                a != -1 and b != -1 and a != b for a, b in zip(xt, ut)):
            raise ShapeError(
                "scatter Updates '%s' trailing dims %s do not match X "
                "'%s' trailing dims %s"
                % (op.input("Updates").name, list(us), op.input("X").name,
                   list(xs)))
    ctx.set(op.output("Out"), xs, dt)


@register_shape("one_hot")
def _one_hot_shape(ctx, op):
    ids = _ids_shape(ctx.shape(op.input("X")))
    depth = op.attr("depth")
    if ids is None or depth is None:
        ctx.set(op.output("Out"), None, np.dtype(np.float32))
        return
    ctx.set(op.output("Out"), tuple(ids) + (int(depth),),
            np.dtype(np.float32))


# ---------------------------------------------------------------------------
# conv / pool / norm
# ---------------------------------------------------------------------------

def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 2


def _conv_dim(size, k, pad, stride, dil):
    if size == -1:
        return -1
    eff = dil * (k - 1) + 1
    out = (size + 2 * pad - eff) // stride + 1
    if out <= 0:
        raise ShapeError(
            "conv/pool window (k=%d, pad=%d, stride=%d, dilation=%d) does "
            "not fit input dim %d" % (k, pad, stride, dil, size))
    return out


@register_shape("conv2d", "depthwise_conv2d")
def _conv2d_shape(ctx, op):
    xs = ctx.shape(op.input("Input"))
    ws = ctx.shape(op.input("Filter"))
    dt = ctx.dtype(op.input("Input"))
    if xs is None or ws is None or len(xs) != 4 or len(ws) != 4:
        ctx.set(op.output("Output"), None, dt)
        return
    strides = _pair(op.attr("strides", [1, 1]))
    pads = _pair(op.attr("paddings", [0, 0]))
    dil = _pair(op.attr("dilations", [1, 1]))
    groups = op.attr("groups", 1) or 1
    if op.type == "depthwise_conv2d" and xs[1] != -1:
        groups = xs[1]
    if xs[1] != -1 and ws[1] != -1 and ws[1] * groups != xs[1]:
        raise ShapeError(
            "in-channels mismatch: input '%s' has C=%d but filter '%s' is "
            "%s with groups=%d (needs C = %d)"
            % (op.input("Input").name, xs[1], op.input("Filter").name,
               list(ws), groups, ws[1] * groups))
    oh = _conv_dim(xs[2], ws[2], pads[0], strides[0], dil[0])
    ow = _conv_dim(xs[3], ws[3], pads[1], strides[1], dil[1])
    ctx.set(op.output("Output"), (xs[0], ws[0], oh, ow), dt)


@register_shape("pool2d")
def _pool2d_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("X"))
    if xs is None or len(xs) != 4:
        ctx.set(op.output("Out"), None, dt)
        return
    ksize = _pair(op.attr("ksize"))
    if op.attr("global_pooling", False) or (
            op.attr("adaptive", False) and ksize == (1, 1)):
        ctx.set(op.output("Out"), (xs[0], xs[1], 1, 1), dt)
        return
    if op.attr("adaptive", False):
        ctx.set(op.output("Out"), (xs[0], xs[1]) + ksize, dt)
        return
    strides = _pair(op.attr("strides", [1, 1]))
    pads = _pair(op.attr("paddings", [0, 0]))
    ceil_mode = op.attr("ceil_mode", False)

    def dim(size, k, pad, stride):
        if size == -1:
            return -1
        if ceil_mode:
            return -(-(size + 2 * pad - k) // stride) + 1
        return (size + 2 * pad - k) // stride + 1

    ctx.set(op.output("Out"),
            (xs[0], xs[1], dim(xs[2], ksize[0], pads[0], strides[0]),
             dim(xs[3], ksize[1], pads[1], strides[1])), dt)


@register_shape("fused_conv2d")
def _fused_conv2d_shape(ctx, op):
    """conv2d+batch_norm(+add)(+relu) chain fused by
    ``core/epilogue_fusion.py``: conv output geometry, BN channel-vector
    checks, and the residual must match the conv output shape."""
    xs = ctx.shape(op.input("Input"))
    ws = ctx.shape(op.input("Filter"))
    dt = ctx.dtype(op.input("Input"))
    out_shape = None
    if xs is not None and ws is not None and len(xs) == 4 and len(ws) == 4:
        strides = _pair(op.attr("strides", [1, 1]))
        pads = _pair(op.attr("paddings", [0, 0]))
        dil = _pair(op.attr("dilations", [1, 1]))
        groups = op.attr("groups", 1) or 1
        if xs[1] != -1 and ws[1] != -1 and ws[1] * groups != xs[1]:
            raise ShapeError(
                "in-channels mismatch: input '%s' has C=%d but filter '%s' "
                "is %s with groups=%d (needs C = %d)"
                % (op.input("Input").name, xs[1], op.input("Filter").name,
                   list(ws), groups, ws[1] * groups))
        oh = _conv_dim(xs[2], ws[2], pads[0], strides[0], dil[0])
        ow = _conv_dim(xs[3], ws[3], pads[1], strides[1], dil[1])
        out_shape = (xs[0], ws[0], oh, ow)
    ctx.set(op.output("Y"), out_shape, dt)
    c = ws[0] if ws is not None else -1
    for slot in ("Scale", "Bias", "Mean", "Variance"):
        v = op.input(slot)
        s = ctx.shape(v)
        if v is not None and s is not None and c != -1 and tuple(s) != (c,):
            raise ShapeError(
                "fused_conv2d %s '%s' has shape %s but the channel dim is "
                "%d" % (slot, v.name, list(s), c))
    rv = op.input("Residual")
    rs = ctx.shape(rv) if rv is not None else None
    if rs is not None and out_shape is not None:
        known = all(a == b or -1 in (a, b) for a, b in zip(rs, out_shape))
        if len(rs) != 4 or not known:
            raise ShapeError(
                "fused_conv2d Residual '%s' has shape %s but the conv "
                "output is %s" % (rv.name, list(rs), list(out_shape)))
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        ctx.set(op.output(slot), (c,) if c != -1 else None, None)


@register_shape("batch_norm")
def _batch_norm_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("X"))
    ctx.set(op.output("Y"), xs, dt)
    if xs is None:
        return
    layout = op.attr("data_layout", "NCHW")
    c = xs[1 if layout == "NCHW" else -1]
    for slot in ("Scale", "Bias", "Mean", "Variance"):
        v = op.input(slot)
        s = ctx.shape(v)
        if v is not None and s is not None and c != -1 and \
                tuple(s) != (c,):
            raise ShapeError(
                "batch_norm %s '%s' has shape %s but the channel dim is %d"
                % (slot, v.name, list(s), c))
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        ctx.set(op.output(slot), (c,) if c != -1 else None, None)


@register_shape("layer_norm")
def _layer_norm_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    ctx.set(op.output("Y"), xs, ctx.dtype(op.input("X")))
    sv = op.input("Scale")
    ss = ctx.shape(sv)
    begin = op.attr("begin_norm_axis", 1)
    if xs is not None and ss is not None and len(ss) == 1:
        norm = _prod(xs[begin:])
        if norm != -1 and ss[0] != -1 and ss[0] != norm:
            raise ShapeError(
                "layer_norm Scale '%s' has %d elements but the normalized "
                "slice of %s has %d" % (sv.name, ss[0], list(xs), norm))


@register_shape("group_norm")
def _group_norm_shape(ctx, op):
    ctx.set(op.output("Y"), ctx.shape(op.input("X")),
            ctx.dtype(op.input("X")))


# ---------------------------------------------------------------------------
# losses / metrics / search
# ---------------------------------------------------------------------------

@register_shape("cross_entropy")
def _cross_entropy_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    if xs is None:
        ctx.set(op.output("Y"), None, ctx.dtype(op.input("X")))
        return
    ctx.set(op.output("Y"), tuple(xs[:-1]) + (1,), ctx.dtype(op.input("X")))


@register_shape("softmax_with_cross_entropy")
def _swce_shape(ctx, op):
    xs = ctx.shape(op.input("Logits"))
    dt = ctx.dtype(op.input("Logits"))
    if xs is None:
        ctx.set(op.output("Loss"), None, dt)
        return
    ctx.set(op.output("Loss"), tuple(xs[:-1]) + (1,), dt)
    ctx.set(op.output("Softmax"), xs, dt)


@register_shape("accuracy")
def _accuracy_shape(ctx, op):
    ctx.set(op.output("Accuracy"), (), np.dtype(np.float32))
    ctx.set(op.output("Correct"), (1,), np.dtype(np.int32))
    ctx.set(op.output("Total"), (1,), np.dtype(np.int32))


@register_shape("top_k")
def _top_k_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    k = int(op.attr("k", 1))
    if xs is None:
        return
    if xs[-1] != -1 and k > xs[-1]:
        raise ShapeError("top_k k=%d exceeds last dim of %s" % (k, list(xs)))
    out = tuple(xs[:-1]) + (k,)
    ctx.set(op.output("Out"), out, ctx.dtype(op.input("X")))
    # int32: the lowering emits int32 indices (x64 is off — see math_ops)
    ctx.set(op.output("Indices"), out, np.dtype(np.int32))


@register_shape("argmax", "argmin")
def _arg_shape(ctx, op):
    xs = ctx.shape(op.input("X"))
    if xs is None:
        ctx.set(op.output("Out"), None, np.dtype(np.int32))
        return
    axis = _norm_axis(op.attr("axis", -1), len(xs))
    ctx.set(op.output("Out"), xs[:axis] + xs[axis + 1:], np.dtype(np.int32))


# ---------------------------------------------------------------------------
# incremental decode (KV-cache step programs — serving/decode_batcher.py)
# ---------------------------------------------------------------------------

@register_shape("kv_cache_write")
def _kv_cache_write_shape(ctx, op):
    cs = ctx.shape(op.input("Cache"))
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("Cache"))
    if cs is not None and xs is not None:
        if len(xs) != len(cs) - 1:
            raise ShapeError(
                "kv_cache_write X '%s' %s must be Cache '%s' %s minus the "
                "capacity axis" % (op.input("X").name, list(xs),
                                   op.input("Cache").name, list(cs)))
        for a, b in zip((xs[0],) + tuple(xs[1:]),
                        (cs[0],) + tuple(cs[2:])):
            if a != -1 and b != -1 and a != b:
                raise ShapeError(
                    "kv_cache_write X '%s' %s does not slot into Cache "
                    "'%s' %s" % (op.input("X").name, list(xs),
                                 op.input("Cache").name, list(cs)))
    ctx.set(op.output("Out"), cs, dt)


@register_shape("cached_attention")
def _cached_attention_shape(ctx, op):
    qs = ctx.shape(op.input("Q"))
    ks = ctx.shape(op.input("CacheK"))
    vs = ctx.shape(op.input("CacheV"))
    dt = ctx.dtype(op.input("Q"))
    h = int(op.attr("num_heads", 1))
    if ks is not None:
        if len(ks) != 3:
            raise ShapeError("cached_attention CacheK '%s' must be "
                             "[B, C, H*D], got %s"
                             % (op.input("CacheK").name, list(ks)))
        if ks[-1] != -1 and ks[-1] % h != 0:
            raise ShapeError(
                "cached_attention CacheK '%s' last dim %d is not divisible "
                "by num_heads=%d" % (op.input("CacheK").name, ks[-1], h))
    if qs is not None and ks is not None and qs[-1] != -1 \
            and ks[-1] != -1 and qs[-1] != ks[-1]:
        raise ShapeError(
            "cached_attention Q '%s' feature dim %d != CacheK '%s' dim %d"
            % (op.input("Q").name, qs[-1], op.input("CacheK").name, ks[-1]))
    if vs is None or qs is None:
        ctx.set(op.output("Out"), qs, dt)
        return
    ctx.set(op.output("Out"), tuple(qs[:-1]) + (vs[-1],), dt)


@register_shape("kv_cache_write_chunk")
def _kv_cache_write_chunk_shape(ctx, op):
    cs = ctx.shape(op.input("Cache"))
    xs = ctx.shape(op.input("X"))
    dt = ctx.dtype(op.input("Cache"))
    if cs is not None and xs is not None:
        if len(xs) != len(cs):
            raise ShapeError(
                "kv_cache_write_chunk X '%s' %s must be [B, K, ...] with "
                "the same rank as Cache '%s' %s (K rows scatter into the "
                "capacity axis)" % (op.input("X").name, list(xs),
                                    op.input("Cache").name, list(cs)))
        for a, b in zip((xs[0],) + tuple(xs[2:]),
                        (cs[0],) + tuple(cs[2:])):
            if a != -1 and b != -1 and a != b:
                raise ShapeError(
                    "kv_cache_write_chunk X '%s' %s does not slot into "
                    "Cache '%s' %s" % (op.input("X").name, list(xs),
                                       op.input("Cache").name, list(cs)))
    ctx.set(op.output("Out"), cs, dt)


@register_shape("cached_attention_chunk")
def _cached_attention_chunk_shape(ctx, op):
    qs = ctx.shape(op.input("Q"))
    ks = ctx.shape(op.input("CacheK"))
    vs = ctx.shape(op.input("CacheV"))
    dt = ctx.dtype(op.input("Q"))
    h = int(op.attr("num_heads", 1))
    if qs is not None and len(qs) != 3:
        raise ShapeError("cached_attention_chunk Q '%s' must be "
                         "[B, K, H*D], got %s"
                         % (op.input("Q").name, list(qs)))
    if ks is not None:
        if len(ks) != 3:
            raise ShapeError("cached_attention_chunk CacheK '%s' must be "
                             "[B, C, H*D], got %s"
                             % (op.input("CacheK").name, list(ks)))
        if ks[-1] != -1 and ks[-1] % h != 0:
            raise ShapeError(
                "cached_attention_chunk CacheK '%s' last dim %d is not "
                "divisible by num_heads=%d"
                % (op.input("CacheK").name, ks[-1], h))
    if qs is not None and ks is not None and qs[-1] != -1 \
            and ks[-1] != -1 and qs[-1] != ks[-1]:
        raise ShapeError(
            "cached_attention_chunk Q '%s' feature dim %d != CacheK "
            "'%s' dim %d" % (op.input("Q").name, qs[-1],
                             op.input("CacheK").name, ks[-1]))
    if vs is None or qs is None:
        ctx.set(op.output("Out"), qs, dt)
        return
    ctx.set(op.output("Out"), tuple(qs[:-1]) + (vs[-1],), dt)

"""Neural-network ops: conv / pool / normalization / dropout / softmax /
losses / interpolation.

Reference kernels: ``paddle/fluid/operators/conv_op.cc`` (+cudnn),
``pool_op.cc``, ``batch_norm_op.cc``, ``layer_norm_op.cc``, ``dropout_op.cc``,
``softmax_with_cross_entropy_op.cc``, ``interpolate_op.cc`` etc. Lowered to
lax convolutions / reduce_window / jnp so XLA maps convs+matmuls onto the MXU
and fuses the elementwise epilogues.
"""

import jax
import jax.numpy as jnp

from ..op_registry import register, get, put, next_rng


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


# ---------------- convolution family ----------------

@register("conv2d", "depthwise_conv2d")
def _conv2d(env, op):
    x = get(env, op.input("Input"))  # NCHW
    w = get(env, op.input("Filter"))  # OIHW
    strides = _pair(op.attr("strides", [1, 1]))
    pads = _pair(op.attr("paddings", [0, 0]))
    dil = _pair(op.attr("dilations", [1, 1]))
    groups = op.attr("groups", 1)
    if op.type == "depthwise_conv2d":
        groups = x.shape[1]
    from ..op_registry import mxu_cast
    x, w = mxu_cast(x, w)
    # bf16 in -> bf16 out under AMP: the TPU conv unit accumulates fp32
    # internally and rounds once at the output. (An explicit f32
    # preferred_element_type would break lax's conv transpose rule, which
    # requires cotangent and operand dtypes to match.)
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    put(env, op.output("Output"), out)


@register("conv3d")
def _conv3d(env, op):
    x = get(env, op.input("Input"))  # NCDHW
    w = get(env, op.input("Filter"))
    s = tuple(op.attr("strides", [1, 1, 1]))
    p = tuple(op.attr("paddings", [0, 0, 0]))
    d = tuple(op.attr("dilations", [1, 1, 1]))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1]), (p[2], p[2])],
        rhs_dilation=d,
        feature_group_count=op.attr("groups", 1),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )
    put(env, op.output("Output"), out)


def conv_transpose_nchw(x, w, strides, pads, dil, groups=1):
    """Transposed conv as a fractionally-strided conv (the reference
    kernel's semantics, ``conv_transpose_op.cc``): w is IOHW
    [Cin, Cout/groups, kh, kw]; output spatial = (i-1)*s - 2p + d*(k-1)+1.
    lhs_dilation inserts the stride zeros; the kernel is spatially flipped
    and I/O-swapped per group into OIHW."""
    cin = w.shape[0]
    cog = w.shape[1]  # Cout / groups
    wf = jnp.flip(w, axis=(2, 3))
    if groups == 1:
        wt = wf.transpose(1, 0, 2, 3)  # [Cout, Cin, kh, kw]
    else:
        wg = wf.reshape((groups, cin // groups, cog) + w.shape[2:])
        wt = wg.transpose(0, 2, 1, 3, 4).reshape(
            (groups * cog, cin // groups) + w.shape[2:])
    kh = (w.shape[2] - 1) * dil[0] + 1
    kw = (w.shape[3] - 1) * dil[1] + 1
    return jax.lax.conv_general_dilated(
        x, wt, window_strides=(1, 1),
        padding=[(kh - 1 - pads[0], kh - 1 - pads[0]),
                 (kw - 1 - pads[1], kw - 1 - pads[1])],
        lhs_dilation=strides,
        rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


@register("conv2d_transpose")
def _conv2d_transpose(env, op):
    x = get(env, op.input("Input"))
    w = get(env, op.input("Filter"))  # IOHW in paddle transpose conv
    strides = _pair(op.attr("strides", [1, 1]))
    pads = _pair(op.attr("paddings", [0, 0]))
    dil = _pair(op.attr("dilations", [1, 1]))
    from ..op_registry import mxu_cast
    x, w = mxu_cast(x, w)
    put(env, op.output("Output"),
        conv_transpose_nchw(x, w, strides, pads, dil,
                            op.attr("groups", 1) or 1))


# ---------------- pooling ----------------

@register("pool2d")
def _pool2d(env, op):
    x = get(env, op.input("X"))  # NCHW
    ptype = op.attr("pooling_type", "max")
    ksize = _pair(op.attr("ksize"))
    strides = _pair(op.attr("strides", [1, 1]))
    pads = _pair(op.attr("paddings", [0, 0]))
    global_pool = op.attr("global_pooling", False)
    adaptive = op.attr("adaptive", False)
    exclusive = op.attr("exclusive", True)
    ceil_mode = op.attr("ceil_mode", False)

    if global_pool or (adaptive and ksize == (1, 1)):
        red = jnp.max if ptype == "max" else jnp.mean
        put(env, op.output("Out"), red(x, axis=(2, 3), keepdims=True))
        return
    if adaptive:
        # adaptive pooling to output size ksize: split H/W into equal bins
        n, c, h, w = x.shape
        oh, ow = ksize
        assert h % oh == 0 and w % ow == 0, "adaptive pool needs divisible dims"
        xr = x.reshape(n, c, oh, h // oh, ow, w // ow)
        red = jnp.max if ptype == "max" else jnp.mean
        put(env, op.output("Out"), red(xr, axis=(3, 5)))
        return

    pad_cfg = [(0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1])]
    if ceil_mode:
        # extend padding on the high side so the last window fits
        n, c, h, w = x.shape
        out_h = -(-(h + 2 * pads[0] - ksize[0]) // strides[0]) + 1
        out_w = -(-(w + 2 * pads[1] - ksize[1]) // strides[1]) + 1
        need_h = (out_h - 1) * strides[0] + ksize[0] - (h + 2 * pads[0])
        need_w = (out_w - 1) * strides[1] + ksize[1] - (w + 2 * pads[1])
        pad_cfg = [(0, 0), (0, 0),
                   (pads[0], pads[0] + max(0, need_h)),
                   (pads[1], pads[1] + max(0, need_w))]
    window = (1, 1) + ksize
    stride = (1, 1) + strides
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, stride, pad_cfg)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride, pad_cfg)
        if exclusive and (pads != (0, 0) or ceil_mode):
            ones = jnp.ones_like(x)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, stride, pad_cfg)
            out = s / cnt
        else:
            out = s / float(ksize[0] * ksize[1])
    put(env, op.output("Out"), out)


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


@register("pool3d")
def _pool3d(env, op):
    """Ref ``pool_op.cc`` pool3d (NCDHW): max/avg over 3-D windows with
    ceil_mode / exclusive / adaptive / global parity."""
    x = get(env, op.input("X"))  # NCDHW
    ptype = op.attr("pooling_type", "max")
    ksize = _triple(op.attr("ksize"))
    strides = _triple(op.attr("strides", [1, 1, 1]))
    pads = _triple(op.attr("paddings", [0, 0, 0]))
    if op.attr("global_pooling", False):
        red = jnp.max if ptype == "max" else jnp.mean
        put(env, op.output("Out"), red(x, axis=(2, 3, 4), keepdims=True))
        return
    if op.attr("adaptive", False):
        n, c, d, h, w = x.shape
        od, oh, ow = ksize
        assert d % od == 0 and h % oh == 0 and w % ow == 0, \
            "adaptive pool3d needs divisible dims"
        xr = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
        red = jnp.max if ptype == "max" else jnp.mean
        put(env, op.output("Out"), red(xr, axis=(3, 5, 7)))
        return
    pad_cfg = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    if op.attr("ceil_mode", False):
        n, c = x.shape[:2]
        for i, (sp, kk, st, pp) in enumerate(zip(x.shape[2:], ksize,
                                                 strides, pads)):
            out_i = -(-(sp + 2 * pp - kk) // st) + 1
            need = (out_i - 1) * st + kk - (sp + 2 * pp)
            pad_cfg[2 + i] = (pp, pp + max(0, need))
    window = (1, 1) + ksize
    stride = (1, 1) + strides
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, stride,
                                    pad_cfg)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride,
                                  pad_cfg)
        if op.attr("exclusive", True) and (any(pads)
                                           or op.attr("ceil_mode", False)):
            cnt = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                        window, stride, pad_cfg)
            out = s / cnt
        else:
            out = s / float(ksize[0] * ksize[1] * ksize[2])
    put(env, op.output("Out"), out)


@register("conv3d_transpose")
def _conv3d_transpose(env, op):
    """Ref ``conv_transpose_op.cc`` conv3d_transpose (NCDHW, IODHW
    kernel): fractionally-strided conv, like the 2-D case."""
    x = get(env, op.input("Input"))
    w = get(env, op.input("Filter"))  # [Cin, Cout/g, kd, kh, kw]
    s = _triple(op.attr("strides", [1, 1, 1]))
    p = _triple(op.attr("paddings", [0, 0, 0]))
    d = _triple(op.attr("dilations", [1, 1, 1]))
    groups = op.attr("groups", 1) or 1
    from ..op_registry import mxu_cast
    x, w = mxu_cast(x, w)
    cin, cog = w.shape[0], w.shape[1]
    wf = jnp.flip(w, axis=(2, 3, 4))
    if groups == 1:
        wt = wf.transpose(1, 0, 2, 3, 4)
    else:
        wg = wf.reshape((groups, cin // groups, cog) + w.shape[2:])
        wt = wg.transpose(0, 2, 1, 3, 4, 5).reshape(
            (groups * cog, cin // groups) + w.shape[2:])
    kd = [(w.shape[2 + i] - 1) * d[i] + 1 for i in range(3)]
    out = jax.lax.conv_general_dilated(
        x, wt, window_strides=(1, 1, 1),
        padding=[(kd[i] - 1 - p[i], kd[i] - 1 - p[i]) for i in range(3)],
        lhs_dilation=s, rhs_dilation=d,
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    put(env, op.output("Output"), out)


@register("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(env, op):
    """Ref ``conv_transpose_op.cc`` depthwise variant: groups == Cin."""
    x = get(env, op.input("Input"))
    w = get(env, op.input("Filter"))
    strides = _pair(op.attr("strides", [1, 1]))
    pads = _pair(op.attr("paddings", [0, 0]))
    dil = _pair(op.attr("dilations", [1, 1]))
    from ..op_registry import mxu_cast
    x, w = mxu_cast(x, w)
    put(env, op.output("Output"),
        conv_transpose_nchw(x, w, strides, pads, dil, groups=x.shape[1]))


@register("fused_conv2d")
def _fused_conv2d(env, op):
    """conv2d + batch_norm (+residual add)(+relu) as ONE op, produced by
    the epilogue-fusion rewrite (``core/epilogue_fusion.py``). On a single
    TPU with a supported geometry it lowers to the Pallas epilogue kernels
    (``ops/fused_conv.py``: the conv-out stats pass and the separate
    residual/relu passes leave the HBM bytes model); everywhere else it
    replays the absorbed original ops verbatim, so the rewrite is
    numerics-neutral by construction on the fallback path."""
    from ...ops import fused_conv
    from ..op_registry import mxu_cast, run_op
    from ..framework import Operator

    is_test = op.attr("is_test", False)
    use_global = op.attr("use_global_stats", False)
    x = get(env, op.input("Input"))  # NCHW
    w = get(env, op.input("Filter"))  # OIHW
    x, w = mxu_cast(x, w)
    strides = _pair(op.attr("strides", [1, 1]))
    pads = _pair(op.attr("paddings", [0, 0]))
    dil = _pair(op.attr("dilations", [1, 1]))
    groups = op.attr("groups", 1) or 1
    res_var = op.input("Residual")
    residual = get(env, res_var) if res_var is not None else None

    decision = fused_conv.gate(x.shape, w.shape, strides, pads, dil,
                               groups, x.dtype.itemsize,
                               residual is not None)
    # trace-time record: which kernel this op actually takes, and why a
    # refusal fell back (the ISSUE 15 no-silent-fallback contract)
    op.attrs["_kernel_choice"] = decision.to_dict()
    if not decision:
        for sub in op.attr("orig_ops") or ():
            if is_test and not sub.attr("is_test", False) \
                    and sub.type in ("batch_norm", "dropout"):
                # a for_test clone flips is_test on the FUSED op only
                sub = Operator(sub.block, sub.type, dict(sub.inputs),
                               dict(sub.outputs),
                               {**sub.attrs, "is_test": True})
            run_op(env, sub)
        return

    scale = get(env, op.input("Scale"))
    bias = get(env, op.input("Bias"))
    mean = get(env, op.input("Mean"))
    var = get(env, op.input("Variance"))
    if residual is not None and residual.dtype != x.dtype:
        from ..op_registry import amp_harmonize
        _, residual = amp_harmonize(x, residual)
    y, mean_out, var_out, saved_mean, saved_var = \
        fused_conv.fused_conv_bn_act(
            x, w, scale, bias, mean, var, strides=strides, paddings=pads,
            eps=op.attr("epsilon", 1e-5), momentum=op.attr("momentum", 0.9),
            act=op.attr("act"), residual=residual, is_test=is_test,
            use_global_stats=use_global)
    put(env, op.output("Y"), y)
    put(env, op.output("MeanOut"), mean_out)
    put(env, op.output("VarianceOut"), var_out)
    if saved_mean is not None:
        put(env, op.output("SavedMean"), saved_mean)
        put(env, op.output("SavedVariance"), saved_var)


# ---------------- normalization ----------------

@register("batch_norm")
def _batch_norm(env, op):
    """Train: normalize by batch stats and update moving stats
    (``MeanOut``/``VarianceOut`` alias the moving-stat vars, matching the
    reference's in-place contract ``batch_norm_op.cc``). Test: moving stats.
    """
    x = get(env, op.input("X"))
    scale = get(env, op.input("Scale"))
    bias = get(env, op.input("Bias"))
    mean = get(env, op.input("Mean"))
    var = get(env, op.input("Variance"))
    eps = op.attr("epsilon", 1e-5)
    momentum = op.attr("momentum", 0.9)
    is_test = op.attr("is_test", False)
    layout = op.attr("data_layout", "NCHW")
    axes = tuple(i for i in range(x.ndim)
                 if i != (1 if layout == "NCHW" else x.ndim - 1))
    c_shape = [1] * x.ndim
    c_shape[1 if layout == "NCHW" else x.ndim - 1] = -1

    # stats + normalization in fp32 even for bf16 inputs (AMP): the casts
    # fuse into the reduction/epilogue reads. The normalized output is
    # stored back in the input dtype — keeping activations bf16 between
    # conv layers halves HBM traffic, and the next conv recasts anyway.
    in_dtype = x.dtype
    x = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x

    if is_test or op.attr("use_global_stats", False):
        use_mean, use_var = mean, var
        put(env, op.output("MeanOut"), mean)
        put(env, op.output("VarianceOut"), var)
    else:
        # one-pass stats: sum and sumsq fuse into a single read of the
        # conv output (jnp.var's two-pass formulation re-reads the whole
        # activation — measured +7.6% on resnet50). The E[x^2]-E[x]^2
        # cancellation caveat for channels with |mean| >> std matches the
        # reference stack's numerics: cuDNN's CUDNN_BATCHNORM_SPATIAL
        # (what `batch_norm_op.cu` calls) computes the same single-pass
        # f32 moments with the same documented precision bound. Centered
        # or subsampled-shift variants were measured and force a second
        # (partial) read: 0.3346 plain / 0.2774 shifted vs_baseline.
        n = 1
        for i in axes:
            n *= x.shape[i]
        s1 = jnp.sum(x, axis=axes)
        s2 = jnp.sum(x * x, axis=axes)
        use_mean = s1 / n
        use_var = jnp.maximum(s2 / n - use_mean * use_mean, 0.0)
        # moving-stat update must not backprop into params
        bm = jax.lax.stop_gradient(use_mean)
        bv = jax.lax.stop_gradient(use_var)
        put(env, op.output("MeanOut"), momentum * mean + (1 - momentum) * bm)
        put(env, op.output("VarianceOut"), momentum * var + (1 - momentum) * bv)
        put(env, op.output("SavedMean"), bm)
        put(env, op.output("SavedVariance"), bv)

    inv = jax.lax.rsqrt(use_var.reshape(c_shape) + eps)
    y = (x - use_mean.reshape(c_shape)) * inv * scale.reshape(c_shape) \
        + bias.reshape(c_shape)
    put(env, op.output("Y"), y.astype(in_dtype))


@register("layer_norm")
def _layer_norm(env, op):
    x = get(env, op.input("X"))
    scale = get(env, op.input("Scale"))
    bias = get(env, op.input("Bias"))
    eps = op.attr("epsilon", 1e-5)
    begin = op.attr("begin_norm_axis", 1)
    if begin == x.ndim - 1:
        # last-axis normalization: fused Pallas fwd+bwd (one HBM pass per
        # direction instead of XLA's ~5 — ops/fused_layer_norm.py)
        from ...ops.fused_layer_norm import fused_layer_norm, _use_fused

        if _use_fused(x.shape[-1]):
            y, mean, var = fused_layer_norm(x, scale, bias, eps)
            put(env, op.output("Y"), y)
            put(env, op.output("Mean"), mean)
            put(env, op.output("Variance"), var)
            return
    axes = tuple(range(begin, x.ndim))
    # stats in fp32 even for bf16-resident activations (AMP); Y stored in
    # the input dtype so the residual stream stays bf16 (cf. batch_norm)
    in_dtype = x.dtype
    x = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    norm = (x - mean) * jax.lax.rsqrt(var + eps)
    bshape = [1] * begin + list(x.shape[begin:])
    if scale is not None:
        norm = norm * scale.reshape(bshape)
    if bias is not None:
        norm = norm + bias.reshape(bshape)
    put(env, op.output("Y"), norm.astype(in_dtype))
    put(env, op.output("Mean"), mean.reshape(mean.shape[:begin]))
    put(env, op.output("Variance"), var.reshape(var.shape[:begin]))


@register("group_norm")
def _group_norm(env, op):
    x = get(env, op.input("X"))  # NCHW
    scale = get(env, op.input("Scale"))
    bias = get(env, op.input("Bias"))
    g = op.attr("groups")
    eps = op.attr("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    cshape = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(cshape)
    if bias is not None:
        y = y + bias.reshape(cshape)
    put(env, op.output("Y"), y)


@register("lrn")
def _lrn(env, op):
    x = get(env, op.input("X"))  # NCHW
    n_size = op.attr("n", 5)
    alpha = op.attr("alpha", 1e-4)
    beta = op.attr("beta", 0.75)
    k = op.attr("k", 1.0)
    sq = jnp.square(x)
    half = n_size // 2
    pad = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n_size))
    put(env, op.output("Out"), x / jnp.power(k + alpha * acc, beta))


# ---------------- dropout / softmax ----------------

@register("dropout")
def _dropout(env, op):
    x = get(env, op.input("X"))
    p = op.attr("dropout_prob", 0.5)
    is_test = op.attr("is_test", False)
    impl = op.attr("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = x * (1.0 - p) if impl == "downgrade_in_infer" else x
        put(env, op.output("Out"), out)
        return
    # (A 16-bit threshold variant halving the RNG-bit volume was measured
    # net-negative on transformer-base and only +1.5% on BERT — XLA's
    # fused rbg + compare + select is already near its roofline here.)
    keep = jax.random.bernoulli(next_rng(env), 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    if impl == "upscale_in_train":
        out = jnp.where(p >= 1.0, jnp.zeros_like(x), x * mask / (1.0 - p))
    else:
        out = x * mask
    put(env, op.output("Out"), out)
    put(env, op.output("Mask"), mask)


@register("softmax")
def _softmax(env, op):
    x = get(env, op.input("X"))
    out = jax.nn.softmax(x.astype(jnp.float32), axis=op.attr("axis", -1))
    put(env, op.output("Out"), out.astype(x.dtype))


@register("log_softmax")
def _log_softmax(env, op):
    x = get(env, op.input("X"))
    out = jax.nn.log_softmax(x.astype(jnp.float32),
                             axis=op.attr("axis", -1))
    put(env, op.output("Out"), out.astype(x.dtype))


# ---------------- losses ----------------

@register("cross_entropy")
def _cross_entropy(env, op):
    """Ref ``cross_entropy_op.cc``: X is a probability distribution.
    Hard label -> -log(p[label]); soft label -> -sum(label*log(p))."""
    x = get(env, op.input("X"))
    label = get(env, op.input("Label"))
    eps = 1e-8
    if op.attr("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        ids = label.astype(jnp.int32)
        if ids.ndim == x.ndim:
            ids = ids.squeeze(-1)
        p = jnp.take_along_axis(x, ids[..., None], axis=-1)
        loss = -jnp.log(p + eps)
        ignore = op.attr("ignore_index", -100)
        loss = jnp.where(ids[..., None] == ignore, 0.0, loss)
    put(env, op.output("Y"), loss)


@register("softmax_with_cross_entropy")
def _softmax_with_cross_entropy(env, op):
    logits = get(env, op.input("Logits"))
    label = get(env, op.input("Label"))
    # fp32 softmax stats for bf16-resident logits (AMP)
    log_p = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    if op.attr("soft_label", False):
        loss = -jnp.sum(label * log_p, axis=-1, keepdims=True)
    else:
        ids = label.astype(jnp.int32)
        if ids.ndim == logits.ndim:
            ids = ids.squeeze(-1)
        loss = -jnp.take_along_axis(log_p, ids[..., None], axis=-1)
        ignore = op.attr("ignore_index", -100)
        loss = jnp.where(ids[..., None] == ignore, 0.0, loss)
    put(env, op.output("Loss"), loss)
    put(env, op.output("Softmax"), jnp.exp(log_p))


@register("smooth_softmax_ce")
def _smooth_softmax_ce(env, op):
    """Label-smoothed softmax CE in closed form:

        loss = lse(logits) - (1-eps)*logits[y] - (eps/V)*sum(logits)

    ≡ (1-eps)*CE(y) + eps*uniform-CE, but reads the [.., V] logits once and
    writes only [..] per-token outputs — no [.., V] log-prob or soft-label
    materialization (the reference pairs ``label_smooth_op.cc`` with
    ``softmax_with_cross_entropy_op.cc``, building a full soft-label tensor).
    eps=0 degrades to plain softmax CE. The backward (via autodiff) is
    softmax(logits) - (1-eps)*onehot - eps/V: one more single pass."""
    logits = get(env, op.input("Logits"))
    ids = get(env, op.input("Label")).astype(jnp.int32)
    if ids.ndim == logits.ndim:
        ids = ids.squeeze(-1)
    eps = op.attr("epsilon", 0.0)
    # fp32 softmax stats regardless of (bf16) logits dtype; the convert
    # fuses into the reduction's read pass
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1)
    logit_y = jnp.take_along_axis(lf, ids[..., None], axis=-1)[..., 0]
    loss = lse - (1.0 - eps) * logit_y
    if eps:
        loss = loss - eps * jnp.mean(lf, axis=-1)
    put(env, op.output("Loss"), loss)


@register("fused_linear_smooth_ce")
def _fused_linear_smooth_ce(env, op):
    """Vocab projection + label-smoothed softmax CE in one kernel: the
    [.., V] logits never reach HBM (Pallas online-softmax forward, chunked
    recompute backward — ``ops/fused_ce.py``). Replaces the reference's
    projection + ``softmax_with_cross_entropy_op.cc`` pairing for the big-
    vocab loss heads."""
    from ...ops.fused_ce import linear_smooth_ce
    from ..op_registry import mxu_cast

    x = get(env, op.input("X"))
    w = get(env, op.input("W"))
    b = get(env, op.input("Bias"))
    ids = get(env, op.input("Label")).astype(jnp.int32)
    if ids.ndim == x.ndim:
        ids = ids.squeeze(-1)
    x, w, b = mxu_cast(x, w, b)
    put(env, op.output("Loss"), linear_smooth_ce(
        x, w, b, ids, op.attr("epsilon", 0.0)))


@register("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(env, op):
    x = get(env, op.input("X"))
    label = get(env, op.input("Label"))
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = op.attr("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    if op.attr("normalize", False):
        denom = jnp.maximum(jnp.sum((label != ignore).astype(x.dtype)), 1.0)
        loss = loss / denom
    put(env, op.output("Out"), loss)


@register("square_error_cost")
def _square_error_cost(env, op):
    x = get(env, op.input("X"))
    y = get(env, op.input("Y"))
    put(env, op.output("Out"), jnp.square(x - y))


@register("smooth_l1_loss")
def _smooth_l1(env, op):
    x = get(env, op.input("X"))
    y = get(env, op.input("Y"))
    sigma = op.attr("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    iw = get(env, op.input("InsideWeight"))
    ow = get(env, op.input("OutsideWeight"))
    if iw is not None:
        diff = diff * iw
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if ow is not None:
        loss = loss * ow
    put(env, op.output("Diff"), diff)
    put(env, op.output("Out"), jnp.sum(loss, axis=tuple(range(1, x.ndim)), keepdims=False).reshape(x.shape[0], 1))


@register("huber_loss")
def _huber_loss(env, op):
    x = get(env, op.input("X"))
    y = get(env, op.input("Y"))
    delta = op.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    put(env, op.output("Residual"), r)
    put(env, op.output("Out"), loss)


@register("label_smooth")
def _label_smooth(env, op):
    x = get(env, op.input("X"))
    eps = op.attr("epsilon", 0.0)
    dist = get(env, op.input("PriorDist"))
    k = x.shape[-1]
    if dist is not None:
        out = (1 - eps) * x + eps * dist
    else:
        out = (1 - eps) * x + eps / k
    put(env, op.output("Out"), out)


@register("kldiv_loss")
def _kldiv_loss(env, op):
    x = get(env, op.input("X"))  # log-probabilities
    target = get(env, op.input("Target"))
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - x)
    red = op.attr("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss)
    elif red == "sum":
        loss = jnp.sum(loss)
    elif red == "batchmean":
        loss = jnp.sum(loss) / x.shape[0]
    put(env, op.output("Loss"), loss)


@register("bpr_loss")
def _bpr_loss(env, op):
    x = get(env, op.input("X"))
    label = get(env, op.input("Label")).astype(jnp.int32)
    if label.ndim == x.ndim:
        label = label.squeeze(-1)
    pos = jnp.take_along_axis(x, label[..., None], axis=-1)
    diff = pos - x
    loss = -jnp.log(jax.nn.sigmoid(diff) + 1e-8)
    # exclude the positive column itself
    mask = 1.0 - jax.nn.one_hot(label, x.shape[-1], dtype=x.dtype)
    loss = jnp.sum(loss * mask, axis=-1, keepdims=True) / jnp.maximum(x.shape[-1] - 1, 1)
    put(env, op.output("Y"), loss)


@register("hinge_loss")
def _hinge_loss(env, op):
    logits = get(env, op.input("Logits"))
    labels = get(env, op.input("Labels"))
    put(env, op.output("Loss"),
        jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0))


@register("log_loss")
def _log_loss(env, op):
    p = get(env, op.input("Predicted"))
    label = get(env, op.input("Labels"))
    eps = op.attr("epsilon", 1e-4)
    put(env, op.output("Loss"),
        -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps))


@register("margin_rank_loss")
def _margin_rank_loss(env, op):
    x1 = get(env, op.input("X1"))
    x2 = get(env, op.input("X2"))
    label = get(env, op.input("Label"))
    margin = op.attr("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    put(env, op.output("Out"), out)
    put(env, op.output("Activated"), (out > 0).astype(x1.dtype))


@register("mse_loss")
def _mse_loss(env, op):
    x = get(env, op.input("X"))
    y = get(env, op.input("Y"))
    put(env, op.output("Out"), jnp.mean(jnp.square(x - y)))


# ---------------- interpolation / resize ----------------

@register("bilinear_interp", "nearest_interp")
def _interp(env, op):
    x = get(env, op.input("X"))  # NCHW
    out_h = op.attr("out_h")
    out_w = op.attr("out_w")
    scale = op.attr("scale", 0.0)
    if scale and (not out_h or out_h <= 0):
        out_h = int(x.shape[2] * scale)
        out_w = int(x.shape[3] * scale)
    method = "bilinear" if op.type == "bilinear_interp" else "nearest"
    align = op.attr("align_corners", True)
    if method == "bilinear" and align and out_h > 1 and out_w > 1:
        # align_corners bilinear: explicit gather-based implementation
        h_in, w_in = x.shape[2], x.shape[3]
        ys = jnp.linspace(0.0, h_in - 1.0, out_h)
        xs = jnp.linspace(0.0, w_in - 1.0, out_w)
        y0 = jnp.floor(ys).astype(jnp.int32)
        x0 = jnp.floor(xs).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, h_in - 1)
        x1 = jnp.minimum(x0 + 1, w_in - 1)
        wy = (ys - y0)[None, None, :, None]
        wx = (xs - x0)[None, None, None, :]
        g = lambda yy, xx: x[:, :, yy, :][:, :, :, xx]
        out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1, x0) * wy * (1 - wx)
               + g(y0, x1) * (1 - wy) * wx + g(y1, x1) * wy * wx)
    else:
        out = jax.image.resize(x, x.shape[:2] + (out_h, out_w), method=method)
    put(env, op.output("Out"), out.astype(x.dtype))


# ---------------- misc nn ----------------

@register("im2sequence")
def _im2sequence(env, op):
    x = get(env, op.input("X"))  # NCHW
    kernels = op.attr("kernels")
    strides = op.attr("strides", [1, 1])
    paddings = op.attr("paddings", [0, 0, 0, 0])
    n, c, h, w = x.shape
    xp = jnp.pad(x, [(0, 0), (0, 0),
                     (paddings[0], paddings[2]), (paddings[1], paddings[3])])
    kh, kw = kernels
    oh = (xp.shape[2] - kh) // strides[0] + 1
    ow = (xp.shape[3] - kw) // strides[1] + 1
    patches = jax.lax.conv_general_dilated_patches(
        xp, (kh, kw), tuple(strides), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, oh, ow] -> [N*oh*ow, C*kh*kw]
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    put(env, op.output("Out"), out)


@register("grid_sampler")
def _grid_sampler(env, op):
    x = get(env, op.input("X"))  # NCHW
    grid = get(env, op.input("Grid"))  # NHW2 in [-1, 1]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1) * (w - 1) / 2
    gy = (grid[..., 1] + 1) * (h - 1) / 2
    x0 = jnp.clip(jnp.floor(gx).astype(jnp.int32), 0, w - 1)
    y0 = jnp.clip(jnp.floor(gy).astype(jnp.int32), 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    wx = gx - x0
    wy = gy - y0

    def gat(yy, xx):
        bidx = jnp.arange(n)[:, None, None]
        return x[bidx, :, yy, xx]  # [N, Ho, Wo, C]

    out = (gat(y0, x0) * ((1 - wy) * (1 - wx))[..., None]
           + gat(y0, x1) * ((1 - wy) * wx)[..., None]
           + gat(y1, x0) * (wy * (1 - wx))[..., None]
           + gat(y1, x1) * (wy * wx)[..., None])
    put(env, op.output("Output"), out.transpose(0, 3, 1, 2))


@register("pixel_shuffle")
def _pixel_shuffle(env, op):
    x = get(env, op.input("X"))
    r = op.attr("upscale_factor")
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3).reshape(n, c // (r * r), h * r, w * r)
    put(env, op.output("Out"), out)


@register("moe_ffn")
def _moe_ffn(env, op):
    """Mixture-of-experts FFN (see ``parallel/moe.py``; new capability vs
    the reference — SURVEY.md §2.5D lists expert parallelism as absent)."""
    from ...parallel.moe import moe_ffn_apply

    x = get(env, op.input("X"))
    gate_w = get(env, op.input("GateW"))
    w1 = get(env, op.input("W1"))
    b1 = get(env, op.input("B1"))
    w2 = get(env, op.input("W2"))
    b2 = get(env, op.input("B2"))
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[op.attr("act", "relu")]
    out, aux = moe_ffn_apply(
        x, gate_w, w1, b1, w2, b2, k=op.attr("k", 2),
        capacity_factor=op.attr("capacity_factor", 1.25), activation=act)
    put(env, op.output("Out"), out)
    put(env, op.output("AuxLoss"), aux)

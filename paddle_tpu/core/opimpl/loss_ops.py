"""CTC / CRF / NCE / hierarchical-sigmoid losses.

Reference kernels: ``operators/warpctc_op.cc`` (wraps the warp-ctc lib),
``operators/linear_chain_crf_op.cc`` + ``crf_decoding_op.cc``,
``operators/nce_op.cc``, ``operators/hierarchical_sigmoid_op.cc`` (+
``math/matrix_bit_code.h`` SimpleCode tree). TPU-native: the dynamic-
programming recursions (CTC alpha, CRF forward, Viterbi) are ``lax.scan``
over time in log space on padded [B, T, ...] batches with length masks —
no LoD, no external warp-ctc; gradients come from jax autodiff through the
scan instead of hand-written backward kernels.
"""

import jax
import jax.numpy as jnp

from ..op_registry import register, get, put, next_rng

_NEG = -1e30


def _lengths(env, op, slot, batch, default):
    v = op.input(slot)
    if v is None:
        return jnp.full((batch,), default, jnp.int32)
    return env[v.name].reshape(batch).astype(jnp.int32)


@register("warpctc")
def _warpctc(env, op):
    """CTC loss on padded [B, T, C] logits (softmax applied internally,
    matching warp-ctc). Alpha recursion over the blank-extended label
    sequence [blank, y1, blank, ..., yL, blank] in log space."""
    logits = get(env, op.input("Logits"))
    label = get(env, op.input("Label")).astype(jnp.int32)
    b, t_max, _ = logits.shape
    l_max = label.shape[1]
    in_len = _lengths(env, op, "LogitsLength", b, t_max)
    lb_len = _lengths(env, op, "LabelLength", b, l_max)
    blank = op.attr("blank", 0)

    logp = jax.nn.log_softmax(logits, axis=-1)
    s = 2 * l_max + 1
    ext = jnp.full((b, s), blank, jnp.int32).at[:, 1::2].set(label)
    # position s may receive from s-2 when it is a non-blank that differs
    # from the non-blank two slots back (standard CTC transition rule)
    allow = ((jnp.arange(s) >= 2) & (ext != blank)
             & (ext != jnp.roll(ext, 2, axis=1)))

    lp0 = jnp.take_along_axis(logp[:, 0, :], ext, axis=1)
    alpha0 = jnp.full((b, s), _NEG)
    alpha0 = alpha0.at[:, 0].set(lp0[:, 0])
    alpha0 = alpha0.at[:, 1].set(lp0[:, 1])

    def step(alpha, t):
        lp = jnp.take_along_axis(logp[:, t, :], ext, axis=1)
        s1 = jnp.concatenate(
            [jnp.full((b, 1), _NEG), alpha[:, :-1]], axis=1)
        s2 = jnp.concatenate(
            [jnp.full((b, 2), _NEG), alpha[:, :-2]], axis=1)
        s2 = jnp.where(allow, s2, _NEG)
        stacked = jnp.stack([alpha, s1, s2])
        new = jax.scipy.special.logsumexp(stacked, axis=0) + lp
        new = jnp.where((t < in_len)[:, None], new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t_max))
    end_b = jnp.take_along_axis(alpha, (2 * lb_len)[:, None], 1)[:, 0]
    end_l = jnp.take_along_axis(
        alpha, jnp.maximum(2 * lb_len - 1, 0)[:, None], 1)[:, 0]
    # empty label: the only path is all-blank, ending at position 0 — the
    # clamped second readout would double-count it
    end_l = jnp.where(lb_len > 0, end_l, _NEG)
    nll = -jnp.logaddexp(end_b, end_l)
    if op.attr("norm_by_times", False):
        # reference warpctc_op scales only the GRADIENT by 1/T; the Loss
        # output stays un-normalized
        scaled = nll / in_len.astype(nll.dtype)
        nll = scaled + jax.lax.stop_gradient(nll - scaled)
    put(env, op.output("Loss"), nll[:, None])


def _crf_unpack(transition):
    """Fluid transition layout (``linear_chain_crf_op.h``): row 0 = start
    weights, row 1 = end weights, rows 2.. = square transition matrix."""
    return transition[0], transition[1], transition[2:]


@register("linear_chain_crf")
def _linear_chain_crf(env, op):
    emission = get(env, op.input("Emission"))   # [B, T, D]
    transition = get(env, op.input("Transition"))
    label = get(env, op.input("Label")).astype(jnp.int32)  # [B, T]
    b, t_max, _ = emission.shape
    length = _lengths(env, op, "Length", b, t_max)
    start, end, w = _crf_unpack(transition)
    mask = jnp.arange(t_max)[None, :] < length[:, None]

    # gold path score
    e_gold = jnp.take_along_axis(emission, label[..., None], 2)[..., 0]
    e_sum = jnp.sum(e_gold * mask, axis=1)
    trans_pairs = w[label[:, :-1], label[:, 1:]]       # [B, T-1]
    t_sum = jnp.sum(trans_pairs * mask[:, 1:], axis=1)
    last_lbl = jnp.take_along_axis(
        label, jnp.maximum(length - 1, 0)[:, None], 1)[:, 0]
    gold = start[label[:, 0]] + e_sum + t_sum + end[last_lbl]

    # partition function (forward algorithm)
    alpha0 = start[None, :] + emission[:, 0, :]

    def step(alpha, t):
        new = jax.scipy.special.logsumexp(
            alpha[:, :, None] + w[None], axis=1) + emission[:, t]
        new = jnp.where((t < length)[:, None], new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t_max))
    log_z = jax.scipy.special.logsumexp(alpha + end[None], axis=1)
    put(env, op.output("LogLikelihood"), (log_z - gold)[:, None])


@register("crf_decoding")
def _crf_decoding(env, op):
    """Viterbi decode (ref ``crf_decoding_op.h``); with a Label input the
    output is the per-position correctness mask (reference semantics)."""
    emission = get(env, op.input("Emission"))
    transition = get(env, op.input("Transition"))
    b, t_max, d = emission.shape
    length = _lengths(env, op, "Length", b, t_max)
    start, end, w = _crf_unpack(transition)

    v0 = start[None, :] + emission[:, 0, :]

    def fwd(v, t):
        scores = v[:, :, None] + w[None]              # [B, D, D]
        best_prev = jnp.argmax(scores, axis=1)        # [B, D]
        new = jnp.max(scores, axis=1) + emission[:, t]
        live = (t < length)[:, None]
        new = jnp.where(live, new, v)
        best_prev = jnp.where(
            live, best_prev, jnp.arange(d)[None, :])  # identity pass-through
        return new, best_prev

    v_t, back = jax.lax.scan(fwd, v0, jnp.arange(1, t_max))
    last = jnp.argmax(v_t + end[None], axis=1)        # [B]

    def bwd(idx, bp):
        prev = jnp.take_along_axis(bp, idx[:, None], 1)[:, 0]
        return prev, idx

    s0, path_rev = jax.lax.scan(bwd, last, back[::-1])
    # path_rev emits states T-1..1; the final carry is the t=0 state
    path = jnp.concatenate(
        [s0[None], jnp.flip(path_rev, axis=0)], axis=0).T  # [B, T]
    live = jnp.arange(t_max)[None] < length[:, None]
    path = jnp.where(live, path, 0)
    lbl_var = op.input("Label")
    # int32, not int64: jax truncates int64 (with a loud UserWarning)
    # unless x64 mode is on — request the effective dtype explicitly
    if lbl_var is not None:
        lbl = env[lbl_var.name].astype(path.dtype)
        out = ((path == lbl) & live).astype(jnp.int32)
    else:
        out = path.astype(jnp.int32)
    put(env, op.output("ViterbiPath"), out)


def _log_q(sampler, ids, vocab):
    if sampler == "log_uniform":
        idf = ids.astype(jnp.float32)
        return jnp.log(
            (jnp.log(idf + 2.0) - jnp.log(idf + 1.0))
            / jnp.log(vocab + 1.0))
    return jnp.full(ids.shape, -jnp.log(float(vocab)))


@register("nce")
def _nce(env, op):
    """Noise-contrastive estimation (ref ``nce_op.h``): logistic loss on the
    true class vs ``num_neg_samples`` sampled noise classes, scores shifted
    by log(k·q(class))."""
    x = get(env, op.input("Input"))                 # [B, D]
    label = get(env, op.input("Label")).reshape(x.shape[0]).astype(jnp.int32)
    w = get(env, op.input("Weight"))                # [V, D]
    bias = get(env, op.input("Bias"))               # [V] or None
    k = op.attr("num_neg_samples")
    sampler = op.attr("sampler", "uniform")
    vocab = w.shape[0]
    seed = op.attr("seed", 0)
    key = jax.random.PRNGKey(seed) if seed else next_rng(env)
    if sampler == "log_uniform":
        u = jax.random.uniform(key, (x.shape[0], k))
        neg = (jnp.exp(u * jnp.log(vocab + 1.0)) - 1.0).astype(jnp.int32)
        neg = jnp.clip(neg, 0, vocab - 1)
    else:
        neg = jax.random.randint(key, (x.shape[0], k), 0, vocab)

    def score(ids):
        s = jnp.sum(jnp.take(w, ids, axis=0) * x[:, None, :], axis=-1)
        if bias is not None:
            s = s + jnp.take(bias.reshape(-1), ids)
        return s

    log_kq_pos = jnp.log(float(k)) + _log_q(sampler, label[:, None], vocab)
    log_kq_neg = jnp.log(float(k)) + _log_q(sampler, neg, vocab)
    s_true = score(label[:, None]) - log_kq_pos     # [B, 1]
    s_neg = score(neg) - log_kq_neg                 # [B, k]
    cost = (jax.nn.softplus(-s_true)[:, 0]
            + jnp.sum(jax.nn.softplus(s_neg), axis=1))
    put(env, op.output("Cost"), cost[:, None])


@register("hsigmoid")
def _hsigmoid(env, op):
    """Hierarchical sigmoid over a class tree (ref
    ``hierarchical_sigmoid_op.h`` + ``math/matrix_bit_code.h``): per-class
    (node indices, bit codes) arrive as static attrs (default complete
    binary tree) or as PathTable/PathCode inputs (custom tree)."""
    x = get(env, op.input("Input"))                 # [B, D]
    label = get(env, op.input("Label")).reshape(x.shape[0]).astype(jnp.int32)
    w = get(env, op.input("W"))                     # [nodes, D]
    bias = get(env, op.input("Bias"))
    pt_var = op.input("PathTable")
    if pt_var is not None:
        idx = env[pt_var.name].astype(jnp.int32)
        bits = env[op.input("PathCode").name].astype(jnp.float32)
    else:
        table = jnp.asarray(op.attr("path_table"), jnp.int32)   # [C, Lmax]
        codes = jnp.asarray(op.attr("path_code"), jnp.float32)
        idx = jnp.take(table, label, axis=0)        # [B, Lmax]
        bits = jnp.take(codes, label, axis=0)
    valid = idx >= 0
    safe = jnp.maximum(idx, 0)
    pre = jnp.sum(jnp.take(w, safe, axis=0) * x[:, None, :], axis=-1)
    if bias is not None:
        pre = pre + jnp.take(bias.reshape(-1), safe)
    # sigmoid cross entropy with target = bit
    bit_loss = jax.nn.softplus(pre) - bits * pre
    cost = jnp.sum(bit_loss * valid, axis=1)
    put(env, op.output("Cost"), cost[:, None])

"""Optimizer update ops.

Reference: ``paddle/fluid/operators/optimizers/`` (sgd, momentum +
lars_momentum, adam, adamax, adagrad, decayed_adagrad, adadelta, rmsprop,
ftrl) — dense paths. Each op's "Out" slots alias the state var names, so the
executor's state write-back gives in-place semantics; with buffer donation
XLA updates parameters in place on device (the TPU equivalent of the
reference's in-place ParamOut contract).

Sparse (SelectedRows) gradient paths: gradients of ``lookup_table`` arrive
dense from jax.grad but XLA lowers the gather-vjp to scatter-add; for truly
sparse updates see ``paddle_tpu.parallel.sharded_embedding``.
"""

import jax.numpy as jnp
import jax

from ..op_registry import register, get, put


def _lr(env, op):
    lr = get(env, op.input("LearningRate"))
    return lr.reshape(()) if lr.ndim else lr


@register("sgd")
def _sgd(env, op):
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    put(env, op.output("ParamOut"), p - _lr(env, op) * g)


@register("momentum")
def _momentum(env, op):
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    v = get(env, op.input("Velocity"))
    mu = op.attr("mu")
    lr = _lr(env, op)
    v_new = mu * v + g
    if op.attr("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    put(env, op.output("ParamOut"), p_new)
    put(env, op.output("VelocityOut"), v_new)


@register("lars_momentum")
def _lars_momentum(env, op):
    """LARS (ref ``lars_momentum_op.cc``): layer-wise adaptive LR."""
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    v = get(env, op.input("Velocity"))
    mu = op.attr("mu")
    lars_coeff = op.attr("lars_coeff", 0.001)
    lars_wd = op.attr("lars_weight_decay", 0.0005)
    lr = _lr(env, op)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * lars_coeff * p_norm / (g_norm + lars_wd * p_norm + 1e-12),
        lr)
    v_new = mu * v + local_lr * (g + lars_wd * p)
    put(env, op.output("ParamOut"), p - v_new)
    put(env, op.output("VelocityOut"), v_new)


@register("adam")
def _adam(env, op):
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    m = get(env, op.input("Moment1"))
    v = get(env, op.input("Moment2"))
    b1p = get(env, op.input("Beta1Pow")).reshape(())
    b2p = get(env, op.input("Beta2Pow")).reshape(())
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    lr = _lr(env, op)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * jnp.square(g)
    # ref adam_op.h: lr_t = lr * sqrt(1-beta2^t) / (1-beta1^t); the pow
    # accumulators arrive already holding beta^t for the current step t
    # (initialized to beta at t=1), so use them directly.
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    put(env, op.output("ParamOut"), p_new)
    put(env, op.output("Moment1Out"), m_new)
    put(env, op.output("Moment2Out"), v_new)
    put(env, op.output("Beta1PowOut"), (b1p * b1).reshape((1,)))
    put(env, op.output("Beta2PowOut"), (b2p * b2).reshape((1,)))


@register("adamax")
def _adamax(env, op):
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    m = get(env, op.input("Moment"))
    inf_norm = get(env, op.input("InfNorm"))
    b1p = get(env, op.input("Beta1Pow")).reshape(())
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    lr = _lr(env, op)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf_norm, jnp.abs(g) + eps)
    lr_t = lr / (1 - b1p)
    put(env, op.output("ParamOut"), p - lr_t * m_new / inf_new)
    put(env, op.output("MomentOut"), m_new)
    put(env, op.output("InfNormOut"), inf_new)


@register("adagrad")
def _adagrad(env, op):
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    mom = get(env, op.input("Moment"))
    eps = op.attr("epsilon", 1e-6)
    lr = _lr(env, op)
    mom_new = mom + jnp.square(g)
    put(env, op.output("ParamOut"), p - lr * g / (jnp.sqrt(mom_new) + eps))
    put(env, op.output("MomentOut"), mom_new)


@register("decayed_adagrad")
def _decayed_adagrad(env, op):
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    mom = get(env, op.input("Moment"))
    decay = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    lr = _lr(env, op)
    mom_new = decay * mom + (1 - decay) * jnp.square(g)
    put(env, op.output("ParamOut"), p - lr * g / (jnp.sqrt(mom_new) + eps))
    put(env, op.output("MomentOut"), mom_new)


@register("adadelta")
def _adadelta(env, op):
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    avg_sq_g = get(env, op.input("AvgSquaredGrad"))
    avg_sq_u = get(env, op.input("AvgSquaredUpdate"))
    rho = op.attr("rho", 0.95)
    eps = op.attr("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    upd = jnp.sqrt(avg_sq_u + eps) / jnp.sqrt(g2 + eps) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(upd)
    put(env, op.output("ParamOut"), p - upd)
    put(env, op.output("AvgSquaredGradOut"), g2)
    put(env, op.output("AvgSquaredUpdateOut"), u2)


@register("rmsprop")
def _rmsprop(env, op):
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    ms = get(env, op.input("MeanSquare"))
    mg = get(env, op.input("MeanGrad"))
    mom = get(env, op.input("Moment"))
    rho = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    momentum = op.attr("momentum", 0.0)
    centered = op.attr("centered", False)
    lr = _lr(env, op)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if centered:
        mg_new = rho * mg + (1 - rho) * g
        denom = jnp.sqrt(ms_new - jnp.square(mg_new) + eps)
        put(env, op.output("MeanGradOut"), mg_new)
    else:
        denom = jnp.sqrt(ms_new + eps)
    mom_new = momentum * mom + lr * g / denom
    put(env, op.output("ParamOut"), p - mom_new)
    put(env, op.output("MeanSquareOut"), ms_new)
    put(env, op.output("MomentOut"), mom_new)


@register("ftrl")
def _ftrl(env, op):
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    sq = get(env, op.input("SquaredAccumulator"))
    lin = get(env, op.input("LinearAccumulator"))
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    lr_power = op.attr("lr_power", -0.5)
    lr = _lr(env, op)
    new_sq = sq + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre_shrink = (l1 * jnp.sign(new_lin) - new_lin) / denom
    p_new = jnp.where(jnp.abs(new_lin) > l1, pre_shrink, jnp.zeros_like(p))
    put(env, op.output("ParamOut"), p_new)
    put(env, op.output("SquaredAccumOut"), new_sq)
    put(env, op.output("LinearAccumOut"), new_lin)


@register("lamb")
def _lamb(env, op):
    """LAMB optimizer — beyond the reference's 2019 set; standard for BERT
    pretraining at scale on TPU pods."""
    p = get(env, op.input("Param"))
    g = get(env, op.input("Grad"))
    m = get(env, op.input("Moment1"))
    v = get(env, op.input("Moment2"))
    b1p = get(env, op.input("Beta1Pow")).reshape(())
    b2p = get(env, op.input("Beta2Pow")).reshape(())
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-6)
    wd = op.attr("weight_decay", 0.01)
    lr = _lr(env, op)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * jnp.square(g)
    m_hat = m_new / (1 - b1p)
    v_hat = v_new / (1 - b2p)
    r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    put(env, op.output("ParamOut"), p - lr * trust * r)
    put(env, op.output("Moment1Out"), m_new)
    put(env, op.output("Moment2Out"), v_new)
    put(env, op.output("Beta1PowOut"), (b1p * b1).reshape((1,)))
    put(env, op.output("Beta2PowOut"), (b2p * b2).reshape((1,)))

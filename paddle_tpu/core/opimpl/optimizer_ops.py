"""Optimizer update ops.

Reference: ``paddle/fluid/operators/optimizers/`` (sgd, momentum +
lars_momentum, adam, adamax, adagrad, decayed_adagrad, adadelta, rmsprop,
ftrl) — dense paths. Each op's "Out" slots alias the state var names, so the
executor's state write-back gives in-place semantics; with buffer donation
XLA updates parameters in place on device (the TPU equivalent of the
reference's in-place ParamOut contract).

Sparse (SelectedRows) gradient paths: when the op carries a ``GradRows``
input (wired by ``Optimizer._create_optimization_pass`` for params whose
grad is sparse), ``Grad`` holds per-row values and the update is a
scatter touching ONLY those rows — parity with the reference's
SelectedRows kernels (``operators/optimizers/adam_op.h`` sparse branch,
``sgd_op.h``), lazy-mode semantics: untouched rows' moments don't decay.
Duplicate rows are merged jit-safely (sort + segment-sum at static length,
duplicates parked on an out-of-range sentinel row dropped by the scatter).
"""

import jax.numpy as jnp

from ..op_registry import register, get, put, merge_sparse_rows


def _lr(env, op):
    lr = get(env, op.input("LearningRate"))
    return lr.reshape(()) if lr.ndim else lr


def _sparse_grad(env, op):
    """Return (grad, rows): rows is None for dense grads."""
    g = get(env, op.input("Grad"))
    rv = op.input("GradRows")
    if rv is None:
        return g, None
    return g, env[rv.name]


_merge_rows = merge_sparse_rows


def _densify(g, rows, shape, op=None):
    """Densify a (rows, values) sparse grad for optimizers whose update
    runs over every row (non-lazy adam — the DeepFM bench path — and the
    optimizers without a dedicated sparse kernel). Routed through the
    Pallas VMEM-resident scatter-add (``ops/scatter.py``) when the table
    qualifies; XLA's ``.at[].add`` otherwise. Exact either way
    (out-of-range sentinel rows drop, duplicates accumulate). The gate's
    structured decision is recorded in ``op``'s attrs
    (``_kernel_choice``) so the chosen kernel — and any refusal's reason
    — is inspectable on the built program."""
    if len(shape) == 2 and g.ndim == 2:
        from ...ops.scatter import record_choice, scatter_add_rows

        record_choice(op, shape[0], shape[1], g.shape[0], g.dtype)
        return scatter_add_rows(jnp.zeros(shape, g.dtype), rows, g)
    return jnp.zeros(shape, g.dtype).at[rows].add(g, mode="drop")


@register("sparse_decay")
def _sparse_decay(env, op):
    """Row-wise weight decay on a sparse (rows, values) grad: values +=
    coeff * param[rows] (l2) or coeff * sign(param[rows]) (l1). Sentinel
    (out-of-range) rows — duplicate/padding slots — stay zero."""
    g = get(env, op.input("Grad"))
    rows = env[op.input("Rows").name]
    p = get(env, op.input("Param"))
    coeff = op.attr("coeff")
    valid = rows < p.shape[0]
    pr = p[jnp.clip(rows, 0, p.shape[0] - 1)]
    if op.attr("mode") == "l1":
        pr = jnp.sign(pr)
    decay = coeff * pr * valid[:, None].astype(g.dtype)
    put(env, op.output("Out"), g + decay)


@register("sgd")
def _sgd(env, op):
    p = get(env, op.input("Param"))
    g, rows = _sparse_grad(env, op)
    if rows is not None:
        # ref sgd_op.h SelectedRows branch: scatter-add handles duplicates
        # (Pallas row-scatter when the table qualifies — ops/scatter.py)
        upd = -_lr(env, op) * g
        if p.ndim == 2 and upd.ndim == 2:
            from ...ops.scatter import record_choice, scatter_add_rows

            record_choice(op, p.shape[0], p.shape[1], upd.shape[0],
                          p.dtype)
            put(env, op.output("ParamOut"), scatter_add_rows(p, rows, upd))
        else:
            put(env, op.output("ParamOut"),
                p.at[rows].add(upd, mode="drop"))
        return
    put(env, op.output("ParamOut"), p - _lr(env, op) * g)


@register("momentum")
def _momentum(env, op):
    p = get(env, op.input("Param"))
    g, rows = _sparse_grad(env, op)
    v = get(env, op.input("Velocity"))
    mu = op.attr("mu")
    lr = _lr(env, op)
    if rows is not None:
        rows_u, g_u = _merge_rows(rows, g, p.shape[0])
        v_rows = mu * v[rows_u] + g_u
        if op.attr("use_nesterov", False):
            upd = (g_u + mu * v_rows) * lr
        else:
            upd = lr * v_rows
        put(env, op.output("ParamOut"),
            p.at[rows_u].add(-upd, mode="drop"))
        put(env, op.output("VelocityOut"),
            v.at[rows_u].set(v_rows, mode="drop"))
        return
    v_new = mu * v + g
    if op.attr("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    put(env, op.output("ParamOut"), p_new)
    put(env, op.output("VelocityOut"), v_new)


@register("lars_momentum")
def _lars_momentum(env, op):
    """LARS (ref ``lars_momentum_op.cc``): layer-wise adaptive LR."""
    p = get(env, op.input("Param"))
    g, _rows = _sparse_grad(env, op)
    if _rows is not None:
        g = _densify(g, _rows, p.shape, op)
    v = get(env, op.input("Velocity"))
    mu = op.attr("mu")
    lars_coeff = op.attr("lars_coeff", 0.001)
    lars_wd = op.attr("lars_weight_decay", 0.0005)
    lr = _lr(env, op)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * lars_coeff * p_norm / (g_norm + lars_wd * p_norm + 1e-12),
        lr)
    v_new = mu * v + local_lr * (g + lars_wd * p)
    put(env, op.output("ParamOut"), p - v_new)
    put(env, op.output("VelocityOut"), v_new)


@register("adam")
def _adam(env, op):
    p = get(env, op.input("Param"))
    g, rows = _sparse_grad(env, op)
    m = get(env, op.input("Moment1"))
    v = get(env, op.input("Moment2"))
    b1p = get(env, op.input("Beta1Pow")).reshape(())
    b2p = get(env, op.input("Beta2Pow")).reshape(())
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    lr = _lr(env, op)
    # ref adam_op.h: lr_t = lr * sqrt(1-beta2^t) / (1-beta1^t); the pow
    # accumulators arrive already holding beta^t for the current step t
    # (initialized to beta at t=1), so use them directly.
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    if rows is not None and not op.attr("lazy_mode", False):
        # ref adam_op.h default (lazy_mode=false): sparse grad is merged
        # and the update runs over EVERY row — identical math to the dense
        # branch on the densified grad. On TPU this is also the fast path:
        # one scatter-add (~15 ns/row) replaces the lazy branch's 3 row
        # gathers + 3 row scatters (measured 45 -> ~12 ms/step on the
        # DeepFM bench, tools/bench_gather.py has the per-op rates).
        g = _densify(g.astype(p.dtype), rows, p.shape, op)
        rows = None
    if rows is not None:
        # ref adam_op.h SparseAdamFunctor (lazy_mode=true): only touched
        # rows' moments advance; pow accumulators still advance every step
        rows_u, g_u = _merge_rows(rows, g, p.shape[0])
        m_rows = b1 * m[rows_u] + (1 - b1) * g_u
        v_rows = b2 * v[rows_u] + (1 - b2) * jnp.square(g_u)
        p_rows = p[rows_u] - lr_t * m_rows / (jnp.sqrt(v_rows) + eps)
        put(env, op.output("ParamOut"),
            p.at[rows_u].set(p_rows, mode="drop"))
        put(env, op.output("Moment1Out"),
            m.at[rows_u].set(m_rows, mode="drop"))
        put(env, op.output("Moment2Out"),
            v.at[rows_u].set(v_rows, mode="drop"))
        put(env, op.output("Beta1PowOut"), (b1p * b1).reshape((1,)))
        put(env, op.output("Beta2PowOut"), (b2p * b2).reshape((1,)))
        return
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * jnp.square(g)
    p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    put(env, op.output("ParamOut"), p_new)
    put(env, op.output("Moment1Out"), m_new)
    put(env, op.output("Moment2Out"), v_new)
    put(env, op.output("Beta1PowOut"), (b1p * b1).reshape((1,)))
    put(env, op.output("Beta2PowOut"), (b2p * b2).reshape((1,)))


@register("adamax")
def _adamax(env, op):
    p = get(env, op.input("Param"))
    g, _rows = _sparse_grad(env, op)
    if _rows is not None:
        g = _densify(g, _rows, p.shape, op)
    m = get(env, op.input("Moment"))
    inf_norm = get(env, op.input("InfNorm"))
    b1p = get(env, op.input("Beta1Pow")).reshape(())
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    lr = _lr(env, op)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf_norm, jnp.abs(g) + eps)
    lr_t = lr / (1 - b1p)
    put(env, op.output("ParamOut"), p - lr_t * m_new / inf_new)
    put(env, op.output("MomentOut"), m_new)
    put(env, op.output("InfNormOut"), inf_new)


@register("adagrad")
def _adagrad(env, op):
    p = get(env, op.input("Param"))
    g, rows = _sparse_grad(env, op)
    mom = get(env, op.input("Moment"))
    eps = op.attr("epsilon", 1e-6)
    lr = _lr(env, op)
    if rows is not None:
        # ref adagrad_op.h SparseAdagradFunctor: merge rows, touched only
        rows_u, g_u = _merge_rows(rows, g, p.shape[0])
        mom_rows = mom[rows_u] + jnp.square(g_u)
        p_rows = p[rows_u] - lr * g_u / (jnp.sqrt(mom_rows) + eps)
        put(env, op.output("ParamOut"),
            p.at[rows_u].set(p_rows, mode="drop"))
        put(env, op.output("MomentOut"),
            mom.at[rows_u].set(mom_rows, mode="drop"))
        return
    mom_new = mom + jnp.square(g)
    put(env, op.output("ParamOut"), p - lr * g / (jnp.sqrt(mom_new) + eps))
    put(env, op.output("MomentOut"), mom_new)


@register("decayed_adagrad")
def _decayed_adagrad(env, op):
    p = get(env, op.input("Param"))
    g, _rows = _sparse_grad(env, op)
    if _rows is not None:
        g = _densify(g, _rows, p.shape, op)
    mom = get(env, op.input("Moment"))
    decay = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    lr = _lr(env, op)
    mom_new = decay * mom + (1 - decay) * jnp.square(g)
    put(env, op.output("ParamOut"), p - lr * g / (jnp.sqrt(mom_new) + eps))
    put(env, op.output("MomentOut"), mom_new)


@register("adadelta")
def _adadelta(env, op):
    p = get(env, op.input("Param"))
    g, _rows = _sparse_grad(env, op)
    if _rows is not None:
        g = _densify(g, _rows, p.shape, op)
    avg_sq_g = get(env, op.input("AvgSquaredGrad"))
    avg_sq_u = get(env, op.input("AvgSquaredUpdate"))
    rho = op.attr("rho", 0.95)
    eps = op.attr("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    upd = jnp.sqrt(avg_sq_u + eps) / jnp.sqrt(g2 + eps) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(upd)
    put(env, op.output("ParamOut"), p - upd)
    put(env, op.output("AvgSquaredGradOut"), g2)
    put(env, op.output("AvgSquaredUpdateOut"), u2)


@register("rmsprop")
def _rmsprop(env, op):
    p = get(env, op.input("Param"))
    g, _rows = _sparse_grad(env, op)
    if _rows is not None:
        g = _densify(g, _rows, p.shape, op)
    ms = get(env, op.input("MeanSquare"))
    mg = get(env, op.input("MeanGrad"))
    mom = get(env, op.input("Moment"))
    rho = op.attr("decay", 0.95)
    eps = op.attr("epsilon", 1e-6)
    momentum = op.attr("momentum", 0.0)
    centered = op.attr("centered", False)
    lr = _lr(env, op)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if centered:
        mg_new = rho * mg + (1 - rho) * g
        denom = jnp.sqrt(ms_new - jnp.square(mg_new) + eps)
        put(env, op.output("MeanGradOut"), mg_new)
    else:
        denom = jnp.sqrt(ms_new + eps)
    mom_new = momentum * mom + lr * g / denom
    put(env, op.output("ParamOut"), p - mom_new)
    put(env, op.output("MeanSquareOut"), ms_new)
    put(env, op.output("MomentOut"), mom_new)


@register("ftrl")
def _ftrl(env, op):
    p = get(env, op.input("Param"))
    g, _rows = _sparse_grad(env, op)
    if _rows is not None:
        g = _densify(g, _rows, p.shape, op)
    sq = get(env, op.input("SquaredAccumulator"))
    lin = get(env, op.input("LinearAccumulator"))
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    lr_power = op.attr("lr_power", -0.5)
    lr = _lr(env, op)
    new_sq = sq + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre_shrink = (l1 * jnp.sign(new_lin) - new_lin) / denom
    p_new = jnp.where(jnp.abs(new_lin) > l1, pre_shrink, jnp.zeros_like(p))
    put(env, op.output("ParamOut"), p_new)
    put(env, op.output("SquaredAccumOut"), new_sq)
    put(env, op.output("LinearAccumOut"), new_lin)


@register("lamb")
def _lamb(env, op):
    """LAMB optimizer — beyond the reference's 2019 set; standard for BERT
    pretraining at scale on TPU pods."""
    p = get(env, op.input("Param"))
    g, _rows = _sparse_grad(env, op)
    if _rows is not None:
        g = _densify(g, _rows, p.shape, op)
    m = get(env, op.input("Moment1"))
    v = get(env, op.input("Moment2"))
    b1p = get(env, op.input("Beta1Pow")).reshape(())
    b2p = get(env, op.input("Beta2Pow")).reshape(())
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-6)
    wd = op.attr("weight_decay", 0.01)
    lr = _lr(env, op)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * jnp.square(g)
    m_hat = m_new / (1 - b1p)
    v_hat = v_new / (1 - b2p)
    r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    put(env, op.output("ParamOut"), p - lr * trust * r)
    put(env, op.output("Moment1Out"), m_new)
    put(env, op.output("Moment2Out"), v_new)
    put(env, op.output("Beta1PowOut"), (b1p * b1).reshape((1,)))
    put(env, op.output("Beta2PowOut"), (b2p * b2).reshape((1,)))

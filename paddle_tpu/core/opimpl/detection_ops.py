"""Detection ops (subset; ref ``paddle/fluid/operators/detection/``).

Static-shape friendly members implemented for round 1: prior_box,
box_coder, iou_similarity, roi_pool/align on fixed ROI counts. NMS-style
dynamic-output ops are provided with fixed-size outputs + validity masks
(XLA cannot produce data-dependent shapes).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..op_registry import register, get, put


@register("iou_similarity")
def _iou_similarity(env, op):
    x = get(env, op.input("X"))  # [N, 4] xmin ymin xmax ymax
    y = get(env, op.input("Y"))  # [M, 4]
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_x[:, None] + area_y[None, :] - inter
    put(env, op.output("Out"), inter / jnp.maximum(union, 1e-10))


@register("box_coder")
def _box_coder(env, op):
    prior = get(env, op.input("PriorBox"))  # [M, 4]
    pvar = get(env, op.input("PriorBoxVar"))
    target = get(env, op.input("TargetBox"))
    code_type = op.attr("code_type", "encode_center_size")
    norm = op.attr("box_normalized", True)
    one = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if pvar is None:
        pvar = jnp.ones((4,), prior.dtype)
    if pvar.ndim == 2:
        v0, v1, v2, v3 = pvar[:, 0], pvar[:, 1], pvar[:, 2], pvar[:, 3]
    else:
        v0, v1, v2, v3 = pvar[0], pvar[1], pvar[2], pvar[3]
    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + one
        th = target[:, 3] - target[:, 1] + one
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :] / v0
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / v1
        ow = jnp.log(tw[:, None] / pw[None, :]) / v2
        oh = jnp.log(th[:, None] / ph[None, :]) / v3
        put(env, op.output("OutputBox"), jnp.stack([ox, oy, ow, oh], axis=-1))
    else:  # decode_center_size; target [N, M, 4]
        ox = v0 * target[..., 0] * pw + pcx
        oy = v1 * target[..., 1] * ph + pcy
        ow = jnp.exp(v2 * target[..., 2]) * pw
        oh = jnp.exp(v3 * target[..., 3]) * ph
        out = jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                         ox + ow * 0.5 - one, oy + oh * 0.5 - one], axis=-1)
        put(env, op.output("OutputBox"), out)


@register("prior_box")
def _prior_box(env, op):
    feat = get(env, op.input("Input"))  # NCHW feature map
    img = get(env, op.input("Image"))
    min_sizes = op.attr("min_sizes")
    max_sizes = op.attr("max_sizes", [])
    ratios = op.attr("aspect_ratios", [1.0])
    flip = op.attr("flip", False)
    clip = op.attr("clip", False)
    step_w = op.attr("step_w", 0.0)
    step_h = op.attr("step_h", 0.0)
    offset = op.attr("offset", 0.5)
    variances = op.attr("variances", [0.1, 0.1, 0.2, 0.2])
    h, w = feat.shape[2], feat.shape[3]
    img_h, img_w = img.shape[2], img.shape[3]
    sw = step_w or img_w / w
    sh = step_h or img_h / h
    ars = [1.0]
    for r in ratios:
        if all(abs(r - a) > 1e-6 for a in ars):
            ars.append(r)
            if flip:
                ars.append(1.0 / r)
    boxes = []
    for ms in min_sizes:
        for ar in ars:
            bw = ms * np.sqrt(ar) * 0.5
            bh = ms / np.sqrt(ar) * 0.5
            boxes.append((bw, bh))
        if max_sizes:
            for mxs in max_sizes:
                s = np.sqrt(ms * mxs) * 0.5
                boxes.append((s, s))
    cx = (jnp.arange(w) + offset) * sw
    cy = (jnp.arange(h) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    all_boxes = []
    for bw, bh in boxes:
        b = jnp.stack([(cxg - bw) / img_w, (cyg - bh) / img_h,
                       (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1)
        all_boxes.append(b)
    out = jnp.stack(all_boxes, axis=2)  # [H, W, num_priors, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, out.dtype), out.shape)
    put(env, op.output("Boxes"), out)
    put(env, op.output("Variances"), var)


@register("roi_align")
def _roi_align(env, op):
    x = get(env, op.input("X"))  # [N, C, H, W]
    rois = get(env, op.input("ROIs"))  # [R, 4] in image coords; batch 0 only
    pooled_h = op.attr("pooled_height", 1)
    pooled_w = op.attr("pooled_width", 1)
    scale = op.attr("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def one_roi(roi):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        ys = y1 + (jnp.arange(pooled_h) + 0.5) * rh / pooled_h
        xs = x1 + (jnp.arange(pooled_w) + 0.5) * rw / pooled_w
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None]
        wx = (xs - x0)[None, :]
        img = x[0]
        g = lambda yy, xx: img[:, yy][:, :, xx]
        return (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1i, x0) * wy * (1 - wx)
                + g(y0, x1i) * (1 - wy) * wx + g(y1i, x1i) * wy * wx)

    put(env, op.output("Out"), jax.vmap(one_roi)(rois))


@register("roi_pool")
def _roi_pool(env, op):
    x = get(env, op.input("X"))
    rois = get(env, op.input("ROIs"))
    pooled_h = op.attr("pooled_height", 1)
    pooled_w = op.attr("pooled_width", 1)
    scale = op.attr("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def one_roi(roi):
        x1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = x[0]
        ys = jnp.arange(h)
        xs = jnp.arange(w)
        outs = []
        for ph in range(pooled_h):
            for pw in range(pooled_w):
                ys_lo = y1 + (ph * rh) // pooled_h
                ys_hi = y1 + ((ph + 1) * rh + pooled_h - 1) // pooled_h
                xs_lo = x1 + (pw * rw) // pooled_w
                xs_hi = x1 + ((pw + 1) * rw + pooled_w - 1) // pooled_w
                m = ((ys >= ys_lo) & (ys < jnp.maximum(ys_hi, ys_lo + 1)))[None, :, None] & \
                    ((xs >= xs_lo) & (xs < jnp.maximum(xs_hi, xs_lo + 1)))[None, None, :]
                outs.append(jnp.max(jnp.where(m, img, -jnp.inf), axis=(1, 2)))
        return jnp.stack(outs, axis=-1).reshape(c, pooled_h, pooled_w)

    put(env, op.output("Out"), jax.vmap(one_roi)(rois))


@register("anchor_generator")
def _anchor_generator(env, op):
    feat = get(env, op.input("Input"))
    sizes = op.attr("anchor_sizes")
    ratios = op.attr("aspect_ratios")
    stride = op.attr("stride")
    offset = op.attr("offset", 0.5)
    variances = op.attr("variances", [0.1, 0.1, 0.2, 0.2])
    h, w = feat.shape[2], feat.shape[3]
    cx = (jnp.arange(w) + offset) * stride[0]
    cy = (jnp.arange(h) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    anchors = []
    for r in ratios:
        for s in sizes:
            aw = s * np.sqrt(1.0 / r) * 0.5
            ah = s * np.sqrt(r) * 0.5
            anchors.append(jnp.stack(
                [cxg - aw, cyg - ah, cxg + aw, cyg + ah], axis=-1))
    out = jnp.stack(anchors, axis=2)
    var = jnp.broadcast_to(jnp.asarray(variances, out.dtype), out.shape)
    put(env, op.output("Anchors"), out)
    put(env, op.output("Variances"), var)

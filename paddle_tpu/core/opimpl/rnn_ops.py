"""Recurrent ops: LSTM / GRU over padded batches with length masks.

The reference handles variable-length sequences with LoD-packed batches and
specialized kernels (``math/lstm_compute``, ``gru_op.cc``,
``recurrent_op.cc``). On TPU the idiomatic form is static-shape padded
[batch, time, ...] tensors + a length mask, scanned with ``lax.scan`` so XLA
compiles ONE fused step function — the gate matmuls hit the MXU per step.
"""

import jax
import jax.numpy as jnp

from ..op_registry import register, get, put


def _mask_from_lengths(lengths, t_steps, dtype):
    # [B] -> [T, B, 1] validity mask
    t = jnp.arange(t_steps)[:, None]
    return (t < lengths[None, :]).astype(dtype)[..., None]


@register("lstm_seq")
def _lstm_seq(env, op):
    """Single-layer LSTM over [B, T, D] input.

    Inputs: Input [B,T,4H] (pre-projected gates, like ref ``lstm_op`` taking
    x@W as input), Weight [H,4H] recurrent weights, Bias [4H] (+peephole
    [7H] unsupported -> first 4H used), Lengths [B] optional.
    Gate order follows the reference: i, f, c(hat), o
    (``operators/math/detail/lstm_kernel.h``)."""
    xproj = get(env, op.input("Input"))  # [B, T, 4H]
    w = get(env, op.input("Weight"))  # [H, 4H]
    bias = get(env, op.input("Bias"))  # [1, 4H] or [4H]
    lengths = get(env, op.input("Lengths"))
    b_sz, t_sz, four_h = xproj.shape
    h_sz = four_h // 4
    is_reverse = op.attr("is_reverse", False)
    if bias is not None:
        bias = bias.reshape(-1)[: 4 * h_sz]

    xs = jnp.swapaxes(xproj, 0, 1)  # [T, B, 4H]
    if is_reverse:
        xs = jnp.flip(xs, axis=0)
    mask = None
    if lengths is not None:
        mask = _mask_from_lengths(lengths.reshape(-1), t_sz, xproj.dtype)
        if is_reverse:
            mask = jnp.flip(mask, axis=0)

    h0 = get(env, op.input("H0"))
    c0 = get(env, op.input("C0"))
    h0 = jnp.zeros((b_sz, h_sz), xproj.dtype) if h0 is None \
        else h0.astype(xproj.dtype)
    c0 = jnp.zeros((b_sz, h_sz), xproj.dtype) if c0 is None \
        else c0.astype(xproj.dtype)

    def step(carry, inp):
        h_prev, c_prev = carry
        x_t, m_t = inp
        gates = x_t + h_prev @ w
        if bias is not None:
            gates = gates + bias
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        if m_t is not None:
            h = h * m_t + h_prev * (1 - m_t)
            c = c * m_t + c_prev * (1 - m_t)
        return (h, c), (h, c)

    if mask is None:
        (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), (xs, jnp.ones((t_sz, b_sz, 1), xproj.dtype)))
    else:
        (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), (xs, mask))
    if is_reverse:
        hs = jnp.flip(hs, axis=0)
        cs = jnp.flip(cs, axis=0)
    put(env, op.output("Hidden"), jnp.swapaxes(hs, 0, 1))  # [B, T, H]
    put(env, op.output("Cell"), jnp.swapaxes(cs, 0, 1))


@register("gru_seq")
def _gru_seq(env, op):
    """Single-layer GRU over [B, T, 3H] pre-projected input (ref ``gru_op``).
    Gate order: update u, reset r, candidate c (``math/detail/gru_kernel.h``).
    """
    xproj = get(env, op.input("Input"))  # [B, T, 3H]
    w = get(env, op.input("Weight"))  # [H, 3H]: [:, :2H] gates, [:, 2H:] candidate
    bias = get(env, op.input("Bias"))
    lengths = get(env, op.input("Lengths"))
    b_sz, t_sz, three_h = xproj.shape
    h_sz = three_h // 3
    is_reverse = op.attr("is_reverse", False)
    origin_mode = op.attr("origin_mode", False)
    if bias is not None:
        bias = bias.reshape(-1)

    xs = jnp.swapaxes(xproj, 0, 1)
    if is_reverse:
        xs = jnp.flip(xs, axis=0)
    if lengths is not None:
        mask = _mask_from_lengths(lengths.reshape(-1), t_sz, xproj.dtype)
        if is_reverse:
            mask = jnp.flip(mask, axis=0)
    else:
        mask = jnp.ones((t_sz, b_sz, 1), xproj.dtype)

    w_g = w[:, : 2 * h_sz]
    w_c = w[:, 2 * h_sz:]
    h0 = jnp.zeros((b_sz, h_sz), xproj.dtype)

    def step(h_prev, inp):
        x_t, m_t = inp
        xg = x_t[:, : 2 * h_sz]
        xc = x_t[:, 2 * h_sz:]
        if bias is not None:
            xg = xg + bias[: 2 * h_sz]
            xc = xc + bias[2 * h_sz:]
        g = jax.nn.sigmoid(xg + h_prev @ w_g)
        u, r = jnp.split(g, 2, axis=-1)
        c = jnp.tanh(xc + (r * h_prev) @ w_c)
        if origin_mode:
            h = u * h_prev + (1 - u) * c
        else:
            h = (1 - u) * h_prev + u * c
        h = h * m_t + h_prev * (1 - m_t)
        return h, h

    _, hs = jax.lax.scan(step, h0, (xs, mask))
    if is_reverse:
        hs = jnp.flip(hs, axis=0)
    put(env, op.output("Hidden"), jnp.swapaxes(hs, 0, 1))


@register("gru_unit")
def _gru_unit(env, op):
    """One GRU step (ref ``operators/gru_unit_op.cc``): Input [B,3H] is the
    pre-projected x, HiddenPrev [B,H]; same gate order as gru_seq."""
    x = get(env, op.input("Input"))
    h_prev = get(env, op.input("HiddenPrev"))
    w = get(env, op.input("Weight"))
    bias = get(env, op.input("Bias"))
    h_sz = h_prev.shape[-1]
    origin_mode = op.attr("origin_mode", False)
    xg = x[:, : 2 * h_sz]
    xc = x[:, 2 * h_sz:]
    if bias is not None:
        bias = bias.reshape(-1)
        xg = xg + bias[: 2 * h_sz]
        xc = xc + bias[2 * h_sz:]
    g = jax.nn.sigmoid(xg + h_prev @ w[:, : 2 * h_sz])
    u, r = jnp.split(g, 2, axis=-1)
    c = jnp.tanh(xc + (r * h_prev) @ w[:, 2 * h_sz:])
    if origin_mode:
        h = u * h_prev + (1 - u) * c
    else:
        h = (1 - u) * h_prev + u * c
    put(env, op.output("Hidden"), h)

"""LayerHelper: shared machinery for layer functions (ref
``python/paddle/fluid/layer_helper.py``): creates parameters in BOTH the main
program (as Parameter vars) and the startup program (with their init op),
appends ops, handles bias/activation epilogues."""

from . import framework
from . import unique_name
from .framework import Parameter
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return framework.default_main_program()

    @property
    def startup_program(self):
        return framework.default_startup_program()

    # -- params -------------------------------------------------------------
    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def create_parameter(self, attr, shape, dtype="float32", is_bias=False,
                         default_initializer=None):
        if attr is False:
            return None
        attr = ParamAttr._to_attr(attr)
        if attr.name is None:
            suffix = "b" if is_bias else "w"
            attr.name = unique_name.generate("%s.%s_0" % (self.name, suffix))
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()

        main_block = self.main_program.global_block()
        # parameter sharing (ref fluid semantics: reusing a ParamAttr name
        # shares the weight): return the existing Parameter instead of
        # re-creating it — re-creation also appended a DUPLICATE init op to
        # the startup program per reuse, leaving N unordered random writes
        # to one var (caught by analysis.check_double_writes on word2vec's
        # shared embedding)
        existing = main_block.vars.get(attr.name)
        if isinstance(existing, Parameter):
            if tuple(existing.shape) != tuple(shape):
                raise ValueError(
                    "parameter %r reused with shape %s but it exists with "
                    "shape %s" % (attr.name, shape, existing.shape))
            import numpy as _np

            if existing.dtype != _np.dtype(
                    framework.convert_np_dtype(dtype)):
                raise ValueError(
                    "parameter %r reused with dtype %s but it exists with "
                    "dtype %s" % (attr.name, dtype, existing.dtype))
            return existing
        kwargs = attr._to_kwargs()
        param = main_block.create_parameter(
            shape=shape, dtype=dtype, **kwargs)
        param.initializer = init

        # same-named var + init op in the startup program
        startup_block = self.startup_program.global_block()
        sp_var = startup_block.create_var(
            name=attr.name, shape=shape, dtype=dtype, persistable=True)
        init(sp_var, startup_block)
        return param

    def create_variable_for_type_inference(self, dtype="float32", shape=None,
                                           stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            shape=shape, dtype=dtype, persistable=False,
            stop_gradient=stop_gradient)

    def create_global_variable(self, name=None, shape=None, dtype="float32",
                               persistable=True):
        return self.main_program.global_block().create_var(
            name=name or unique_name.generate(".".join([self.name, "global"])),
            shape=shape, dtype=dtype, persistable=persistable)

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        return self.main_program.current_block().append_op(
            type, inputs, outputs, attrs)

    # -- epilogues ----------------------------------------------------------
    def append_bias_op(self, input_var, dim_start=1, num_flatten_dims=None):
        bias_attr = self.bias_attr
        if bias_attr is False:
            return input_var
        size = input_var.shape[-1]
        b = self.create_parameter(bias_attr, shape=[size],
                                  dtype=str(input_var.dtype), is_bias=True)
        out = self.create_variable_for_type_inference(
            dtype=str(input_var.dtype), shape=input_var.shape)
        self.append_op("elementwise_add", {"X": input_var, "Y": b},
                       {"Out": out}, {"axis": len(input_var.shape) - 1})
        return out

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        out = self.create_variable_for_type_inference(
            dtype=str(input_var.dtype), shape=input_var.shape)
        self.append_op(act, {"X": input_var}, {"Out": out}, {})
        return out
